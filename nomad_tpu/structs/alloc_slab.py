"""Columnar allocation slabs: the alloc contract of the scheduling hot path.

The per-eval host floor (BENCH stage ``finish``) was dominated not by port
assignment but by the OBJECT contract around it: the native finish loop
built ~12 Python objects per placement (Allocation, AllocMetric, Resources
and NetworkResource per task, port lists, task dicts), the plan verifier
walked them back into dense arrays, the raft wire re-serialized every
alloc as a ~17-key dict (embedding the full job per alloc), and the store
copied each object per upsert.

``AllocSlab`` replaces that round trip with columns.  One slab carries an
eval's placements as dense arrays — ids, node ids, slot indexes, scores,
a flat int32 port column — plus the per-slot templates (size/Resources
protos, network asks) every row shares.  The native finish
(native/port_alloc.cpp ``bulk_finish_cols``) writes ports straight into
the slab's buffer and emits one tiny ``SlabAlloc`` per row: an
``Allocation`` whose heavy fields (``resources``, ``task_resources``,
``metrics``, ``task_states``) are data-descriptor properties that
materialize lazily FROM the slab on first read.  Everything downstream
consumes columns:

  - plan verify (ops/plan_conflict, server/plan_apply) reads
    ``slab.vec``/``slab.net_row`` through the slab-aware
    ``models/fleet.alloc_vec``/``_net_row`` — no ``task_resources`` walk;
  - the raft wire (``SlabWireEncoder``) encodes slab rows as
    ``[slab, row, delta]`` references against one shared column record
    (the job dict rides ONCE per slab, not once per alloc);
  - the FSM/state store upsert ``SlabAlloc`` objects whose ``copy()`` is
    one small dict copy — no task-resource materialization;
  - FSM snapshots serialize whole slab families as one columnar record
    (``fsm.py`` SNAP_ALLOC_SLAB) — byte size shrinks by the shared-job
    and shared-template factor.

Full ``Allocation`` semantics materialize only when an API / client /
snapshot-digest consumer actually reads a heavy field, and the result is
bit-identical to the object path (``tests/test_columnar_alloc.py`` and
the storm parity rig in ``tests/test_plan_batch.py`` byte-compare store
fingerprints between the two contracts).

Invalidation rule: slab columns are IMMUTABLE once sealed; any row
rewrite must go through ``patch_row``, which drops that row's cached
``SlabAlloc`` (and its derived net row) so no consumer can observe a
stale materialization.  Store-side updates never mutate rows — they
copy the object and override scalars, exactly like the object contract.
"""
from __future__ import annotations

import os
import threading
import weakref
from typing import Optional

import numpy as np

from .model import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    AllocMetric,
    Allocation,
    NetworkResource,
    Resources,
)

# Kill switch for the columnar contract (parity rigs flip it to replay
# identical storms through the legacy object path): the schedulers fall
# back to the object-emitting native finish when False.
COLUMNAR = os.environ.get("NOMAD_TPU_COLUMNAR", "1") != "0"


def columnar_enabled() -> bool:
    return COLUMNAR


_MISS = object()

# One lock for all lazy materializations (same policy as AllocMetric's
# _METRIC_LAZY_LOCK): first reads are rare and idempotent, but two
# concurrent first reads of ``task_resources`` must not each install a
# half-observed dict.
_SLAB_LAZY_LOCK = threading.Lock()

# The scalar fields a slab row canonically determines.  ``job`` is
# checked by identity separately; the four heavy fields are never
# scalars.  Defaults mirror the Allocation dataclass (class attributes
# back any key the eager dict omits).
_SCALAR_FIELDS = (
    ("id", ""), ("eval_id", ""), ("name", ""), ("node_id", ""),
    ("job_id", ""), ("task_group", ""),
    ("desired_status", ""), ("desired_description", ""),
    ("client_status", ""), ("client_description", ""),
    ("create_index", 0), ("modify_index", 0),
)


def _lazy_field(name: str):
    """Data-descriptor property for one heavy Allocation field: reads
    materialize from the slab on first access; writes record the field
    in ``_hmut`` so the wire encoder knows the row no longer speaks for
    this object (it falls back to a full dict)."""

    def _get(self):
        d = self.__dict__
        v = d.get(name, _MISS)
        if v is _MISS:
            return _slab_fill(self, name)
        return v

    def _set(self, value):
        d = self.__dict__
        d[name] = value
        mut = d.get("_hmut")
        if mut is None:
            mut = d["_hmut"] = set()
        mut.add(name)

    return property(_get, _set)


def _slab_fill(alloc, name: str):
    with _SLAB_LAZY_LOCK:
        d = alloc.__dict__
        v = d.get(name, _MISS)
        if v is not _MISS:  # lost the race: another reader built it
            return v
        slab = d["_slab"]
        r = d["_srow"]
        if name == "resources":
            v = slab.size_of(r)
        elif name == "metrics":
            v = slab.metric_of(r)
        elif name == "task_resources":
            v = slab.task_resources_of(r)
        else:  # task_states
            v = {}
        d[name] = v
        return v


class SlabAlloc(Allocation):
    """An Allocation backed by one AllocSlab row.

    Eagerly carries only the scalars the store/verify hot paths read
    (ids, statuses, the job reference) plus ``_slab``/``_srow``; the
    heavy fields materialize lazily from the slab's columns.  The
    properties are data descriptors, so reads stay correct whether or
    not the field has materialized, and writes (rare: in-place updates)
    are flagged so the columnar wire encoder stops speaking for the
    object.  Never constructed through ``__init__`` — the native finish
    loop and ``AllocSlab.alloc`` build instances via ``__new__`` plus a
    template dict, the same pattern the object path already used."""

    resources = _lazy_field("resources")
    task_resources = _lazy_field("task_resources")
    metrics = _lazy_field("metrics")
    task_states = _lazy_field("task_states")

    def copy(self) -> "SlabAlloc":
        # dataclasses.replace would read every field through the
        # properties and materialize the whole row; a slab-backed copy
        # is one dict copy instead (the store upsert's per-alloc cost).
        new = SlabAlloc.__new__(SlabAlloc)
        d = dict(self.__dict__)
        d.pop("_res_vec", None)
        d.pop("_net_row", None)
        mut = d.get("_hmut")
        if mut is not None:
            d["_hmut"] = set(mut)
        tr = d.get("task_resources")
        if tr is not None:
            d["task_resources"] = dict(tr)
        ts = d.get("task_states")
        if ts is not None:
            d["task_states"] = dict(ts)
        new.__dict__ = d
        return new


class AllocSlab:
    """Dense columns for one eval's placements (or one decoded wire/
    snapshot record).  Rows [0, n) are valid; the scheduler allocates
    for the whole placement list and ``seal``s to the native prefix."""

    __slots__ = (
        "__weakref__",
        "eval_id", "job_id", "job",
        "slots",        # slot -> (size Resources, tasks_c) — build_slots_c layout
        "metric_proto",  # shared AllocMetric template (nodes_evaluated, time)
        "ids", "names", "tgs", "node_ids", "ips", "devs",
        "groups",       # row -> slot index (list)
        "scores",       # row -> float
        "ports",        # np.int32 flat dynamic-port column
        "port_off",     # np.int64 [rows+1] prefix offsets into ports
        "n",            # sealed row count
        "_cache",       # row -> canonical SlabAlloc (lazy; see alloc())
        "_slot_vec", "_slot_mbits", "_slot_has_net",
        "_owned",       # row columns private to this slab (see patch_row)
    )

    def __init__(self, eval_id: str, job, slots: list, metric_proto: dict,
                 groups: list, ids: list, names: list, tgs: list,
                 scores: list, port_off: np.ndarray, n_rows: int,
                 ports: Optional[np.ndarray] = None,
                 slot_mbits: Optional[list] = None,
                 slot_has_net: Optional[list] = None) -> None:
        self.eval_id = eval_id
        self.job = job
        self.job_id = job.id if job is not None else ""
        self.slots = slots
        self.metric_proto = metric_proto
        self.groups = groups
        self.ids = ids
        self.names = names
        self.tgs = tgs
        self.scores = scores
        self.port_off = port_off
        self.ports = ports if ports is not None else \
            np.empty(int(port_off[-1]) if len(port_off) else 0,
                     dtype=np.int32)
        self.node_ids: list = [None] * n_rows
        self.ips: list = [None] * n_rows
        self.devs: list = [None] * n_rows
        self.n = 0
        # Canonical row objects, WEAKLY held: a cached alloc references
        # the slab back, so a strong cache would close a tracked cycle
        # and break the store's refcount-only teardown contract
        # (tests/test_gc_untrack.py).  Weak entries dedup rows within a
        # decode pass and die with their last outside holder.
        self._cache: "weakref.WeakValueDictionary" = \
            weakref.WeakValueDictionary()
        self._slot_vec: dict = {}
        # Pre-derived per-slot network totals when the caller already
        # has them (the scheduler's col_meta cache); lazily derived
        # from ``slots`` otherwise.
        self._slot_mbits = slot_mbits
        self._slot_has_net = slot_has_net
        # Scheduler-built slabs SHARE their names/tgs (col_meta) and
        # groups columns with sibling slabs of the same job version;
        # patch_row privatizes before the first mutation.
        self._owned = False

    def seal(self, n: int) -> None:
        """Mark rows [0, n) valid (the native finish's happy prefix)."""
        self.n = n

    # -- per-slot derivations ---------------------------------------------
    def _slot_net(self) -> tuple[list, list]:
        mbits = self._slot_mbits
        if mbits is None:
            mbits = []
            has = []
            for _size, tasks in self.slots:
                mb = 0
                any_net = False
                for _t, _rp, net_c in tasks:
                    if net_c is not None:
                        any_net = True
                        mb += net_c[0]
                mbits.append(mb)
                has.append(any_net)
            self._slot_mbits = mbits
            self._slot_has_net = has
        return mbits, self._slot_has_net

    # -- columnar reads (the verify hot path) ------------------------------
    def vec(self, r: int) -> np.ndarray:
        """Resource vector of row ``r`` — per-slot constant, shared
        read-only across the slot's rows (models/fleet.alloc_vec)."""
        g = self.groups[r]
        v = self._slot_vec.get(g)
        if v is None:
            size = self.slots[g][0]
            v = self._slot_vec[g] = np.asarray(
                size.as_vector() if size is not None else [0] * 6,
                dtype=np.float32)
        return v

    def net_row(self, r: int):
        """The verifier's (ports, mbits, (ip, device)) row — identical
        to models/fleet._net_row_build on the materialized object."""
        mbits, has_net = self._slot_net()
        g = self.groups[r]
        if not has_net[g] and not mbits[g]:
            return None
        o0 = int(self.port_off[r])
        o1 = int(self.port_off[r + 1])
        return (tuple(self.ports[o0:o1].tolist()), mbits[g],
                (self.ips[r], self.devs[r]))

    # -- lazy materialization ----------------------------------------------
    def size_of(self, r: int):
        """Shared per-slot total Resources (the object path shared one
        size object per slot the same way)."""
        return self.slots[self.groups[r]][0]

    def metric_of(self, r: int) -> AllocMetric:
        m = AllocMetric.__new__(AllocMetric)
        d = dict(self.metric_proto)
        d["_lazy_score_key"] = self.node_ids[r] + ".binpack"
        d["_lazy_score_val"] = float(self.scores[r])
        m.__dict__ = d
        return m

    def task_resources_of(self, r: int) -> dict:
        ip = self.ips[r]
        dev = self.devs[r]
        off = int(self.port_off[r])
        out = {}
        for tname, res_proto, net_c in self.slots[self.groups[r]][1]:
            rd = dict(res_proto)
            if net_c is None:
                rd["networks"] = []
            else:
                _mbits, net_proto, labels = net_c
                nd = dict(net_proto)
                nd["device"] = dev
                nd["ip"] = ip
                nd["reserved_ports"] = \
                    self.ports[off:off + len(labels)].tolist()
                nd["dynamic_ports"] = list(labels)
                off += len(labels)
                offer = NetworkResource.__new__(NetworkResource)
                offer.__dict__ = nd
                rd["networks"] = [offer]
            tr = Resources.__new__(Resources)
            tr.__dict__ = rd
            out[tname] = tr
        return out

    # -- row objects -------------------------------------------------------
    def row_scalars(self, r: int) -> dict:
        """Canonical scalar values row ``r`` stands for — what a fresh
        placement carries before the store stamps indexes."""
        return {
            "id": self.ids[r], "eval_id": self.eval_id,
            "name": self.names[r], "node_id": self.node_ids[r],
            "job_id": self.job_id, "task_group": self.tgs[r],
            "desired_status": ALLOC_DESIRED_STATUS_RUN,
            "desired_description": "",
            "client_status": ALLOC_CLIENT_STATUS_PENDING,
            "client_description": "",
            "create_index": 0, "modify_index": 0,
        }

    def _eager(self, r: int) -> dict:
        # Mirrors the native loop's lazy proto exactly: scalars whose
        # values differ from the Allocation class defaults, plus the
        # slab backref.  Omitted keys resolve through class attributes.
        return {
            "id": self.ids[r], "eval_id": self.eval_id,
            "name": self.names[r], "node_id": self.node_ids[r],
            "job_id": self.job_id, "job": self.job,
            "task_group": self.tgs[r],
            "desired_status": ALLOC_DESIRED_STATUS_RUN,
            "client_status": ALLOC_CLIENT_STATUS_PENDING,
            "_slab": self, "_srow": r,
        }

    def alloc(self, r: int) -> SlabAlloc:
        """The canonical Allocation for row ``r``, built lazily and
        cached (the FSM decode path asks once per row; store upserts
        copy it).  ``patch_row`` invalidates the cache entry."""
        a = self._cache.get(r)
        if a is None:
            a = SlabAlloc.__new__(SlabAlloc)
            a.__dict__ = self._eager(r)
            self._cache[r] = a
        return a

    def alloc_with(self, r: int, **overrides) -> SlabAlloc:
        """Row ``r`` with scalar/task_states overrides (wire deltas,
        snapshot-restore indexes).  Never cached — overridden rows are
        one-off views."""
        a = SlabAlloc.__new__(SlabAlloc)
        d = self._eager(r)
        d.update(overrides)
        a.__dict__ = d
        return a

    def patch_row(self, r: int, **scalars) -> None:
        """THE row-mutation seam: rewrite scalar columns for row ``r``
        and drop every cached derivation so no consumer can observe a
        stale materialization.  Columns are otherwise immutable once
        sealed.

        Copy-on-first-write: scheduler-built slabs alias their
        names/tgs columns to the per-job-version col_meta cache (shared
        with every sibling slab of the same job version), so the first
        patch privatizes every patchable column — mutating a shared
        list in place would rewrite other evals' canonical rows."""
        if not self._owned:
            self.ids = list(self.ids)
            self.names = list(self.names)
            self.tgs = list(self.tgs)
            self.node_ids = list(self.node_ids)
            self.scores = list(self.scores)
            self.ips = list(self.ips)
            self.devs = list(self.devs)
            self._owned = True
        for key, value in scalars.items():
            if key == "id":
                self.ids[r] = value
            elif key == "name":
                self.names[r] = value
            elif key == "task_group":
                self.tgs[r] = value
            elif key == "node_id":
                self.node_ids[r] = value
            elif key == "score":
                self.scores[r] = value
            elif key == "ip":
                self.ips[r] = value
            elif key == "device":
                self.devs[r] = value
            else:
                raise KeyError(f"not a per-row scalar column: {key}")
        self._cache.pop(r, None)

    # -- wire / snapshot ---------------------------------------------------
    def wire(self, rows: Optional[list] = None) -> dict:
        """msgpack-safe columnar record for ``rows`` (default: all
        sealed rows).  The job dict rides ONCE here instead of once per
        alloc — the dominant term of the old per-alloc dict encoding."""
        if rows is None:
            rows = list(range(self.n))
        poff = [0]
        chunks = []
        for r in rows:
            o0 = int(self.port_off[r])
            o1 = int(self.port_off[r + 1])
            chunks.append(self.ports[o0:o1])
            poff.append(poff[-1] + (o1 - o0))
        ports = np.concatenate(chunks) if chunks else \
            np.empty(0, dtype=np.int32)
        slots_w = []
        for size, tasks in self.slots:
            tasks_w = [[t, rp, None if net_c is None
                        else [net_c[0], net_c[1], list(net_c[2])]]
                       for t, rp, net_c in tasks]
            slots_w.append([size.to_dict() if size is not None else None,
                            tasks_w])
        return {
            "eval_id": self.eval_id,
            "job": self.job.to_dict() if self.job is not None else None,
            "ne": self.metric_proto.get("nodes_evaluated", 0),
            "at": self.metric_proto.get("allocation_time", 0.0),
            "slots": slots_w,
            "ids": [self.ids[r] for r in rows],
            "names": [self.names[r] for r in rows],
            "tgs": [self.tgs[r] for r in rows],
            "nids": [self.node_ids[r] for r in rows],
            "ips": [self.ips[r] for r in rows],
            "devs": [self.devs[r] for r in rows],
            "groups": [self.groups[r] for r in rows],
            "scores": [self.scores[r] for r in rows],
            "ports": np.ascontiguousarray(ports).tobytes(),
            "poff": poff,
        }

    @classmethod
    def from_wire(cls, d: dict) -> "AllocSlab":
        from .model import Job

        job = Job.from_dict(d["job"]) if d.get("job") is not None else None
        slots = []
        for size_d, tasks_w in d["slots"]:
            size = Resources.from_dict(size_d) if size_d is not None \
                else None
            tasks = [(t, rp, None if net_c is None
                      else (net_c[0], net_c[1], list(net_c[2])))
                     for t, rp, net_c in tasks_w]
            slots.append((size, tasks))
        from .model import proto_of as _proto_of
        metric_static, _ = _proto_of(AllocMetric)
        metric_proto = dict(metric_static, nodes_evaluated=d["ne"],
                            allocation_time=d["at"])
        n = len(d["ids"])
        port_off = np.asarray(d["poff"], dtype=np.int64)
        slab = cls(eval_id=d["eval_id"], job=job, slots=slots,
                   metric_proto=metric_proto, groups=list(d["groups"]),
                   ids=list(d["ids"]), names=list(d["names"]),
                   tgs=list(d["tgs"]), scores=list(d["scores"]),
                   port_off=port_off, n_rows=n,
                   ports=np.frombuffer(d["ports"], dtype=np.int32).copy())
        slab.node_ids = list(d["nids"])
        slab.ips = list(d["ips"])
        slab.devs = list(d["devs"])
        slab.seal(n)
        return slab


# ---------------------------------------------------------------------------
# Wire encoding: alloc lists as slab references
# ---------------------------------------------------------------------------

def slab_ref(a):
    """``(slab, row, delta)`` when ``a`` can ride a columnar reference,
    else None (heavy field assigned, job swapped, or not slab-backed).
    ``delta`` holds only the scalars that differ from the row's
    canonical values (evictions carry desired_status/description;
    store-resident rows carry their stamped indexes)."""
    d = a.__dict__
    slab = d.get("_slab")
    if slab is None or "_hmut" in d:
        return None
    if d.get("job") is not slab.job:
        return None
    r = d["_srow"]
    canon = slab.row_scalars(r)
    delta = {}
    for f, default in _SCALAR_FIELDS:
        v = d.get(f, default)
        if v != canon[f]:
            delta[f] = v
    ts = d.get("task_states")
    if ts:
        delta["task_states"] = ts
    return slab, r, delta


class SlabWireEncoder:
    """Accumulates alloc lists into wire entries plus a shared slab
    table.  An entry is either a plain to_dict() payload or a
    ``[slab_index, row, delta?]`` reference; ``slabs_wire()`` emits the
    referenced slabs with rows compacted to exactly those used."""

    def __init__(self) -> None:
        self._slabs: dict = {}  # id(slab) -> [index, slab, {row: pos}]

    def encode_list(self, allocs: list) -> list:
        entries = []
        for a in allocs:
            ref = slab_ref(a) if type(a) is SlabAlloc else None
            if ref is None:
                entries.append(a.to_dict())
                continue
            slab, r, delta = ref
            ent = self._slabs.get(id(slab))
            if ent is None:
                ent = self._slabs[id(slab)] = [len(self._slabs), slab, {}]
            rows = ent[2]
            pos = rows.get(r)
            if pos is None:
                pos = rows[r] = len(rows)
            entries.append([ent[0], pos, delta] if delta
                           else [ent[0], pos])
        return entries

    def slabs_wire(self) -> list:
        out: list = [None] * len(self._slabs)
        for index, slab, rows in self._slabs.values():
            ordered = sorted(rows, key=rows.get)
            out[index] = slab.wire(ordered)
        return out


def encode_alloc_update(allocs: list) -> dict:
    """ALLOC_UPDATE_REQUEST payload with columnar references."""
    enc = SlabWireEncoder()
    payload = {"alloc": enc.encode_list(allocs)}
    slabs = enc.slabs_wire()
    if slabs:
        payload["slabs"] = slabs
    return payload


def encode_plan_batch(alloc_lists: list) -> dict:
    """PLAN_BATCH_APPLY_REQUEST payload: sub-plans share one slab
    table (an eval's update+placement rows ride the same slab)."""
    enc = SlabWireEncoder()
    payload = {"plans": [{"alloc": enc.encode_list(allocs)}
                         for allocs in alloc_lists]}
    slabs = enc.slabs_wire()
    if slabs:
        payload["slabs"] = slabs
    return payload


def decode_slabs(payload: dict) -> list:
    return [AllocSlab.from_wire(w) for w in payload.get("slabs", ())]


def decode_alloc_list(entries: list, slabs: list) -> list:
    """Rebuild an alloc list from wire entries, order preserved (the
    store's last-writer-wins within a batch depends on it)."""
    out = []
    for e in entries:
        if isinstance(e, dict):
            out.append(Allocation.from_dict(e))
            continue
        slab = slabs[e[0]]
        if len(e) > 2 and e[2]:
            out.append(slab.alloc_with(e[1], **e[2]))
        else:
            out.append(slab.alloc(e[1]))
    return out
