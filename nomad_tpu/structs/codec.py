"""Type-prefixed msgpack encoding for the replicated log and RPC plane.

Capability parity with /root/reference/nomad/structs/structs.go:21-43 and
:1530-1543 — a one-byte MessageType prefix followed by msgpack payload, with
an ignore-unknown-type flag bit for forward compatibility.
"""
from __future__ import annotations

import msgpack

# MessageTypes (reference: structs.go:21-43)
NODE_REGISTER_REQUEST = 0
NODE_DEREGISTER_REQUEST = 1
NODE_UPDATE_STATUS_REQUEST = 2
NODE_UPDATE_DRAIN_REQUEST = 3
JOB_REGISTER_REQUEST = 4
JOB_DEREGISTER_REQUEST = 5
EVAL_UPDATE_REQUEST = 6
EVAL_DELETE_REQUEST = 7
ALLOC_UPDATE_REQUEST = 8
ALLOC_CLIENT_UPDATE_REQUEST = 9
# Group-commit extension (no reference analogue): one log entry carrying
# the accepted alloc sets of a whole plan window, applied in eval order
# by one batched FSM pass (server/plan_apply.py group commit).
PLAN_BATCH_APPLY_REQUEST = 10

# Upper bit: apply must not error on unknown type (structs.go:40-43)
IGNORE_UNKNOWN_TYPE_FLAG = 128


def encode(msg_type: int, payload: dict) -> bytes:
    """Encode a raft log entry: 1-byte type + msgpack body."""
    return bytes([msg_type]) + msgpack.packb(payload, use_bin_type=True)


def decode(buf: bytes) -> tuple[int, dict, bool]:
    """Decode a raft log entry into (msg_type, payload, ignore_unknown).

    The ignore flag is masked off the type byte so dispatch can compare
    against the bare message-type constants; callers that hit an unknown
    type must only error when ignore_unknown is False.
    """
    if not buf:
        raise ValueError("empty log entry")
    raw = buf[0]
    ignorable = bool(raw & IGNORE_UNKNOWN_TYPE_FLAG)
    msg_type = raw & ~IGNORE_UNKNOWN_TYPE_FLAG
    payload = msgpack.unpackb(buf[1:], raw=False, strict_map_key=False)
    return msg_type, payload, ignorable
