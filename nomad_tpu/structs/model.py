"""Core data model for the tpu-nomad framework.

Declarative job model (Job -> TaskGroup -> Task), cluster objects (Node,
Allocation, Evaluation, Plan) and the request/response envelopes used by the
RPC layer.  Capability parity with the reference data model
(/root/reference/nomad/structs/structs.go), re-designed as Python dataclasses
with explicit copy semantics: every object handed out by the state store is
treated as immutable; mutations go through ``.copy()`` + field assignment.

The model also carries the *tensorization contract*: `Resources.as_vector()`
defines the canonical resource-dimension ordering used by the device-resident
fleet tensors (see nomad_tpu/models/fleet.py).
"""
from __future__ import annotations

import threading
import time
import os as _os
import uuid as _uuid
from dataclasses import MISSING, dataclass, field, fields, replace
from typing import Any, Optional

# ---------------------------------------------------------------------------
# Constants (reference: nomad/structs/structs.go:696-727, 1065-1128, 1267-1290)
# ---------------------------------------------------------------------------

JOB_TYPE_CORE = "_core"
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_COMPLETE = "complete"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100
CORE_JOB_PRIORITY = JOB_MAX_PRIORITY * 2

NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

ALLOC_DESIRED_STATUS_RUN = "run"
ALLOC_DESIRED_STATUS_STOP = "stop"
ALLOC_DESIRED_STATUS_EVICT = "evict"
ALLOC_DESIRED_STATUS_FAILED = "failed"

ALLOC_CLIENT_STATUS_PENDING = "pending"
ALLOC_CLIENT_STATUS_RUNNING = "running"
ALLOC_CLIENT_STATUS_DEAD = "dead"
ALLOC_CLIENT_STATUS_FAILED = "failed"

EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"

EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"

# Core-scheduler job ids (reference: nomad/core_sched.go)
CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"
# Operator-requested GC: both collectors, age thresholds bypassed.
CORE_JOB_FORCE_GC = "force-gc"

# Dynamic port range (reference: nomad/structs/network.go:9-18)
MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 60000
MAX_RAND_PORT_ATTEMPTS = 20

# Constraint operands (reference: scheduler/feasible.go:259-376; distinct_hosts
# is a forward-ported operand used by the bench configs).
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"


def generate_uuid() -> str:
    """Random UUID-format string (reference: nomad/structs/funcs.go:127-139).
    os.urandom + hex slicing: ~5x cheaper than uuid.uuid4() and the
    scheduler mints one per placement (hot at 10k placements/eval)."""
    h = _os.urandom(16).hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


_FORMAT_UUIDS_CACHE: list = []


def generate_uuids(n: int) -> list:
    """n random UUID strings from ONE urandom read (bulk minting for the
    scheduler finish path).  Formatting runs in C when the native
    extension is built (native/port_alloc.cpp format_uuids — same
    entropy source, same output)."""
    if not _FORMAT_UUIDS_CACHE:
        from nomad_tpu.utils.native import HAS_NATIVE, native
        _FORMAT_UUIDS_CACHE.append(
            native.format_uuids if HAS_NATIVE and
            hasattr(native, "format_uuids") else None)
    fmt = _FORMAT_UUIDS_CACHE[0]
    if fmt is not None:
        return fmt(_os.urandom(16 * n))
    h = _os.urandom(16 * n).hex()
    out = []
    for i in range(0, 32 * n, 32):
        s = h[i:i + 32]
        out.append(f"{s[:8]}-{s[8:12]}-{s[12:16]}-{s[16:20]}-{s[20:]}")
    return out


def msec_now() -> int:
    return int(time.time() * 1000)


def proto_of(cls) -> tuple[dict, list]:
    """Split a dataclass into (static-default dict, default_factory list)
    for template-based construction: hot paths build thousands of
    identical-shaped objects per eval, and ``cls.__new__`` + one dict
    copy is ~3x cheaper than the generated ``__init__`` while staying in
    sync with the dataclass definition automatically."""
    static, factories = {}, []
    for f in fields(cls):
        if f.default_factory is not MISSING:  # type: ignore[misc]
            factories.append((f.name, f.default_factory))
        else:
            static[f.name] = None if f.default is MISSING else f.default
    return static, factories


# ---------------------------------------------------------------------------
# Serialization helpers: every struct supports to_dict()/from_dict() so the
# raft log, RPC plane and HTTP API share one msgpack/JSON-safe representation.
# ---------------------------------------------------------------------------

class _Struct:
    """Mixin providing shallow-copy + dict round trip for dataclasses."""

    def copy(self):
        return replace(self)  # shallow, like Go's *new = *old

    def to_dict(self) -> dict:
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            out[f.name] = _to_plain(v)
        return out

    @classmethod
    def from_dict(cls, d: dict):
        kwargs = {}
        hints = {f.name: f for f in fields(cls)}
        for name, f in hints.items():
            if name not in d:
                continue
            kwargs[name] = _from_plain(cls._field_types().get(name), d[name])
        return cls(**kwargs)

    @classmethod
    def _field_types(cls) -> dict:
        return getattr(cls, "_NESTED", {})


def _to_plain(v):
    if isinstance(v, _Struct):
        return v.to_dict()
    if isinstance(v, list):
        return [_to_plain(x) for x in v]
    if isinstance(v, dict):
        return {k: _to_plain(x) for k, x in v.items()}
    return v


def _from_plain(spec, v):
    if v is None or spec is None:
        return v
    if isinstance(spec, tuple):
        kind, inner = spec
        if kind == "list":
            return [_from_plain(inner, x) for x in v]
        if kind == "dict":
            return {k: _from_plain(inner, x) for k, x in v.items()}
    if isinstance(spec, type) and issubclass(spec, _Struct):
        return spec.from_dict(v)
    return v


# ---------------------------------------------------------------------------
# Resources / networks (reference: nomad/structs/structs.go:538-694)
# ---------------------------------------------------------------------------

# Canonical resource dimension order for fleet tensors.  Bandwidth (mbits) and
# port-count capacity are modeled as extra dims so the device-side fit mask is
# a sound over-approximation of the exact host-side network accounting
# (SURVEY.md section 7 "Network/port allocation").
RESOURCE_DIMS = ("cpu", "memory_mb", "disk_mb", "iops")
NET_DIMS = ("mbits", "port_slots")
ALL_FIT_DIMS = RESOURCE_DIMS + NET_DIMS


@dataclass
class NetworkResource(_Struct):
    """Available or requested network bandwidth + ports on one device."""

    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: list = field(default_factory=list)
    dynamic_ports: list = field(default_factory=list)  # labels

    def copy(self) -> "NetworkResource":
        return NetworkResource(
            device=self.device, cidr=self.cidr, ip=self.ip,
            mbits=self.mbits,
            reserved_ports=list(self.reserved_ports),
            dynamic_ports=list(self.dynamic_ports))

    def add(self, delta: "NetworkResource") -> None:
        if delta.reserved_ports:
            self.reserved_ports = self.reserved_ports + list(delta.reserved_ports)
        self.mbits += delta.mbits
        self.dynamic_ports = self.dynamic_ports + list(delta.dynamic_ports)

    def map_dynamic_ports(self) -> dict:
        """Label -> assigned port for dynamic ports (appended to reserved)."""
        nd = len(self.dynamic_ports)
        ports = self.reserved_ports[len(self.reserved_ports) - nd:] if nd else []
        return dict(zip(self.dynamic_ports, ports))

    def list_static_ports(self) -> list:
        nd = len(self.dynamic_ports)
        return self.reserved_ports[: len(self.reserved_ports) - nd]


@dataclass
class Resources(_Struct):
    """CPU (MHz), memory, disk, IOPS and network asks/capacity."""

    _NESTED = {"networks": ("list", NetworkResource)}

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    iops: int = 0
    networks: list = field(default_factory=list)

    def copy(self) -> "Resources":
        return Resources(
            cpu=self.cpu, memory_mb=self.memory_mb, disk_mb=self.disk_mb,
            iops=self.iops, networks=[n.copy() for n in self.networks])

    def net_index(self, n: NetworkResource) -> int:
        for i, net in enumerate(self.networks):
            if net.device == n.device:
                return i
        return -1

    def superset(self, other: "Resources") -> tuple[bool, str]:
        """Is self a superset of other?  Ignores networks (use NetworkIndex)."""
        if self.cpu < other.cpu:
            return False, "cpu exhausted"
        if self.memory_mb < other.memory_mb:
            return False, "memory exhausted"
        if self.disk_mb < other.disk_mb:
            return False, "disk exhausted"
        if self.iops < other.iops:
            return False, "iops exhausted"
        return True, ""

    def add(self, delta: Optional["Resources"]) -> None:
        if delta is None:
            return
        self.cpu += delta.cpu
        self.memory_mb += delta.memory_mb
        self.disk_mb += delta.disk_mb
        self.iops += delta.iops
        for n in delta.networks:
            idx = self.net_index(n)
            if idx == -1:
                self.networks.append(n.copy())
            else:
                self.networks[idx].add(n)

    def as_vector(self) -> list:
        """Resource ask as [cpu, mem, disk, iops, mbits, port_slots]."""
        mbits = sum(n.mbits for n in self.networks)
        ports = sum(len(n.reserved_ports) + len(n.dynamic_ports)
                    for n in self.networks)
        return [self.cpu, self.memory_mb, self.disk_mb, self.iops, mbits, ports]


# ---------------------------------------------------------------------------
# Job / TaskGroup / Task / Constraint
# (reference: nomad/structs/structs.go:729-1063)
# ---------------------------------------------------------------------------

@dataclass
class Constraint(_Struct):
    """A scheduling constraint: ``l_target operand r_target``.

    Targets support interpolation: ``$node.id|datacenter|name``,
    ``$attr.<key>``, ``$meta.<key>``; operands: = == is != not < <= > >=
    version regexp distinct_hosts (reference: scheduler/feasible.go:225-376).
    """

    hard: bool = True
    l_target: str = ""
    r_target: str = ""
    operand: str = "="
    weight: int = 0

    def validate(self) -> list:
        errs = []
        if not self.operand:
            errs.append("missing constraint operand")
        if not self.hard and self.weight == 0:
            errs.append("soft constraint needs a weight")
        # Operand-specific checks (reference structs.go Constraint.Validate).
        if self.operand == "regexp":
            import re as _re
            try:
                _re.compile(self.r_target)
            except _re.error as e:
                errs.append(
                    f"regular expression failed to compile: {e}")
        elif self.operand == "version":
            from nomad_tpu.utils.versions import parse_constraint
            if parse_constraint(self.r_target) is None:
                errs.append(
                    f"version constraint is invalid: {self.r_target!r}")
        return errs


@dataclass
class Task(_Struct):
    _NESTED = {"resources": Resources, "constraints": ("list", Constraint)}

    name: str = ""
    driver: str = ""
    config: dict = field(default_factory=dict)
    env: dict = field(default_factory=dict)
    constraints: list = field(default_factory=list)
    resources: Resources = field(default_factory=Resources)
    meta: dict = field(default_factory=dict)

    def copy(self) -> "Task":
        t = replace(self)
        t.config = dict(self.config)
        t.env = dict(self.env)
        t.constraints = [c.copy() for c in self.constraints]
        t.resources = self.resources.copy()
        t.meta = dict(self.meta)
        return t

    def validate(self) -> list:
        errs = []
        if not self.name:
            errs.append("missing task name")
        if not self.driver:
            errs.append(f"task {self.name!r} missing driver")
        if self.resources is None:
            errs.append(f"task {self.name!r} missing resources")
        for c in self.constraints:
            errs.extend(c.validate())
        return errs


@dataclass
class TaskGroup(_Struct):
    _NESTED = {"constraints": ("list", Constraint), "tasks": ("list", Task)}

    name: str = ""
    count: int = 1
    constraints: list = field(default_factory=list)
    tasks: list = field(default_factory=list)
    meta: dict = field(default_factory=dict)

    def copy(self) -> "TaskGroup":
        tg = replace(self)
        tg.constraints = [c.copy() for c in self.constraints]
        tg.tasks = [t.copy() for t in self.tasks]
        tg.meta = dict(self.meta)
        return tg

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None

    def validate(self) -> list:
        errs = []
        if not self.name:
            errs.append("missing task group name")
        if self.count <= 0:
            errs.append(f"task group {self.name!r} count must be positive")
        if not self.tasks:
            errs.append(f"task group {self.name!r} has no tasks")
        seen = set()
        for t in self.tasks:
            if t.name in seen:
                errs.append(f"task group {self.name!r} has duplicate task {t.name!r}")
            seen.add(t.name)
            errs.extend(t.validate())
        for c in self.constraints:
            errs.extend(c.validate())
        return errs


@dataclass
class UpdateStrategy(_Struct):
    """Rolling update config (reference: structs.go:888-899)."""

    stagger: float = 0.0  # seconds
    max_parallel: int = 0

    def rolling(self) -> bool:
        return self.stagger > 0 and self.max_parallel > 0


@dataclass
class Job(_Struct):
    _NESTED = {
        "constraints": ("list", Constraint),
        "task_groups": ("list", TaskGroup),
        "update": UpdateStrategy,
    }

    id: str = ""
    name: str = ""
    region: str = "global"
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: list = field(default_factory=list)
    constraints: list = field(default_factory=list)
    task_groups: list = field(default_factory=list)
    update: UpdateStrategy = field(default_factory=UpdateStrategy)
    meta: dict = field(default_factory=dict)
    status: str = JOB_STATUS_PENDING
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Job":
        j = replace(self)
        j.datacenters = list(self.datacenters)
        j.constraints = [c.copy() for c in self.constraints]
        j.task_groups = [tg.copy() for tg in self.task_groups]
        j.update = self.update.copy()
        j.meta = dict(self.meta)
        return j

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def validate(self) -> list:
        errs = []
        if not self.region:
            errs.append("missing job region")
        if not self.id:
            errs.append("missing job id")
        elif " " in self.id:
            errs.append("job id contains a space")
        if not self.name:
            errs.append("missing job name")
        if self.type not in (JOB_TYPE_CORE, JOB_TYPE_SERVICE, JOB_TYPE_BATCH,
                             JOB_TYPE_SYSTEM):
            errs.append(f"invalid job type {self.type!r}")
        if not (JOB_MIN_PRIORITY <= self.priority <= JOB_MAX_PRIORITY
                or self.priority == CORE_JOB_PRIORITY):
            errs.append(
                f"job priority must be between [{JOB_MIN_PRIORITY}, "
                f"{JOB_MAX_PRIORITY}]")
        if not self.datacenters:
            errs.append("missing job datacenters")
        if not self.task_groups:
            errs.append("missing job task groups")
        seen = set()
        for tg in self.task_groups:
            if tg.name in seen:
                errs.append(f"duplicate task group {tg.name!r}")
            seen.add(tg.name)
            if self.type == JOB_TYPE_SYSTEM and tg.count != 1:
                errs.append(
                    f"system job task group {tg.name!r} should have "
                    "a count of 1")
            errs.extend(tg.validate())
        for c in self.constraints:
            errs.extend(c.validate())
        return errs


# ---------------------------------------------------------------------------
# Node (reference: nomad/structs/structs.go:438-534)
# ---------------------------------------------------------------------------

@dataclass
class Node(_Struct):
    _NESTED = {"resources": Resources, "reserved": Resources}

    id: str = ""
    datacenter: str = "dc1"
    name: str = ""
    attributes: dict = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    reserved: Optional[Resources] = None
    links: dict = field(default_factory=dict)
    meta: dict = field(default_factory=dict)
    node_class: str = ""
    drain: bool = False
    status: str = NODE_STATUS_INIT
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Node":
        n = replace(self)
        n.attributes = dict(self.attributes)
        n.resources = self.resources.copy()
        n.reserved = self.reserved.copy() if self.reserved else None
        n.links = dict(self.links)
        n.meta = dict(self.meta)
        return n

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN


def should_drain_node(status: str) -> bool:
    """Whether allocs on a node with this status must be migrated."""
    return status == NODE_STATUS_DOWN


def valid_node_status(status: str) -> bool:
    return status in (NODE_STATUS_INIT, NODE_STATUS_READY, NODE_STATUS_DOWN)


# ---------------------------------------------------------------------------
# Allocation + metrics (reference: structs.go:1065-1259)
# ---------------------------------------------------------------------------

_METRIC_LAZY_DICTS = frozenset((
    "class_filtered", "constraint_filtered", "class_exhausted",
    "dimension_exhausted", "scores"))
# One lock for all lazy materializations: they are rare (first read of
# a field the fast constructors skipped) and idempotent, but without
# the lock two concurrent first reads of ``scores`` could race the
# _lazy_score_key pop and one would see an empty dict.
_METRIC_LAZY_LOCK = threading.Lock()


@dataclass
class AllocMetric(_Struct):
    """Scheduling explainability data recorded on every placement attempt.

    Lazily materialized: the bulk construction paths (the native finish
    loop in native/port_alloc.cpp and the schedulers' fast_metric
    templates) skip the five per-placement factory dicts and stash the
    one binpack score as two scalars (``_lazy_score_key``/``_lazy_
    score_val``); ``__getattr__`` materializes the dicts on first read,
    so the object/wire contract (reference
    nomad/structs/structs.go:1178-1259 — to_dict, CLI explainability,
    codec) is unchanged while the placement hot loop allocates ~6 fewer
    objects per alloc."""

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    class_filtered: dict = field(default_factory=dict)
    constraint_filtered: dict = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: dict = field(default_factory=dict)
    dimension_exhausted: dict = field(default_factory=dict)
    scores: dict = field(default_factory=dict)
    allocation_time: float = 0.0  # seconds
    coalesced_failures: int = 0

    def __getattr__(self, name: str):
        if name in _METRIC_LAZY_DICTS:
            with _METRIC_LAZY_LOCK:
                d = self.__dict__
                if name in d:  # lost the race: another reader built it
                    return d[name]
                if name == "scores":
                    key = d.pop("_lazy_score_key", None)
                    s = {} if key is None \
                        else {key: d.pop("_lazy_score_val")}
                    d["scores"] = s
                    return s
                val = d[name] = {}
                return val
        raise AttributeError(name)

    def copy(self) -> "AllocMetric":
        m = replace(self)
        m.class_filtered = dict(self.class_filtered)
        m.constraint_filtered = dict(self.constraint_filtered)
        m.class_exhausted = dict(self.class_exhausted)
        m.dimension_exhausted = dict(self.dimension_exhausted)
        m.scores = dict(self.scores)
        return m

    def evaluate_node(self) -> None:
        self.nodes_evaluated += 1

    def filter_node(self, node: Optional[Node], constraint: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = \
                self.class_filtered.get(node.node_class, 0) + 1
        if constraint:
            self.constraint_filtered[constraint] = \
                self.constraint_filtered.get(constraint, 0) + 1

    def exhausted_node(self, node: Optional[Node], dimension: str) -> None:
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = \
                self.class_exhausted.get(node.node_class, 0) + 1
        if dimension:
            self.dimension_exhausted[dimension] = \
                self.dimension_exhausted.get(dimension, 0) + 1

    def score_node(self, node: Node, name: str, score: float) -> None:
        key = f"{node.id}.{name}"
        self.scores[key] = self.scores.get(key, 0.0) + score


@dataclass
class Allocation(_Struct):
    _NESTED = {
        "job": Job,
        "resources": Resources,
        "task_resources": ("dict", Resources),
        "metrics": AllocMetric,
    }

    id: str = ""
    eval_id: str = ""
    name: str = ""
    node_id: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    resources: Optional[Resources] = None
    task_resources: dict = field(default_factory=dict)
    metrics: Optional[AllocMetric] = None
    desired_status: str = ""
    desired_description: str = ""
    client_status: str = ""
    client_description: str = ""
    task_states: dict = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Allocation":
        a = replace(self)
        a.task_resources = dict(self.task_resources)
        a.task_states = dict(self.task_states)
        return a

    def terminal_status(self) -> bool:
        """Terminal by *desired* state only — never by client status, so a
        crashed-but-restartable task keeps its resources accounted."""
        return self.desired_status in (ALLOC_DESIRED_STATUS_STOP,
                                       ALLOC_DESIRED_STATUS_EVICT,
                                       ALLOC_DESIRED_STATUS_FAILED)


# ---------------------------------------------------------------------------
# Evaluation (reference: structs.go:1293-1409)
# ---------------------------------------------------------------------------

@dataclass
class Evaluation(_Struct):
    id: str = ""
    priority: int = JOB_DEFAULT_PRIORITY
    type: str = JOB_TYPE_SERVICE
    triggered_by: str = ""
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait: float = 0.0  # seconds
    next_eval: str = ""
    previous_eval: str = ""
    create_index: int = 0
    modify_index: int = 0
    # Trace context (obs/trace.py): {"trace_id", "span_id"} of the
    # eval's anchor span, stamped at creation by the serving endpoint
    # and carried across the raft wire so broker/worker/applier spans
    # on any thread (or server) join the same tree.  Empty when tracing
    # is off.
    trace: dict = field(default_factory=dict)

    def terminal_status(self) -> bool:
        return self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED)

    def should_enqueue(self) -> bool:
        if self.status == EVAL_STATUS_PENDING:
            return True
        if self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED):
            return False
        raise ValueError(f"unhandled eval ({self.id}) status {self.status}")

    def make_plan(self, job: Optional[Job]) -> "Plan":
        return Plan(
            eval_id=self.id,
            priority=self.priority,
            all_at_once=bool(job.all_at_once) if job else False,
            # The plan joins its eval's span tree: queue/verify/commit
            # spans parent to the eval's anchor.
            trace=dict(self.trace),
        )

    def next_rolling_eval(self, wait: float) -> "Evaluation":
        return Evaluation(
            id=generate_uuid(),
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait=wait,
            previous_eval=self.id,
        )


# ---------------------------------------------------------------------------
# Plan / PlanResult (reference: structs.go:1414-1527)
# ---------------------------------------------------------------------------

@dataclass
class Plan(_Struct):
    _NESTED = {
        "node_update": ("dict", ("list", Allocation)),
        "node_allocation": ("dict", ("list", Allocation)),
        "failed_allocs": ("list", Allocation),
    }

    eval_id: str = ""
    eval_token: str = ""
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    node_update: dict = field(default_factory=dict)       # node_id -> [Alloc]
    node_allocation: dict = field(default_factory=dict)   # node_id -> [Alloc]
    failed_allocs: list = field(default_factory=list)
    # Overload control plane: absolute MONOTONIC deadline on this
    # host's clock (0.0 = none).  The applier drops expired plans
    # instead of verifying them (server/plan_apply.py expired_drops).
    # Host-local only — the Plan.Submit endpoint re-stamps it from the
    # RPC envelope's relative budget, never trusting a wire value.
    deadline: float = 0.0
    # Trace context (obs/trace.py): the owning eval's anchor, stamped
    # by Evaluation.make_plan and carried through Plan.Submit so the
    # leader's queue-wait/verify/raft/upsert spans join the tree.
    trace: dict = field(default_factory=dict)

    def append_update(self, alloc: Allocation, status: str, desc: str) -> None:
        new = alloc.copy()
        new.desired_status = status
        new.desired_description = desc
        self.node_update.setdefault(alloc.node_id, []).append(new)

    def pop_update(self, alloc: Allocation) -> None:
        existing = self.node_update.get(alloc.node_id, [])
        if existing and existing[-1].id == alloc.id:
            existing.pop()
            if not existing:
                self.node_update.pop(alloc.node_id, None)

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_failed(self, alloc: Allocation) -> None:
        self.failed_allocs.append(alloc)

    def is_noop(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and not self.failed_allocs)


@dataclass
class PlanResult(_Struct):
    _NESTED = {
        "node_update": ("dict", ("list", Allocation)),
        "node_allocation": ("dict", ("list", Allocation)),
        "failed_allocs": ("list", Allocation),
    }

    node_update: dict = field(default_factory=dict)
    node_allocation: dict = field(default_factory=dict)
    failed_allocs: list = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0

    def is_noop(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and not self.failed_allocs)

    def full_commit(self, plan: Plan) -> tuple[bool, int, int]:
        pna = plan.node_allocation
        expected = sum(map(len, pna.values()))
        if self.node_allocation is pna:
            # Result shares the plan's dict (nothing was trimmed):
            # committed in full by construction.
            return True, expected, expected
        sna = self.node_allocation
        actual = 0
        for k in pna:
            v = sna.get(k)
            if v is not None:
                actual += len(v)
        return actual == expected, expected, actual
