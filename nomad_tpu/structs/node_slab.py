"""Columnar node table: the fleet-axis twin of the alloc slab.

At 100k-1M nodes the scaling wall is not HBM — it is the per-object
node table feeding it: a full ``Node`` costs ~8 Python objects
(Resources + NetworkResource pairs, four dicts), so a 1M-node fleet is
~8M objects to build, walk and GC before a single tensor uploads.
``NodeSlab`` applies the alloc-slab contract (structs/alloc_slab.py) to
the node axis:

  - ONE template carries everything a (near-)uniform fleet shares —
    resource/reserved protos, network shapes, attributes/meta/links,
    node class, status — and dense columns carry the per-row scalars
    (ids, names, datacenters, per-row ip/cidr);
  - each store row is a tiny lazy ``SlabNode`` whose heavy fields
    (``resources``/``reserved``/``attributes``/``links``/``meta``)
    are data-descriptor properties materializing from the slab on
    first read, bit-identical to the object path;
  - the state->HBM bridge (models/fleet.build_fleet) reads the slab's
    dense vectors directly — no per-node Python walk — and constraint
    masks compile ONCE per (constraint, slab) instead of once per
    (constraint, node) because the slab declares attribute uniformity.

``state/store.upsert_node_slab`` bulk-registers a slab in one lock
hold.  Scale boundary (documented, deliberate): the slab covers the
store/scheduler plane — the state->HBM bridge that ROADMAP item 1
names as the wall; per-node wire registration (NODE_REGISTER_REQUEST)
still rides the object path, and a slab row that is *written* through
the object API (status/drain updates) detaches into a plain copied row
exactly like a mutated SlabAlloc leaves the columnar wire.
"""
from __future__ import annotations

import weakref

import numpy as np

from .model import (
    NODE_STATUS_READY,
    NetworkResource,
    Node,
    Resources,
)

_MISS = object()

# Heavy Node fields backed by slab columns/templates.  Everything else
# is an eager scalar (or a dataclass class-attribute default).
_NODE_LAZY = ("resources", "reserved", "attributes", "links", "meta")


def _node_lazy_field(name: str):
    """Data-descriptor for one heavy Node field: reads materialize from
    the slab on first access; writes mark the row mutated (``_hmut``)
    so the fleet fast path stops speaking for this object."""

    def _get(self):
        d = self.__dict__
        v = d.get(name, _MISS)
        if v is _MISS:
            v = d[name] = self._nslab.materialize(self._nrow, name)
        return v

    def _set(self, value):
        d = self.__dict__
        d[name] = value
        mut = d.get("_hmut")
        if mut is None:
            mut = d["_hmut"] = set()
        mut.add(name)

    return property(_get, _set)


class SlabNode(Node):
    """A Node backed by one NodeSlab row.

    Eagerly carries only the scalars the store/scheduler hot paths read
    (id, name, datacenter, status, indexes) plus ``_nslab``/``_nrow``;
    the heavy fields materialize lazily.  Materialized dicts/Resources
    are fresh per row (never the shared template itself), so callers
    keep the full Node mutability contract on their copies."""

    resources = _node_lazy_field("resources")
    reserved = _node_lazy_field("reserved")
    attributes = _node_lazy_field("attributes")
    links = _node_lazy_field("links")
    meta = _node_lazy_field("meta")

    def __setattr__(self, name, value):
        # ANY public-field write (status/drain flips on store copies
        # included) marks the row mutated: the slab no longer speaks
        # for this object, so the fleet fast path (node_slab_of) must
        # fall back to reading it as an object.  Internal caches
        # (underscore names) stay exempt.
        if not name.startswith("_"):
            d = self.__dict__
            mut = d.get("_hmut")
            if mut is None:
                mut = d["_hmut"] = set()
            mut.add(name)
        super().__setattr__(name, value)

    def copy(self) -> "SlabNode":
        # Node.copy() would read every heavy field through the
        # properties and deep-copy it; a slab-backed copy is one small
        # dict copy — materialized fields (already fresh per row) are
        # re-copied so the copy honors Node.copy()'s deep-dict contract.
        new = SlabNode.__new__(SlabNode)
        d = dict(self.__dict__)
        mut = d.get("_hmut")
        if mut is not None:
            d["_hmut"] = set(mut)
        for name in _NODE_LAZY:
            v = d.get(name)
            if v is None:
                continue
            d[name] = v.copy() if isinstance(v, Resources) else dict(v)
        new.__dict__ = d
        return new


def _net_from_proto(proto: dict, **overrides) -> NetworkResource:
    n = NetworkResource.__new__(NetworkResource)
    d = dict(proto)
    d["reserved_ports"] = list(d.get("reserved_ports", ()))
    d["dynamic_ports"] = list(d.get("dynamic_ports", ()))
    d.update(overrides)
    n.__dict__ = d
    return n


class NodeSlab:
    """Dense columns + one shared template for a homogeneous node fleet.

    ``template`` is a fully-formed Node whose resources/reserved/
    attributes/meta/links every row shares except for the per-row
    network endpoints: row r's ``resources`` network carries
    ``cidrs[r]`` and its ``reserved`` network carries ``ips[r]`` (None
    columns mean the template's own values everywhere).  Because the
    shared fields are uniform by construction, the slab can declare
    ``uniform=True`` and the fleet bridge compiles each constraint mask
    against ONE representative row instead of walking the fleet.
    """

    __slots__ = ("__weakref__", "n", "ids", "names", "datacenters",
                 "cidrs", "ips", "template", "index",
                 "_res_proto", "_res_net", "_rsv_proto", "_rsv_net",
                 "_cap6", "_rsv6", "_cache")

    def __init__(self, ids: list, names: list, datacenters,
                 template: Node, cidrs=None, ips=None) -> None:
        n = len(ids)
        self.n = n
        self.ids = ids
        self.names = names
        # Shared string when the whole slab lives in one datacenter.
        self.datacenters = datacenters
        self.cidrs = cidrs
        self.ips = ips
        self.template = template
        self.index = 0
        # Split the template into protos once: materialization is a
        # dict copy + per-row endpoint insert, no attribute walks.
        res = template.resources
        self._res_proto = {k: v for k, v in res.__dict__.items()
                           if k != "networks"}
        self._res_net = res.networks[0].__dict__ if res.networks else None
        rsv = template.reserved
        if rsv is not None:
            self._rsv_proto = {k: v for k, v in rsv.__dict__.items()
                               if k != "networks"}
            self._rsv_net = rsv.networks[0].__dict__ if rsv.networks \
                else None
        else:
            self._rsv_proto = None
            self._rsv_net = None
        # Canonical per-row vectors (uniform across rows: per-row
        # endpoints never change mbits/port counts).
        self._cap6 = np.asarray(res.as_vector(), dtype=np.float32)
        self._rsv6 = np.asarray(rsv.as_vector(), dtype=np.float32) \
            if rsv is not None else np.zeros(6, dtype=np.float32)
        # Canonical row objects, weakly held (same policy as
        # AllocSlab._cache): the store's table keeps rows alive; a
        # dropped generation frees its rows refcount-only.
        self._cache: "weakref.WeakValueDictionary" = \
            weakref.WeakValueDictionary()

    # -- columnar reads (the fleet bridge) ---------------------------------
    def datacenter_of(self, r: int) -> str:
        dc = self.datacenters
        return dc if isinstance(dc, str) else dc[r]

    def capacity_vec(self) -> np.ndarray:
        """f32[6] shared capacity vector (uniform fleet)."""
        return self._cap6

    def reserved_vec(self) -> np.ndarray:
        return self._rsv6

    def ready(self) -> bool:
        t = self.template
        return t.status == NODE_STATUS_READY and not t.drain

    # -- lazy materialization ----------------------------------------------
    def materialize(self, r: int, name: str):
        if name == "resources":
            res = Resources.__new__(Resources)
            d = dict(self._res_proto)
            if self._res_net is not None:
                cidr = self.cidrs[r] if self.cidrs is not None else None
                net = _net_from_proto(self._res_net) if cidr is None \
                    else _net_from_proto(self._res_net, cidr=cidr)
                d["networks"] = [net]
            else:
                d["networks"] = []
            res.__dict__ = d
            return res
        if name == "reserved":
            if self._rsv_proto is None:
                return None
            rsv = Resources.__new__(Resources)
            d = dict(self._rsv_proto)
            if self._rsv_net is not None:
                ip = self.ips[r] if self.ips is not None else None
                net = _net_from_proto(self._rsv_net) if ip is None \
                    else _net_from_proto(self._rsv_net, ip=ip)
                d["networks"] = [net]
            else:
                d["networks"] = []
            rsv.__dict__ = d
            return rsv
        if name == "attributes":
            return dict(self.template.attributes)
        if name == "links":
            return dict(self.template.links)
        if name == "meta":
            return dict(self.template.meta)
        raise KeyError(name)

    # -- row objects -------------------------------------------------------
    def _eager(self, r: int) -> dict:
        t = self.template
        return {
            "id": self.ids[r], "name": self.names[r],
            "datacenter": self.datacenter_of(r),
            "node_class": t.node_class, "status": t.status,
            "drain": t.drain,
            "create_index": self.index, "modify_index": self.index,
            "_nslab": self, "_nrow": r,
        }

    def node(self, r: int) -> SlabNode:
        """The canonical SlabNode for row ``r`` (weakly cached)."""
        node = self._cache.get(r)
        if node is None:
            node = SlabNode.__new__(SlabNode)
            node.__dict__ = self._eager(r)
            self._cache[r] = node
        return node

    def rows(self) -> list:
        return [self.node(r) for r in range(self.n)]


def node_slab_of(nodes: list):
    """The NodeSlab speaking for EVERY node in ``nodes`` (in row
    order, unmutated), or None — the fleet bridge's fast-path probe.
    A single mutated/foreign/out-of-order row disqualifies the slab:
    correctness first, the object walk handles mixed tables."""
    if not nodes:
        return None
    slab = nodes[0].__dict__.get("_nslab")
    if slab is None or slab.n != len(nodes):
        return None
    for i, node in enumerate(nodes):
        d = node.__dict__
        if d.get("_nslab") is not slab or d.get("_nrow") != i \
                or "_hmut" in d:
            return None
    return slab
