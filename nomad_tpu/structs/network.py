"""Network resource indexing: port + bandwidth accounting per node.

Capability parity with /root/reference/nomad/structs/network.go:21-204.
Port assignment for dynamic ports stays host-side (inherently sequential);
the device-side scheduler models bandwidth and port-slot capacity as extra
resource dims so its fit mask over-approximates soundly before this exact
assignment runs.
"""
from __future__ import annotations

import ipaddress
import random
from typing import Optional

from .model import (
    MAX_DYNAMIC_PORT,
    MAX_RAND_PORT_ATTEMPTS,
    MIN_DYNAMIC_PORT,
    Allocation,
    NetworkResource,
    Node,
)


_CIDR_CACHE: dict = {}
_CIDR_CACHE_MAX_IPS = 256


def _cidr_ips(cidr: str):
    """Yield a CIDR's IP strings; the first 256 are cached (node CIDRs are
    static and usually /32 — re-parsing per placement dominated the
    scheduler's host time), the rest iterate lazily so a /8 or IPv6 block
    never materializes in memory."""
    cached = _CIDR_CACHE.get(cidr)
    if cached is None:
        try:
            net = ipaddress.ip_network(cidr, strict=False)
        except ValueError:
            _CIDR_CACHE[cidr] = ([], True)
            return
        head: list = []
        complete = True
        for ip in net:
            if len(head) >= _CIDR_CACHE_MAX_IPS:
                complete = False
                break
            head.append(str(ip))
        if len(_CIDR_CACHE) > 65536:
            _CIDR_CACHE.clear()
        _CIDR_CACHE[cidr] = cached = (head, complete)
    head, complete = cached
    yield from head
    if not complete:
        for i, ip in enumerate(ipaddress.ip_network(cidr, strict=False)):
            if i >= _CIDR_CACHE_MAX_IPS:
                yield str(ip)


class NetworkIndex:
    """Tracks available and used network resources on one node."""

    def __init__(self) -> None:
        self.avail_networks: list[NetworkResource] = []
        self.avail_bandwidth: dict[str, int] = {}
        self.used_ports: dict[str, set[int]] = {}
        self.used_bandwidth: dict[str, int] = {}

    def overcommitted(self) -> bool:
        for device, used in self.used_bandwidth.items():
            if used > self.avail_bandwidth.get(device, 0):
                return True
        return False

    def set_node(self, node: Node) -> bool:
        """Register the node's networks; True if reserved ports collide."""
        collide = False
        for n in node.resources.networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = n.mbits
        if node.reserved is not None:
            for n in node.reserved.networks:
                if self.add_reserved(n):
                    collide = True
        return collide

    def add_allocs(self, allocs: list[Allocation]) -> bool:
        collide = False
        for alloc in allocs:
            for task_res in alloc.task_resources.values():
                if not task_res.networks:
                    continue
                if self.add_reserved(task_res.networks[0]):
                    collide = True
        return collide

    def add_reserved(self, n: NetworkResource) -> bool:
        collide = False
        used = self.used_ports.setdefault(n.ip, set())
        for port in n.reserved_ports:
            if port in used:
                collide = True
            else:
                used.add(port)
        self.used_bandwidth[n.device] = \
            self.used_bandwidth.get(n.device, 0) + n.mbits
        return collide

    def remove_reserved(self, n: NetworkResource) -> None:
        """Undo add_reserved (speculative offers rolled back)."""
        used = self.used_ports.get(n.ip)
        if used is not None:
            for port in n.reserved_ports:
                used.discard(port)
        self.used_bandwidth[n.device] = \
            self.used_bandwidth.get(n.device, 0) - n.mbits

    def _yield_ips(self):
        for n in self.avail_networks:
            for ip in _cidr_ips(n.cidr):
                yield n, ip

    def assign_network(
        self, ask: NetworkResource,
        rng: Optional[random.Random] = None,
    ) -> tuple[Optional[NetworkResource], str]:
        """Offer an IP + ports satisfying the ask, or (None, reason)."""
        from nomad_tpu.utils.native import HAS_NATIVE, native

        use_native = HAS_NATIVE and rng is None
        rng = rng or random
        err = "no networks available"
        for n, ip_str in self._yield_ips():
            if (self.used_bandwidth.get(n.device, 0) + ask.mbits
                    > self.avail_bandwidth.get(n.device, 0)):
                err = "bandwidth exceeded"
                continue

            used = self.used_ports.get(ip_str)
            if used is None:
                used = self.used_ports.setdefault(ip_str, set())

            if use_native:
                # C++ fast path (native/port_alloc.cpp): same semantics,
                # one call instead of a Python loop per port attempt.
                ports = native.assign_ports(
                    used, ask.reserved_ports, len(ask.dynamic_ports),
                    MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT,
                    MAX_RAND_PORT_ATTEMPTS)
                if ports is None:
                    err = "port selection failed"
                    continue
                return NetworkResource(
                    device=n.device, ip=ip_str, mbits=ask.mbits,
                    reserved_ports=ports,
                    dynamic_ports=list(ask.dynamic_ports)), ""

            if any(port in used for port in ask.reserved_ports):
                err = "reserved port collision"
                continue

            offer = NetworkResource(
                device=n.device,
                ip=ip_str,
                mbits=ask.mbits,
                reserved_ports=list(ask.reserved_ports),
                dynamic_ports=list(ask.dynamic_ports),
            )

            ok = True
            for _ in range(len(ask.dynamic_ports)):
                for attempt in range(MAX_RAND_PORT_ATTEMPTS):
                    port = rng.randrange(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)
                    if port not in used and port not in offer.reserved_ports:
                        offer.reserved_ports.append(port)
                        break
                else:
                    ok = False
                    break
            if not ok:
                err = "dynamic port selection failed"
                continue

            return offer, ""
        return None, err
