"""Placement scoring + fitting math.

Capability parity with /root/reference/nomad/structs/funcs.go.  `score_fit`
(Google BestFit-v3: 20 - (10^freeCpuFrac + 10^freeMemFrac), clamped [0, 18])
is the exact function the device-side scheduler vectorizes over the fleet
tensor in nomad_tpu/ops/binpack.py — this scalar version is the golden
reference for parity tests.
"""
from __future__ import annotations

from typing import Optional

from .model import Allocation, Node, Resources
from .network import NetworkIndex


def remove_allocs(allocs: list[Allocation],
                  remove: list[Allocation]) -> list[Allocation]:
    remove_ids = {a.id for a in remove}
    return [a for a in allocs if a.id not in remove_ids]


def filter_terminal_allocs(allocs: list[Allocation]) -> list[Allocation]:
    return [a for a in allocs if not a.terminal_status()]


def allocs_fit(
    node: Node,
    allocs: list[Allocation],
    net_idx: Optional[NetworkIndex] = None,
) -> tuple[bool, str, Resources]:
    """Check whether the allocation set fits on the node.

    Returns (fit, exhausted-dimension, total-utilization).  If net_idx is
    given the caller has already checked port collisions.
    """
    used = Resources()
    if node.reserved is not None:
        used.add(node.reserved)
    for alloc in allocs:
        used.add(alloc.resources)

    ok, dim = node.resources.superset(used)
    if not ok:
        return False, dim, used

    if net_idx is None:
        net_idx = NetworkIndex()
        collide = net_idx.set_node(node)
        collide = net_idx.add_allocs(allocs) or collide
        if collide:
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    return True, "", used


def score_fit(node: Node, util: Resources) -> float:
    """BestFit-v3 packing score; 18 is a perfect fit, 0 is empty/overfit."""
    node_cpu = float(node.resources.cpu)
    node_mem = float(node.resources.memory_mb)
    if node.reserved is not None:
        node_cpu -= node.reserved.cpu
        node_mem -= node.reserved.memory_mb

    # Zero-capacity nodes score 0 (Go float division yields Inf -> clamped).
    if node_cpu <= 0 or node_mem <= 0:
        return 0.0

    free_pct_cpu = 1.0 - (util.cpu / node_cpu)
    free_pct_mem = 1.0 - (util.memory_mb / node_mem)

    total = 10.0 ** free_pct_cpu + 10.0 ** free_pct_mem
    score = 20.0 - total
    return max(0.0, min(18.0, score))


def score_fit_vec(util_cpu, util_mem, node_cpu, node_mem, *,
                  valid=None, safe_cpu=None, safe_mem=None):
    """Vectorized BestFit-v3 twin of score_fit (numpy arrays in, array
    out): the ONE producer of the scoring curve for the vector paths
    (ops/binpack_host._HostScorer, scheduler/system_vec stage 2) —
    tuning the curve or the [0, 18] clamp happens here and in the
    scalar above only.  Zero-capacity rows score 0 like the scalar's
    early return.  Callers on a hot path may pass the node-static
    pieces precomputed (``valid``/``safe_cpu``/``safe_mem``)."""
    import numpy as np

    given = (valid is not None, safe_cpu is not None, safe_mem is not None)
    if not any(given):
        valid = (node_cpu > 0) & (node_mem > 0)
        safe_cpu = np.where(valid, node_cpu, 1.0)
        safe_mem = np.where(valid, node_mem, 1.0)
    elif not all(given):
        raise TypeError("score_fit_vec: the precomputed kwargs are "
                        "all-or-nothing (valid + safe_cpu + safe_mem)")
    score = 20.0 - (
        np.power(np.float32(10.0), 1.0 - util_cpu / safe_cpu)
        + np.power(np.float32(10.0), 1.0 - util_mem / safe_mem))
    score = np.asarray(score)
    # dtype-preserving zero: float32 pipelines must stay float32 (the
    # host top-k packs the raw float32 bits into its selection key).
    return np.where(valid, np.clip(score, 0.0, 18.0),
                    score.dtype.type(0.0))
