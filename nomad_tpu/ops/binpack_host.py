"""Host (numpy) executor for the bin-pack kernels.

Same math as nomad_tpu/ops/binpack.py (score_all_nodes / place_sequence /
place_rounds), evaluated eagerly with numpy on the host.  Exists because a
device dispatch has a fixed floor — one network round trip on
remote-attached TPUs (~100 ms through the tunnel), ~100 us locally — that
dwarfs the compute for small workloads: a 100-node fleet scores in a few
microseconds of vectorized numpy.  The scheduler picks the executor per
dispatch (nomad_tpu/scheduler/jax_binpack.py choose_executor): tiny
fleets/evals run here latency-optimal, large ones ride the device where
the MXU + pipelining win and the node axis can shard across a mesh.

This is the same engineering trade XLA itself makes with host callbacks:
don't ship work to an accelerator that costs more to reach than to run.
Semantics are kernel-for-kernel identical (parity-tested in
tests/test_jax_binpack.py); reference math AllocsFit/ScoreFit
(/root/reference/nomad/structs/funcs.go:48-124), anti-affinity
(/root/reference/scheduler/rank.go:243-302).
"""
from __future__ import annotations

import numpy as np

from nomad_tpu.structs.funcs import score_fit_vec

NEG_INF = -1.0e30
DIM_CPU = 0
DIM_MEM = 1


class _HostScorer:
    """Precomputes node-static pieces so per-step work is minimal."""

    def __init__(self, capacity, reserved) -> None:
        self.capacity = capacity
        self.base = reserved.astype(np.float32)
        node_cpu = capacity[:, DIM_CPU] - reserved[:, DIM_CPU]
        node_mem = capacity[:, DIM_MEM] - reserved[:, DIM_MEM]
        self.valid_node = (node_cpu > 0) & (node_mem > 0)
        self.safe_cpu = np.where(node_cpu > 0, node_cpu, 1.0
                                 ).astype(np.float32)
        self.safe_mem = np.where(node_mem > 0, node_mem, 1.0
                                 ).astype(np.float32)

    def masked_scores(self, usage, job_counts, ask, feasible, distinct,
                      penalty):
        util = self.base + usage + ask
        fit = (util <= self.capacity).all(axis=-1)
        score = score_fit_vec(
            util[:, DIM_CPU], util[:, DIM_MEM], None, None,
            valid=self.valid_node, safe_cpu=self.safe_cpu,
            safe_mem=self.safe_mem)
        score -= penalty * job_counts
        ok = feasible & fit
        if distinct:
            ok = ok & (job_counts == 0)
        return np.where(ok, score, np.float32(NEG_INF))


def place_sequence_host(capacity, reserved, usage0, job_counts0, feasible,
                        asks, distinct, group_idx, valid, penalty,
                        n_real: int = 0):
    """numpy twin of ops/binpack.place_sequence (same args/outputs).

    ``n_real``: number of real (non-padding) node rows.  The device needs
    the padded static shape; the host doesn't — scoring is sliced to the
    real rows (padding rows are never feasible, so results are identical).
    """
    capacity = np.asarray(capacity)
    n_pad = capacity.shape[0]
    n = n_real or n_pad
    scorer = _HostScorer(capacity[:n], np.asarray(reserved)[:n])
    usage_full = np.array(usage0, dtype=np.float32, copy=True)
    jc_full = np.array(job_counts0, dtype=np.float32, copy=True)
    usage, jc = usage_full[:n], jc_full[:n]
    P = len(group_idx)
    chosen = np.full(P, -1, dtype=np.int32)
    scores = np.zeros(P, dtype=np.float32)
    feasible = np.asarray(feasible)
    asks = np.asarray(asks, dtype=np.float32)
    for p in range(P):
        if not valid[p]:
            continue
        g = group_idx[p]
        ask = asks[g]
        masked = scorer.masked_scores(usage, jc, ask, feasible[g, :n],
                                      bool(distinct[g]), penalty)
        c = int(masked.argmax())
        best = masked[c]
        if best > NEG_INF / 2:
            usage[c] += ask
            jc[c] += 1
            chosen[p] = c
            scores[p] = best
    return chosen, scores, usage_full


def _topk_exact(masked: np.ndarray, k: int) -> np.ndarray:
    """Indices of the k largest scores, ties broken by LOWER index —
    exactly lax.top_k's contract — in O(n + k log k).

    A plain argpartition can't be used directly: when ties straddle the
    k boundary it picks an arbitrary subset (and homogeneous fleets tie
    constantly).  Packing the score and the inverted index into one
    int64 key makes the order total, so argpartition selects the same
    SET top_k would and a small sort of that slice gives the same
    ORDER.  The float->int map is the standard monotone transform
    (IEEE-754 totally ordered as sign-flipped integers)."""
    n = len(masked)
    if k <= 0:
        return np.empty(0, dtype=np.intp)
    if k >= n:
        return np.argsort(-masked, kind="stable")
    # -0.0 == +0.0 as floats (tie -> index order) but their bit
    # patterns differ; +0.0 normalizes both to one key.
    masked = masked + np.float32(0.0)
    bits = masked.view(np.int32).astype(np.int64)
    u = np.where(bits >= 0, bits + np.int64(0x80000000), ~bits)
    # Center the 32-bit ordered value into signed range BEFORE the
    # shift so the packed key cannot overflow int64.
    key = ((u - np.int64(0x80000000)) << np.int64(32)) \
        | np.arange(n - 1, -1, -1, dtype=np.int64)
    sel = np.argpartition(key, n - k)[n - k:]
    return sel[np.argsort(-key[sel])]


def place_rounds_host(capacity, reserved, usage0, jc0, feasible, asks,
                      distinct, counts, penalty, k_cap: int, rounds: int,
                      n_real: int = 0):
    """numpy twin of ops/binpack.place_rounds (same args/outputs):
    [G, rounds * k_cap] per-slot placement streams via top-k rounds.

    Host-only shortcuts (results identical): node rows sliced to
    ``n_real`` and padding slots (count 0 — they place nothing on the
    device too) skipped outright.
    """
    capacity = np.asarray(capacity)
    n = n_real or capacity.shape[0]
    scorer = _HostScorer(capacity[:n], np.asarray(reserved)[:n])
    usage_full = np.array(usage0, dtype=np.float32, copy=True)
    jc_full = np.array(jc0, dtype=np.float32, copy=True)
    usage, jc = usage_full[:n], jc_full[:n]
    feasible = np.asarray(feasible)
    asks = np.asarray(asks, dtype=np.float32)
    G = feasible.shape[0]
    chosen = np.full((G, rounds * k_cap), -1, dtype=np.int32)
    scores = np.zeros((G, rounds * k_cap), dtype=np.float32)
    pos = np.arange(k_cap)
    for s in range(G):
        ask = asks[s]
        remaining = int(counts[s])
        if remaining <= 0:
            continue
        for r in range(rounds):
            if remaining <= 0:
                break
            masked = scorer.masked_scores(usage, jc, ask,
                                          feasible[s, :n],
                                          bool(distinct[s]), penalty)
            order = _topk_exact(masked, k_cap)
            vals = masked[order]
            take = (pos[:len(order)] < remaining) & (vals > NEG_INF / 2)
            idx = order[take]
            usage[idx] += ask
            jc[idx] += 1
            placed = int(take.sum())
            remaining -= placed
            lo = r * k_cap
            chosen[s, lo:lo + len(order)][take] = idx.astype(np.int32)
            scores[s, lo:lo + len(order)][take] = vals[take]
    return chosen, scores, usage_full
