"""Device-side ops: vectorized fit/score/placement kernels."""
from .binpack import (  # noqa: F401
    place_sequence,
    place_sequence_batch,
    score_all_nodes,
)
