"""Window-verify policy: which engine runs the group-commit base fit.

The window verify (ops/plan_conflict.py evaluate_window) picks between
two engines for the cross-plan base-fit pass:

  host    dense numpy against the UsageMirror's host arrays — zero
          dispatch latency, always available, and the byte-exact
          reference every parity rig replays;
  device  one sharded dispatch per window against the mesh-resident
          ShardedResidency twins (parallel/mesh.window_verify_sharded)
          — the commit-path cost stops scaling with fleet size because
          the fleet tensors never leave the mesh (bench 5f's
          fleet-scaling sub-table asserts the flatness).

``auto`` (the default) takes the device path only when it is FREE to
take: a mesh is configured AND the mirror's sharded usage twin is
already resident for the current generation (the window-lease rule,
models/fleet.py UsageMirror.window_lease) — so a host-only deployment
never pays an upload it didn't ask for.  ``device`` forces the intent:
it additionally triggers the out-of-lock twin upload so subsequent
windows hold the lease (the first window after a cold start may still
fall back, counted by the applier's ``device_verify_fallbacks``).
``host`` pins the reference path.

Resolution order mirrors ``NOMAD_TPU_EXECUTOR`` (scheduler/executor.py)
exactly — first set wins:

  1. the ``NOMAD_TPU_VERIFY`` environment variable — checked per window
     so a bench or operator can flip it without a restart;
  2. the process policy set from server config
     (``set_verify_policy``);
  3. ``auto``.

The lever only selects the engine; verdicts, accepted alloc sets and
store fingerprints are byte-identical on both sides (the
tests/test_plan_batch.py host/device parity rigs gate this on every
run), and the exact-walk punts — out-of-fleet nodes, odd port/topology
shapes, ``conflict_fallbacks`` — run the unchanged host code under
either policy.
"""
from __future__ import annotations

import os

VERIFY_AUTO = "auto"
VERIFY_HOST = "host"
VERIFY_DEVICE = "device"

VALID_VERIFY = (VERIFY_AUTO, VERIFY_HOST, VERIFY_DEVICE)

ENV_VAR = "NOMAD_TPU_VERIFY"

_configured: str = VERIFY_AUTO


class VerifyPolicyError(ValueError):
    pass


def _validate(value: str, source: str) -> str:
    v = (value or "").strip().lower()
    if v not in VALID_VERIFY:
        raise VerifyPolicyError(
            f"invalid verify engine {value!r} from {source}: want one "
            f"of {', '.join(VALID_VERIFY)}")
    return v


def validate_verify(value: str, source: str = "config") -> str:
    """Public validation hook for config loaders: normalized value or
    VerifyPolicyError."""
    return _validate(value, source)


def set_verify_policy(value: str) -> None:
    """Install the process-wide policy (config plumbing; env still
    wins).  Raises VerifyPolicyError on unknown values so a typo in a
    config file fails the boot instead of silently running ``auto``."""
    global _configured
    _configured = _validate(value, "config")


def verify_policy() -> str:
    """The effective policy right now: env var, then configured value,
    then ``auto``.  Read per window — cheap (one getenv) and it keeps
    the bench's scoped overrides race-free with respect to restarts."""
    env = os.environ.get(ENV_VAR)
    if env:
        return _validate(env, f"${ENV_VAR}")
    return _configured


class verify_override:
    """Scoped force of the verify engine (bench rows, parity tests).

    Sets the ENV override — the highest-precedence source — and restores
    the previous value on exit, so nesting and config interplay behave
    predictably.  Process-global like the env var itself; use from the
    thread that owns the run (the applier reads the policy once per
    window, on its own thread).
    """

    def __init__(self, value: str) -> None:
        self.value = _validate(value, "verify_override")
        self._saved: str | None = None

    def __enter__(self) -> "verify_override":
        self._saved = os.environ.get(ENV_VAR)
        os.environ[ENV_VAR] = self.value
        return self

    def __exit__(self, *exc) -> None:
        if self._saved is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = self._saved
