"""Vectorized bin-packing: the TPU replacement for the iterator hot loop.

Re-expresses the reference's per-candidate scoring walk
(/root/reference/scheduler/rank.go:161-234 BinPackIterator +
/root/reference/nomad/structs/funcs.go:48-124 AllocsFit/ScoreFit +
/root/reference/scheduler/rank.go:243-302 JobAntiAffinityIterator +
/root/reference/scheduler/select.go MaxScoreIterator) as array ops over the
whole fleet at once:

  fit    = all(reserved + usage + ask <= capacity, dims)     # AllocsFit
  score  = clamp(20 - (10^freeCpu% + 10^freeMem%), 0, 18)    # ScoreFit v3
  score -= penalty * same_job_count                          # anti-affinity
  choice = argmax(where(feasible & fit, score, -inf))        # MaxScore

Placements within one evaluation interact through the usage tensor (placing
alloc i changes the residual seen by alloc i+1), so a single evaluation is a
``lax.scan`` over its placement sequence, each step O(N) elementwise + one
argmax — fully on-device, no host round-trips.  Independent evaluations are
batched with ``vmap`` (optimistic concurrency: each plans against its own
copy of the snapshot usage, conflicts resolved at plan-apply, exactly like
the reference's worker pool).

Instead of the reference's power-of-two-choices truncation
(stack.go:106-117, LimitIterator) the device scores EVERY feasible node —
a full-fleet argmax is cheaper on TPU than emulating sequential truncation,
and placement quality strictly improves (SURVEY.md section 7).

All shapes are static (node axis padded to a power of two, placement axis
bucketed) so jit caches stay hot across evals.  The node axis is the
sharding axis for multi-chip meshes (nomad_tpu/parallel/mesh.py).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1.0e30

# Resource dim layout (nomad_tpu/structs ALL_FIT_DIMS).
DIM_CPU = 0
DIM_MEM = 1


def score_all_nodes(capacity, reserved, usage, job_counts, ask, feasible,
                    distinct, penalty):
    """Score one ask against every node. Returns (masked_scores f32[N]).

    Exact vectorization of ScoreFit (funcs.go:92-124) + AllocsFit dimension
    check (funcs.go:48-87) + job anti-affinity (rank.go:243-302).
    """
    util = reserved + usage + ask  # == AllocsFit's `used` + this ask

    # AllocsFit: every dimension must fit within capacity.
    fit = jnp.all(util <= capacity, axis=-1)

    # ScoreFit (BestFit v3): free fraction of cpu+mem after reservation.
    node_cpu = capacity[:, DIM_CPU] - reserved[:, DIM_CPU]
    node_mem = capacity[:, DIM_MEM] - reserved[:, DIM_MEM]
    safe_cpu = jnp.where(node_cpu > 0, node_cpu, 1.0)
    safe_mem = jnp.where(node_mem > 0, node_mem, 1.0)
    free_cpu = 1.0 - util[:, DIM_CPU] / safe_cpu
    free_mem = 1.0 - util[:, DIM_MEM] / safe_mem
    score = 20.0 - (jnp.power(10.0, free_cpu) + jnp.power(10.0, free_mem))
    score = jnp.clip(score, 0.0, 18.0)
    score = jnp.where((node_cpu > 0) & (node_mem > 0), score, 0.0)

    # Job anti-affinity: spread same-job allocs across nodes.
    score = score - penalty * job_counts.astype(score.dtype)

    # distinct_hosts: no second same-job alloc on a node.
    ok = feasible & fit & jnp.where(distinct, job_counts == 0, True)
    return jnp.where(ok, score, NEG_INF)


def _place_sequence(capacity, reserved, usage0, job_counts0, feasible, asks,
                    distinct, group_idx, valid, penalty, unroll: int = 1):
    """Place a sequence of allocations for one evaluation, on device.

    Args:
      capacity, reserved: f32[N, D] node-static tensors.
      usage0:     f32[N, D] usage at plan start (existing - evictions).
      job_counts0: i32[N] proposed same-job allocs per node.
      feasible:   bool[G, N] precompiled static feasibility per task group.
      asks:       f32[G, D] total resource ask per task group.
      distinct:   bool[G] distinct_hosts flag per group.
      group_idx:  i32[P] which group each placement instance belongs to.
      valid:      bool[P] padding mask over the placement axis.
      penalty:    f32 scalar anti-affinity penalty (10 service / 5 batch).

    Returns:
      chosen: i32[P] node index per placement, -1 = no feasible node.
      scores: f32[P] winning score (meaningless where chosen == -1).
      usage:  f32[N, D] usage after all placements.
    """

    def step(carry, xs):
        usage, job_counts = carry
        g, is_valid = xs
        ask = asks[g]
        masked = score_all_nodes(capacity, reserved, usage, job_counts,
                                 ask, feasible[g], distinct[g], penalty)
        choice = jnp.argmax(masked)
        best = masked[choice]
        ok = is_valid & (best > NEG_INF / 2)

        delta = jnp.where(ok, 1.0, 0.0)
        usage = usage.at[choice].add(ask * delta)
        job_counts = job_counts.at[choice].add(delta.astype(job_counts.dtype))
        out_choice = jnp.where(ok, choice.astype(jnp.int32), -1)
        return (usage, job_counts), (out_choice, best)

    (usage, _), (chosen, scores) = lax.scan(
        step, (usage0, job_counts0), (group_idx, valid), unroll=unroll)
    return chosen, scores, usage


place_sequence = jax.jit(_place_sequence, static_argnames=("unroll",))


def _place_rounds(capacity, reserved, usage0, jc0, feasible, asks, distinct,
                  counts, penalty, k_cap: int, rounds: int):
    """Round-based placement: many copies per device step.

    For each task-group slot, one step scores the fleet once and places up
    to ``min(remaining, k_cap)`` copies on the top-scoring DISTINCT nodes
    (lax.top_k), then repeats for ``rounds`` rounds.  Equivalent to the
    one-at-a-time greedy whenever the anti-affinity penalty exceeds the
    bin-packing score gain of adding one copy (the host checks that
    condition and falls back to ``place_sequence`` otherwise) — because
    then the greedy never stacks a second copy on a node before using every
    other feasible node, i.e. it spreads exactly like top-k.

    Motivation: sequential scans pay a fixed per-iteration cost (severe on
    remote-attached TPUs); this path needs S x rounds steps instead of one
    step per placement — a 10k-placement eval with one deduped group runs
    in ~1 device step.

    Args mirror place_sequence except:
      counts: i32[G] — copies to place per slot.
      k_cap:  static — max copies placeable per round (<= padded node
              axis; may be below a slot's count, extra rounds cover it).
      rounds: static — rounds per slot (host sizes it so
              rounds * min(feasible_count, k_cap) >= count).

    Returns:
      chosen: i32[G, rounds * k_cap] node indices in placement order per
              slot (-1 = unplaced), scores alike, final usage.
    """

    def slot_step(carry, s):
        usage, jc = carry
        ask = asks[s]
        feas = feasible[s]
        dist = distinct[s]

        def round_step(carry2, _r):
            usage, jc, m = carry2
            masked = score_all_nodes(capacity, reserved, usage, jc, ask,
                                     feas, dist, penalty)
            vals, idx = lax.top_k(masked, k_cap)
            pos = lax.iota(jnp.int32, k_cap)
            valid = (pos < m) & (vals > NEG_INF / 2)
            usage = usage.at[idx].add(
                jnp.where(valid[:, None], ask[None, :], 0.0))
            jc = jc.at[idx].add(valid.astype(jc.dtype))
            placed = valid.sum()
            chosen_r = jnp.where(valid, idx.astype(jnp.int32), -1)
            return (usage, jc, m - placed), (chosen_r, vals)

        (usage, jc, _m), (chosen_rs, val_rs) = lax.scan(
            round_step, (usage, jc, counts[s]), jnp.arange(rounds))
        return (usage, jc), (chosen_rs.reshape(-1), val_rs.reshape(-1))

    (usage, _jc), (chosen, scores) = lax.scan(
        slot_step, (usage0, jc0), jnp.arange(feasible.shape[0]))
    return chosen, scores, usage


place_rounds = jax.jit(_place_rounds, static_argnames=("k_cap", "rounds"))


def _place_rounds_batched(capacity, reserved, usage0, jc0, feasible, asks,
                          distinct, counts, penalty, k_cap: int,
                          rounds: int):
    fn = jax.vmap(partial(_place_rounds, k_cap=k_cap, rounds=rounds),
                  in_axes=(None, None, None, 0, 0, 0, 0, 0, 0))
    return fn(capacity, reserved, usage0, jc0, feasible, asks, distinct,
              counts, penalty)


place_rounds_batch = jax.jit(_place_rounds_batched,
                             static_argnames=("k_cap", "rounds"))

# Batched over independent evaluations (axis 0 of per-eval args):
# optimistic concurrency on device — every eval starts from the SAME
# snapshot usage (broadcast on device, no per-eval upload) and evolves its
# own copy through the scan; job_counts IS per-eval (each eval schedules its
# own job).  The host plan-apply loop serializes commits (reference
# nomad/plan_apply.go parity).
place_sequence_batch = jax.jit(
    jax.vmap(
        partial(_place_sequence, unroll=1),
        in_axes=(None, None, None, 0, 0, 0, 0, 0, 0, 0),
    )
)
