"""Partitioned cross-plan conflict windows for the group-commit applier.

The leader's plan applier is the serialization point of optimistic
concurrency (server/plan_apply.py): under a contended storm it pays one
verify + one commit per plan.  ``evaluate_window`` restructures the
verify side for a whole *window* of pending plans:

  - the per-node resource fit — the numpy-churn hot loop of
    ``_evaluate_plan_vec`` — is computed for every (plan, node) claim in
    the window with a handful of dense array ops against the base
    snapshot's incremental usage mirror (models/fleet.py UsageMirror);
  - the window is PARTITIONED into connected components of the claim
    graph (``partition_window``: plans are vertices, joined when they
    claim a node in common).  Plans in different components touch
    disjoint node sets and therefore *cannot* conflict — each component
    verifies independently (concurrently, when the applier passes its
    component executor), while eval order is preserved exactly *within*
    each component;
  - order sensitivity within a component rides a *component overlay*
    (``_WindowState``) over a read-only per-window ``_Frame`` copied
    from the mirror: each plan's accepted portion is folded into the
    overlay before the next plan's verdicts — so plan i's claims are
    checked against committed state plus every earlier claim that could
    possibly interact with them, exactly the state sequential
    application would have reached;
  - claims the incremental path cannot serve (node not in the fleet,
    odd network topology) punt to the exact scalar walk against a
    component-local OptimisticSnapshot carrying the same folds, exactly
    as the per-plan verifier punts them.

The frame is copied under the mirror lock and the lock is RELEASED
before any component walks, so concurrent worker-side syncs are never
blocked behind a window verify (the old code held the mirror for the
whole pass).

Device-resident verify (``NOMAD_TPU_VERIFY``, ops/verify_policy.py):
when the policy resolves ``device`` (or ``auto`` with the twins already
resident), the dense base fit dispatches ONE sharded kernel per window
against the mesh-resident ShardedResidency twins
(parallel/mesh.window_verify_sharded) instead of gathering the host
mirror arrays: under the mirror lock the verify takes a residency
*lease* (models/fleet.py UsageMirror.window_lease — a reference to the
immutable resident usage twin, never a copy and never an upload), and
the claim-scatter + claim-sum/compare plus an optimistic scatter-add
overlay fold (all earlier window plans' accepted deltas per node) run
on the device.  Component walks consume the fetched numbers exactly
where the host lists sat, and take the device fold verdict only when
the walk can PROVE the optimistic assumption held (no in-flight
overlay, no rejected earlier plan, no alloc id referenced twice in the
window) — everything else, including every exact-walk punt
(out-of-fleet nodes, odd port/topology shapes) and the byte-exact
within-component ordering guarantee, runs the unchanged host code, so
verdicts, accepted alloc sets and store fingerprints are byte-identical
under either policy (tests/test_plan_batch.py host/device rigs).

Deadline-aware component scheduling: components are ordered by their
nearest member deadline (then window position), and the executor starts
them in that order — under saturation a near-deadline plan's component
verifies first, which together with the plan queue's deadline-promoted
drain keeps ``expired_drops`` at 0.

A plan whose claims overlap an earlier plan in the window (the
order-sensitive prefix conflict) is reported as a ``fallback`` — its
verdicts rode the component overlay rather than the clean dense pass —
and counted by the applier's ``conflict_fallbacks`` stat.  Because two
overlapping plans are by construction in the same component, the flag
means exactly what it meant when the window was one flat list.

Results are identical to calling ``evaluate_plan`` per plan in eval
order with the accepted portion of each plan folded into the view before
the next — the property the group-commit parity rigs
(tests/test_plan_batch.py) lock down for both the partitioned and the
``partition=False`` sequential path.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from nomad_tpu.structs import PlanResult

from nomad_tpu.utils.metrics import metrics

_MISS = object()

# Components below this size verify inline on the applier thread even
# when an executor is available: a saturated-but-uncontended window is
# dozens of single-plan components whose walks are a few microseconds
# of GIL-bound Python — worker handoff costs more than it buys.  A
# component at or past this size carries a real conflict cluster (an
# ordered chain of folds and possibly exact-walk punts), which is what
# concurrent verification exists for.
MIN_CONCURRENT_COMPONENT = 8


class WindowOutcome:
    """One plan's verdict within a window."""

    __slots__ = ("result", "fallback", "component")

    def __init__(self, result: PlanResult, fallback: bool,
                 component: int = 0) -> None:
        self.result = result
        # True when this plan's claims overlapped an earlier plan in the
        # window (or an in-flight apply) — the order-sensitive prefix
        # conflict: its verdicts came from the component overlay, not
        # the clean dense pass.
        self.fallback = fallback
        # Scheduling-order index of the claim-graph component this plan
        # verified in (0 on the unpartitioned paths).
        self.component = component


class WindowVerdicts(list):
    """The outcomes list plus window-level partition/scheduling info
    (``.info`` — None on the paths that never partitioned)."""

    def __init__(self, outcomes, info: Optional[dict] = None) -> None:
        super().__init__(outcomes)
        self.info = info


class _OverGet:
    """dict-shaped ``.get`` view: window overrides chained over the base
    frame's dict.  An override of None is a tombstone (entry removed
    within the window)."""

    __slots__ = ("over", "base")

    def __init__(self, over: dict, base: dict) -> None:
        self.over = over
        self.base = base

    def get(self, key, default=None):
        v = self.over.get(key, _MISS)
        if v is _MISS:
            return self.base.get(key, default)
        return default if v is None else v


class _DupGet:
    """``node_dup``-shaped view: duplicate-port counts recomputed from
    the window's materialized per-node port dicts, base passthrough for
    untouched nodes.  Port dicts are tens of entries, so the recompute
    is cheaper than incremental bookkeeping is error-prone."""

    __slots__ = ("ports", "base")

    def __init__(self, ports: dict, base: dict) -> None:
        self.ports = ports
        self.base = base

    def get(self, ni, default=None):
        pc = self.ports.get(ni)
        if pc is None:
            return self.base.get(ni, default)
        dup = sum(1 for c in pc.values() if c > 1)
        return dup if dup else default


class _Frame:
    """Read-only per-window copy of the mirror state the component
    walks consume, restricted to the window's touched nodes and claimed
    alloc ids.  Copied under the mirror lock, read without it — the
    lock is released before any component verifies, so worker-side
    mirror syncs never queue behind a window, and component walks on
    executor threads never read mirror state the lock discipline
    guards."""

    __slots__ = ("alloc_rows", "net_rows", "node_ports", "node_bw",
                 "node_net_keys", "node_dup")

    def __init__(self, mirror, ids, nis) -> None:
        alloc_rows = {}
        net_rows = {}
        m_rows = mirror.alloc_rows
        m_net = mirror.net_rows
        nis = set(nis)  # caller's set stays untouched; adds are O(1)
        for aid in ids:
            row = m_rows.get(aid)
            if row is not None:
                alloc_rows[aid] = (row[0], row[1])
                nis.add(row[0])
            nr = m_net.get(aid)
            if nr is not None:
                net_rows[aid] = nr
                nis.add(nr[0])
        self.alloc_rows = alloc_rows
        self.net_rows = net_rows
        self.node_ports = {}
        self.node_bw = {}
        self.node_net_keys = {}
        self.node_dup = {}
        for ni in nis:
            pc = mirror.node_ports.get(ni)
            if pc is not None:
                self.node_ports[ni] = dict(pc)
            bw = mirror.node_bw.get(ni)
            if bw:
                self.node_bw[ni] = bw
            keys = mirror.node_net_keys.get(ni)
            if keys is not None:
                self.node_net_keys[ni] = dict(keys)
            dup = mirror.node_dup.get(ni)
            if dup:
                self.node_dup[ni] = dup


class _WindowState:
    """Component overlay over a window ``_Frame``: base state plus the
    accepted portions of earlier plans in the component (and the
    in-flight apply's allocs that touch it), exposing exactly the reads
    the verifier needs — the same
    ``net_rows/node_ports/node_dup/node_bw/node_net_keys`` surface
    ``plan_apply._verify_node_net`` consumes, plus per-node 4-dim usage
    deltas for the fit check.  Never mutates the frame: per-node dicts
    are copied on first window write."""

    def __init__(self, frame, index_of) -> None:
        from nomad_tpu.models.fleet import _net_row, alloc_vec

        self._net_row = _net_row
        self._alloc_vec = alloc_vec
        self.m = frame
        self.index_of = index_of
        self.usage_delta: dict = {}   # ni -> [f, f, f, f]
        self._rows: dict = {}         # aid -> (ni, vec) | None
        self._net_over: dict = {}     # aid -> net row | None
        self._ports: dict = {}        # ni -> merged {port: count}
        self._bw: dict = {}           # ni -> merged mbits
        self._keys: dict = {}         # ni -> merged {(ip, dev): count}
        # The verifier-facing surface:
        self.net_rows = _OverGet(self._net_over, frame.net_rows)
        self.node_ports = _OverGet(self._ports, frame.node_ports)
        self.node_bw = _OverGet(self._bw, frame.node_bw)
        self.node_net_keys = _OverGet(self._keys, frame.node_net_keys)
        self.node_dup = _DupGet(self._ports, frame.node_dup)

    # -- removal accounting (the caller's removed_ids walk) ---------------
    def alloc_row(self, aid):
        """(ni, vec) of a live alloc — window override first, then the
        frame — or None when absent/removed."""
        v = self._rows.get(aid, _MISS)
        if v is not _MISS:
            return v
        return self.m.alloc_rows.get(aid)

    # -- copy-on-write materialization ------------------------------------
    def _ports_for(self, ni) -> dict:
        pc = self._ports.get(ni)
        if pc is None:
            pc = self._ports[ni] = dict(self.m.node_ports.get(ni, ()))
        return pc

    def _keys_for(self, ni) -> dict:
        keys = self._keys.get(ni)
        if keys is None:
            keys = self._keys[ni] = dict(
                self.m.node_net_keys.get(ni, ()))
        return keys

    def _bw_add(self, ni, mbits) -> None:
        self._bw[ni] = self.node_bw.get(ni, 0) + mbits

    # -- folds -------------------------------------------------------------
    def fold(self, alloc) -> None:
        """Apply one accepted alloc (placement or eviction) to the
        component overlay — the same old-row-out/new-row-in transition
        the mirror's own delta sync performs on commit."""
        aid = alloc.id
        old = self.alloc_row(aid)
        if old is not None:
            ni0, vec0 = old
            d = self.usage_delta.setdefault(ni0, [0.0] * 4)
            d[0] -= float(vec0[0])
            d[1] -= float(vec0[1])
            d[2] -= float(vec0[2])
            d[3] -= float(vec0[3])
        self._rows[aid] = None
        nr = self.net_rows.get(aid)
        if nr is not None:
            ni0, ports, mbits, key = nr
            if mbits:
                self._bw_add(ni0, -mbits)
            keys = self._keys_for(ni0)
            c = keys.get(key, 0) - 1
            if c > 0:
                keys[key] = c
            else:
                keys.pop(key, None)
            if ports:
                pc = self._ports_for(ni0)
                for p in ports:
                    c = pc.get(p, 0) - 1
                    if c > 0:
                        pc[p] = c
                    else:
                        pc.pop(p, None)
        self._net_over[aid] = None

        if alloc.terminal_status():
            return
        ni = self.index_of.get(alloc.node_id, -1)
        if ni < 0:
            return
        vec = self._alloc_vec(alloc)
        self._rows[aid] = (ni, vec)
        d = self.usage_delta.setdefault(ni, [0.0] * 4)
        d[0] += float(vec[0])
        d[1] += float(vec[1])
        d[2] += float(vec[2])
        d[3] += float(vec[3])
        row = self._net_row(alloc)
        if row is not None:
            ports, mbits, key = row
            self._net_over[aid] = (ni, ports, mbits, key)
            if mbits:
                self._bw_add(ni, mbits)
            keys = self._keys_for(ni)
            keys[key] = keys.get(key, 0) + 1
            if ports:
                pc = self._ports_for(ni)
                for p in ports:
                    pc[p] = pc.get(p, 0) + 1


def _touched(plan) -> set:
    return set(plan.node_update) | set(plan.node_allocation)


def _plan_alloc_ids(plan) -> set:
    ids = set()
    for allocs in plan.node_update.values():
        ids.update(a.id for a in allocs)
    for allocs in plan.node_allocation.values():
        ids.update(a.id for a in allocs)
    return ids


def _accepted_allocs(result) -> list:
    allocs = []
    for updates in result.node_update.values():
        allocs.extend(updates)
    for placements in result.node_allocation.values():
        allocs.extend(placements)
    allocs.extend(result.failed_allocs)
    return allocs


def partition_window(plans: list) -> list:
    """Connected components of the window's claim graph: plans are
    vertices, joined when they claim (place on OR evict from) a node in
    common.  Returns a list of components, each an ascending list of
    plan indices, ordered by first member — so concatenating them in
    order visits a conflict-free permutation of the window.

    Union-find over a node-id -> first-claimant map: O(total claims)
    with near-constant find, cheap enough to run on every window."""
    n = len(plans)
    parent = list(range(n))

    def find(i: int) -> int:
        while parent[i] != i:
            parent[i] = parent[parent[i]]  # path halving
            i = parent[i]
        return i

    owner: dict = {}
    for i, plan in enumerate(plans):
        for nid in _touched(plan):
            j = owner.get(nid)
            if j is None:
                owner[nid] = i
            else:
                ri, rj = find(i), find(j)
                if ri != rj:
                    # Union by MIN root: a component's root is always
                    # its earliest plan, keeping output deterministic.
                    if rj < ri:
                        ri, rj = rj, ri
                    parent[rj] = ri
    comps: dict = {}
    for i in range(n):
        comps.setdefault(find(i), []).append(i)
    return [comps[r] for r in sorted(comps)]


def evaluate_window(snap, plans: list, executor=None,
                    partition: bool = True) -> WindowVerdicts:
    """Verify a window of plans; returns one WindowOutcome per plan,
    results identical to sequential ``evaluate_plan`` + fold-into-
    overlay per plan in eval order.

    ``snap`` may be an OptimisticSnapshot carrying an in-flight apply's
    overlay; it is MUTATED — each plan's accepted portion is folded in so
    the caller's overlay ends up exactly as sequential application would
    leave it.

    ``partition=True`` splits the window into claim-graph components
    (scheduled nearest-deadline-first, concurrently when ``executor``
    is given); ``partition=False`` keeps the flat one-overlay walk —
    the pre-partition behavior, kept as the bench's in-run sequential
    baseline and exercised by the parity rigs.
    """
    from nomad_tpu.server.plan_apply import (
        OptimisticSnapshot,
        evaluate_plan,
    )

    overlay = snap if isinstance(snap, OptimisticSnapshot) \
        else OptimisticSnapshot(snap)
    if len(plans) == 1:
        # No cross-plan structure to exploit: the per-plan path already
        # carries its own vectorized fit (plan_apply._evaluate_plan_vec).
        # Same fallback definition as the window paths — overlap with
        # the in-flight apply's overlay counts.
        fallback = bool(_touched(plans[0])
                        & {n for n in overlay._by_node if n})
        result = evaluate_plan(snap, plans[0])
        if overlay is snap:
            # Only a caller-owned overlay needs the fold; a throwaway
            # one built here is dead work.
            overlay.upsert_allocs(_accepted_allocs(result))
        return WindowVerdicts([WindowOutcome(result, fallback)])

    start = time.perf_counter()
    outcomes = _evaluate_window_vec(overlay, plans, executor, partition)
    if outcomes is None:
        # No incremental mirror for this snapshot: per-plan exact path
        # against the running overlay, still in eval order.
        outcomes = WindowVerdicts([])
        dirty: set = {n for n in overlay._by_node if n}
        for plan in plans:
            nodes = _touched(plan)
            result = evaluate_plan(overlay, plan)
            outcomes.append(WindowOutcome(result, bool(nodes & dirty)))
            overlay.upsert_allocs(_accepted_allocs(result))
            # Same fallback definition as the vec path's `claimed`:
            # every node an earlier plan TOUCHED (accepted or not), so
            # the stat means one thing regardless of which path ran.
            dirty |= nodes
    metrics.measure_since("nomad.plan.evaluate_window", start)
    return outcomes


class _Prep:
    """Everything the component walks share, frozen by the coordinator
    before any component starts: the dense base-fit results, the frame,
    and the in-flight overlay's contents.  Read-only once built.

    ``devfit`` is None on the host engine; on a device dispatch it
    carries the kernel's optimistic fold verdicts (``base_used``/
    ``caps`` then hold the FETCHED device numbers — byte-identical to
    the host gather, so the walks don't care which engine filled
    them)."""

    __slots__ = ("plans", "plan_nodes", "verdicts", "pairs", "pair_of",
                 "base_used", "caps", "frame", "index_of", "statics",
                 "base", "refresh_index", "inflight", "inflight_nodes",
                 "inflight_by_node", "inflight_by_id", "devfit")


class _DeviceFit:
    """Fetched per-pair results of one window_verify_sharded dispatch.

    ``fits_seq[pair]`` is the device's optimistic overlay-fold verdict
    — base fit plus ALL earlier same-component window plans' deltas
    under the all-accepted assumption.  ``seq_ok`` is the window-level
    eligibility: False when any alloc id is referenced by two claims
    (double-evict / replace-after-place), where the optimistic prefix
    cannot equal the host fold order.  _walk_component additionally
    requires its own ``clean`` proof before trusting a verdict."""

    __slots__ = ("fits_seq", "seq_ok")


def _window_device_args(plans, plan_nodes, verdicts, pairs, mirror,
                        index_of, frame_ids, plan_comp, alloc_vec):
    """Per-window fold descriptors for the device kernel, built under
    the mirror lock (reads ``mirror.alloc_rows`` — the same rows the
    ``_Frame`` copies).  Simulates ``_WindowState.fold`` for every
    claim that can still be accepted (pass-1 rejections excluded,
    ``failed_allocs`` included — the walk folds those even on
    rejection), tagging each entry with its window plan index and
    claim-graph component so the kernel's prefix mask reproduces the
    component-local host fold order exactly."""
    m_rows = mirror.alloc_rows
    seq_ni: list = []
    seq_vec: list = []
    seq_order: list = []
    seq_comp: list = []
    ref_count: dict = {}

    def sim_fold(a, i, ci) -> None:
        aid = a.id
        ref_count[aid] = ref_count.get(aid, 0) + 1
        # Frame-restricted like _WindowState.alloc_row: an id outside
        # the window's frame reads None on the host walk too.
        row = m_rows.get(aid) if aid in frame_ids else None
        if row is not None:
            v = row[1]
            seq_ni.append(row[0])
            seq_vec.append([-float(v[0]), -float(v[1]), -float(v[2]),
                           -float(v[3])])
            seq_order.append(i)
            seq_comp.append(ci)
        if a.terminal_status():
            return
        ni = index_of.get(a.node_id, -1)
        if ni < 0:
            return
        v = alloc_vec(a)
        seq_ni.append(ni)
        seq_vec.append([float(v[0]), float(v[1]), float(v[2]),
                        float(v[3])])
        seq_order.append(i)
        seq_comp.append(ci)

    for i, plan in enumerate(plans):
        ci = plan_comp[i]
        pv = verdicts[i]
        for nid in plan_nodes[i]:
            if pv.get(nid, _MISS) is False:
                continue  # pass-1 rejection: none of its allocs fold
            for a in plan.node_update.get(nid, ()):
                sim_fold(a, i, ci)
            for a in plan.node_allocation.get(nid, ()):
                sim_fold(a, i, ci)
        for a in plan.failed_allocs:
            sim_fold(a, i, ci)
    seq_ok = all(c == 1 for c in ref_count.values())

    pair_removed: list = []
    for (_i, _nid, ni, _node, _placements, removed) in pairs:
        r0 = r1 = r2 = r3 = 0.0
        for aid in removed:
            row = m_rows.get(aid)
            if row is not None and row[0] == ni:
                v = row[1]
                r0 += float(v[0])
                r1 += float(v[1])
                r2 += float(v[2])
                r3 += float(v[3])
        pair_removed.append([r0, r1, r2, r3])

    return {
        "pair_ni": [p[2] for p in pairs],
        "pair_order": [p[0] for p in pairs],
        "pair_comp": [plan_comp[p[0]] for p in pairs],
        "pair_removed": pair_removed,
        "seq_ni": seq_ni,
        "seq_vec": seq_vec,
        "seq_order": seq_order,
        "seq_comp": seq_comp,
        "seq_ok": seq_ok,
    }


def _dispatch_window_fit(mesh, capres, lease, dargs, vec_pair, vec_rows,
                         n_pairs):
    """ONE sharded dispatch for the whole window's base fit + overlay
    fold, against the resident twins (``capres`` from the statics
    residency, ``lease`` from UsageMirror.window_lease).  Runs OUTSIDE
    the mirror lock — the descriptors are tiny host arrays, padded to
    one shared power-of-two bucket so distinct window sizes reuse the
    trace.  Returns (used_rows, caps_rows, _DeviceFit, devinfo);
    used/caps come back through devices.fetch_host and drop into
    ``prep.base_used``/``prep.caps`` exactly where the host gather's
    ``.tolist()`` sat."""
    from nomad_tpu.models.fleet import _pad_to
    from nomad_tpu.parallel.devices import fetch_host, transfer_counts
    from nomad_tpu.parallel.mesh import window_verify_sharded

    bucket = _pad_to(max(n_pairs, len(vec_rows), len(dargs["seq_ni"])))

    def pad_i(vals, fill):
        arr = np.full(bucket, fill, dtype=np.int32)
        if vals:
            arr[:len(vals)] = vals
        return arr

    def pad_v(vals):
        arr = np.zeros((bucket, 4), dtype=np.float32)
        if len(vals):
            arr[:len(vals)] = np.asarray(vals, dtype=np.float32)[:, :4]
        return arr

    t0 = time.perf_counter()
    before = transfer_counts()
    used, caps, fits = window_verify_sharded(
        mesh, capres[0], capres[1], lease,
        pad_i(dargs["pair_ni"], 0), pad_i(vec_pair, 0),
        pad_v(vec_rows), pad_i(dargs["seq_ni"], -1),
        pad_v(dargs["seq_vec"]), pad_i(dargs["seq_order"], 0),
        pad_i(dargs["seq_comp"], -1), pad_i(dargs["pair_order"], 0),
        pad_i(dargs["pair_comp"], 0), pad_v(dargs["pair_removed"]))
    used = fetch_host(used)
    caps = fetch_host(caps)
    fits = fetch_host(fits)
    after = transfer_counts()
    devfit = _DeviceFit()
    devfit.fits_seq = fits[:n_pairs]
    devfit.seq_ok = dargs["seq_ok"]
    devinfo = {
        "dispatched": True,
        "fallback": None,
        "pairs": n_pairs,
        "bucket": int(bucket),
        "seq_ok": dargs["seq_ok"],
        "h2d": after["h2d"] - before["h2d"],
        "d2h": after["d2h"] - before["d2h"],
        "wall": time.perf_counter() - t0,
    }
    return (np.asarray(used[:n_pairs], dtype=np.float32).tolist(),
            np.asarray(caps[:n_pairs], dtype=np.float32).tolist(),
            devfit, devinfo)


def _evaluate_window_vec(overlay, plans: list, executor,
                         partition: bool) -> Optional[WindowVerdicts]:
    """The vectorized window pass: dense base fit for every claim under
    the mirror lock, then per-component in-order verdict walks against
    the released frame.  Returns None when the snapshot cannot take the
    incremental path at all."""
    from nomad_tpu.models.fleet import alloc_vec, fleet_cache, mirror_for
    from nomad_tpu.structs import NODE_STATUS_READY

    base = overlay.base
    if getattr(base, "_t", None) is None:
        return None
    if not any(any(p.node_allocation.values()) for p in plans):
        # Evict/update-only window: every per-node verdict is True by
        # definition; don't spin up the mirror's net tracking for it.
        # The fallback stat keeps the uniform definition (claims
        # overlapping an earlier plan's touched nodes) even though the
        # verdicts here are state-independent.
        outcomes = WindowVerdicts([])
        claimed = {n for n in overlay._by_node if n}
        for plan in plans:
            nodes = _touched(plan)
            result = PlanResult(
                node_update={k: v for k, v in plan.node_update.items()
                             if v},
                node_allocation={k: v for k, v
                                 in plan.node_allocation.items() if v},
                failed_allocs=list(plan.failed_allocs))
            outcomes.append(WindowOutcome(result, bool(nodes & claimed)))
            overlay.upsert_allocs(_accepted_allocs(result))
            claimed |= nodes
        return outcomes

    statics = fleet_cache.statics_for(base)
    mirror = mirror_for(statics)
    capacity = statics.capacity
    index_of = statics.index_of

    # Pass-2 components are computed up front (pure on the plans): the
    # device fold descriptors need each plan's component id so the
    # kernel's prefix mask stays component-local — exactly the overlay
    # each host walk sees.
    if partition:
        comps = partition_window(plans)
    else:
        comps = [list(range(len(plans)))]
    plan_comp = [0] * len(plans)
    for ci, comp in enumerate(comps):
        for i in comp:
            plan_comp[i] = ci

    # Device-verify policy (ops/verify_policy.py): mesh resolution and
    # any twin warm-up happen OUTSIDE the mirror lock; under the lock
    # the device path only LOOKS UP residency (the window-lease rule).
    from nomad_tpu.ops.verify_policy import (
        VERIFY_DEVICE,
        VERIFY_HOST,
        verify_policy,
    )

    policy = verify_policy()
    dev_mesh = None
    devinfo = None
    if policy != VERIFY_HOST:
        from nomad_tpu.parallel.mesh import dispatch_mesh
        dev_mesh = dispatch_mesh(1, statics.n_pad)
        if dev_mesh is None:
            if policy == VERIFY_DEVICE:
                devinfo = {"dispatched": False, "fallback": "no-mesh"}
        elif policy == VERIFY_DEVICE:
            # Forced intent: warm the twins now (no-op when resident)
            # so this window — or the next — holds the lease.  ``auto``
            # never uploads: it takes the device path only when the
            # twins are already there.
            statics.device_capacity_reserved_sharded(dev_mesh)
            mirror.device_usage_sharded(dev_mesh, mirror.usage)

    prep = _Prep()
    prep.plans = plans
    prep.base = base
    prep.statics = statics
    prep.index_of = index_of
    prep.refresh_index = max(overlay.get_index("nodes"),
                             overlay.get_index("allocs"))
    prep.inflight = list(overlay._overlay.values())
    prep.inflight_nodes = {n for n in overlay._by_node if n}
    # Indexed ONCE per window: each component slices the in-flight
    # overlay by ITS nodes/ids in O(component), not O(overlay) — a
    # per-component scan would re-grow the O(window^2) fold churn the
    # partition exists to remove.  Entries carry their overlay
    # insertion ordinal so component folds keep the sequential order.
    prep.inflight_by_node = by_node = {}
    prep.inflight_by_id = by_id = {}
    for k, a in enumerate(prep.inflight):
        by_node.setdefault(a.node_id, []).append((k, a))
        by_id[a.id] = (k, a)
    prep.plan_nodes = [_touched(p) for p in plans]

    # The net dicts are mutated in place by concurrent worker syncs;
    # hold the mirror for the composite read — but ONLY for the dense
    # pass and the frame copy: the component walks run lock-free
    # against the frame.
    with mirror.lock:
        if not mirror.sync_net(base):
            return None  # snapshot older than the mirror: scalar truth
        usage = mirror.usage

        # Pass 1: classify every (plan, node) claim; gather the
        # placement-carrying in-fleet ones into flat arrays for ONE
        # dense base-fit pass (usage + reserved + sum-of-placements).
        verdicts: list = [dict() for _ in plans]
        pairs: list = []     # (plan_i, nid, ni, node, placements, removed)
        vec_rows: list = []  # placement resource vectors
        vec_pair: list = []  # pair index per vec row
        frame_ids: set = set()
        touched_nis: set = set()
        for i, plan in enumerate(plans):
            pv = verdicts[i]
            for nid in prep.plan_nodes[i]:
                placements = plan.node_allocation.get(nid)
                removed = {a.id for a in plan.node_update.get(nid, ())}
                frame_ids |= removed
                if not placements:
                    pv[nid] = True  # evict-only claims always fit
                    ni = index_of.get(nid, -1)
                    if ni >= 0:
                        touched_nis.add(ni)
                    continue
                frame_ids.update(a.id for a in placements)
                node = base.node_by_id(nid)
                if node is None or node.status != NODE_STATUS_READY \
                        or node.drain:
                    pv[nid] = False
                    continue
                ni = index_of.get(nid, -1)
                if ni < 0:
                    pv[nid] = None  # not in fleet: exact walk
                    continue
                touched_nis.add(ni)
                removed.update(a.id for a in placements)  # in-place upd
                pair = len(pairs)
                pairs.append((i, nid, ni, node, placements, removed))
                for a in placements:
                    vec_pair.append(pair)
                    vec_rows.append(alloc_vec(a))

        base_used: list = []
        caps: list = []
        dev_args = None
        dev_capres = None
        dev_lease = None
        if pairs:
            if dev_mesh is not None:
                # Residency lease: references to the resident twins for
                # THIS generation, or None — never an upload under the
                # lock.
                dev_lease = mirror.window_lease(dev_mesh)
                dev_capres = statics.sharded.lookup(("capres", dev_mesh))
            if dev_lease is not None and dev_capres is not None:
                # Device engine: only the tiny fold descriptors are
                # built under the lock; the dispatch (and every
                # counted transfer) runs after release.
                dev_args = _window_device_args(
                    plans, prep.plan_nodes, verdicts, pairs, mirror,
                    index_of, frame_ids, plan_comp, alloc_vec)
            else:
                if policy == VERIFY_DEVICE:
                    devinfo = {"dispatched": False,
                               "fallback": "lease-miss"
                               if dev_lease is None else "capres-miss"}
                # Host engine — dense fit inputs over every claim at
                # once: the 4 dims Resources.superset checks, float32
                # like the mirror rows (exact for values < 2^24, i.e.
                # any realistic node).
                ni_arr = np.fromiter((p[2] for p in pairs),
                                     dtype=np.int64, count=len(pairs))
                delta = np.zeros((len(pairs), 4), dtype=np.float32)
                np.add.at(delta, np.asarray(vec_pair, dtype=np.int64),
                          np.asarray(vec_rows, dtype=np.float32)[:, :4])
                used = usage[ni_arr, :4] \
                    + statics.reserved[ni_arr, :4] + delta
                base_used = used.tolist()
                caps = capacity[ni_arr, :4].tolist()

        # The in-flight apply's allocs fold into component overlays, so
        # their frame rows (and nodes) must ride along too.
        for a in prep.inflight:
            frame_ids.add(a.id)
            ni = index_of.get(a.node_id, -1)
            if ni >= 0:
                touched_nis.add(ni)
        prep.frame = _Frame(mirror, frame_ids, touched_nis)

    prep.devfit = None
    if dev_args is not None:
        try:
            base_used, caps, prep.devfit, devinfo = \
                _dispatch_window_fit(dev_mesh, dev_capres, dev_lease,
                                     dev_args, vec_pair, vec_rows,
                                     len(pairs))
        except Exception:
            # Rare (runtime teardown, device OOM): the window still
            # verifies exactly — the caller's per-plan scalar path.
            return None

    prep.verdicts = verdicts
    prep.pairs = pairs
    prep.base_used = base_used
    prep.caps = caps
    pair_of: dict = {}
    for pair, (i, nid, *_rest) in enumerate(pairs):
        pair_of[(i, nid)] = pair
    prep.pair_of = pair_of

    # Pass 2: schedule and walk the components computed up front.
    # Mirror lock released — the walks read only the frame, the base
    # snapshot, and prep.
    if len(comps) > 1:
        # Deadline-aware scheduling: nearest member deadline first
        # (ties by window position), so a near-deadline plan's
        # component is never last in line behind the executor.
        def comp_key(comp):
            deadline = min((plans[i].deadline for i in comp
                            if plans[i].deadline), default=float("inf"))
            return (deadline, comp[0])
        order = sorted(range(len(comps)), key=lambda k: comp_key(comps[k]))
    else:
        order = list(range(len(comps)))

    wall0 = time.perf_counter()
    tasks = [(lambda comp=comps[k]: _walk_component(prep, comp))
             for k in order]
    if executor is not None and len(tasks) > 1 and \
            max(len(c) for c in comps) >= MIN_CONCURRENT_COMPONENT:
        results = executor.run_components(
            tasks, descs=[{"component": k, "plans": len(comps[k]),
                           "eval_ids": [plans[i].eval_id
                                        for i in comps[k]]}
                          for k in order])
    else:
        results = [t() for t in tasks]
    wall = time.perf_counter() - wall0

    slots: list = [None] * len(plans)
    comp_walls: list = []
    comp_t0s: list = []
    accepted_by_plan: list = [None] * len(plans)
    for ordinal, (entries, comp_t0, comp_wall) in enumerate(results):
        comp_walls.append(comp_wall)
        comp_t0s.append(comp_t0)
        for i, outcome, accepted in entries:
            outcome.component = ordinal
            slots[i] = outcome
            accepted_by_plan[i] = accepted
    # Fold every accepted portion into the caller's overlay in eval
    # order — the exact end state sequential application leaves.
    for i in range(len(plans)):
        overlay.upsert_allocs(accepted_by_plan[i])
    info = {
        "components": len(comps),
        "sizes": [len(c) for c in comps],
        "order": order,
        "comp_walls": comp_walls,
        "comp_t0s": comp_t0s,  # perf_counter epoch (span conversion)
        "wall": wall,
        # How much wall the partition saved vs walking the same
        # components serially (1.0 = none; GIL-bound walks cap this).
        "speedup": (sum(comp_walls) / wall) if wall > 0 else 1.0,
        # Device-verify engine record: None when the host engine ran by
        # policy; else dispatch/fallback details for the applier's
        # device_verify_* stats and the applier.verify.device span.
        "device": devinfo,
    }
    return WindowVerdicts(slots, info)


def _walk_component(prep, comp: list) -> tuple:
    """In-order verdict walk of one claim-graph component against its
    own overlay.  Returns ([(plan_index, WindowOutcome, accepted)],
    t0_perf_counter, wall_seconds).  Reads only frozen prep state + the
    base snapshot — safe on an executor thread."""
    from nomad_tpu.server.plan_apply import (
        OptimisticSnapshot,
        _evaluate_node_plan,
        _verify_node_net,
    )

    t0 = time.perf_counter()
    plans = prep.plans
    statics = prep.statics
    inflight_nodes = prep.inflight_nodes
    wm = _WindowState(prep.frame, prep.index_of)
    comp_view: Optional[OptimisticSnapshot] = None
    accepted_log: list = []
    # Device fold verdicts apply only while the walk can PROVE the
    # kernel's optimistic all-accepted prefix held for this component:
    # window-unique alloc ids (seq_ok), no in-flight overlay folded in,
    # and every earlier plan of the component fully accepted.  Any
    # breach downgrades the REST of the component to the host
    # arithmetic — which reads prep.base_used/prep.caps, numbers that
    # are byte-identical under either engine.
    dev = prep.devfit
    dev_clean = dev is not None and dev.seq_ok

    comp_nodes: set = set()
    for i in comp:
        comp_nodes |= prep.plan_nodes[i]
    if prep.inflight:
        # Only the in-flight allocs this component can see: anything on
        # its nodes, or anything its plans replace/evict by id —
        # gathered via the per-window indexes in O(component), folded
        # in the overlay's insertion order (the fold order sequential
        # application used).
        picked: dict = {}
        for nid in comp_nodes:
            for k, a in prep.inflight_by_node.get(nid, ()):
                picked[k] = a
        by_id = prep.inflight_by_id
        for i in comp:
            for aid in _plan_alloc_ids(plans[i]):
                entry = by_id.get(aid)
                if entry is not None:
                    picked[entry[0]] = entry[1]
        for k in sorted(picked):
            wm.fold(picked[k])  # in-flight apply: committed state
        if picked:
            dev_clean = False  # overlay state the kernel never saw

    def view() -> OptimisticSnapshot:
        # Exact-walk punts are rare; the component's OptimisticSnapshot
        # is built lazily on the first one, seeded to the state the
        # shared sequential overlay would hold at this point.
        nonlocal comp_view
        if comp_view is None:
            comp_view = OptimisticSnapshot(prep.base)
            comp_view.upsert_allocs(prep.inflight)
            for accepted in accepted_log:
                comp_view.upsert_allocs(accepted)
        return comp_view

    entries: list = []
    claimed: set = set()
    last = comp[-1]
    for i in comp:
        plan = plans[i]
        pv = prep.verdicts[i]
        nodes = prep.plan_nodes[i]
        fallback = (not nodes.isdisjoint(claimed)) or \
                   (not nodes.isdisjoint(inflight_nodes))
        result = PlanResult(failed_allocs=list(plan.failed_allocs))
        plan_ok = True
        for nid in nodes:
            ok = pv.get(nid, _MISS)
            if ok is None:
                # Vector-ineligible claim: exact walk against the
                # component view (identical to the sequential verdict).
                ok = _evaluate_node_plan(view(), plan, nid)
            elif ok is _MISS:
                pair = prep.pair_of[(i, nid)]
                _i, _nid, ni, node, placements, removed = \
                    prep.pairs[pair]
                if dev_clean:
                    # The kernel's overlay fold IS this arithmetic
                    # (proof obligations met): take its verdict, keep
                    # the exact net checks.
                    ok = bool(dev.fits_seq[pair])
                else:
                    u0, u1, u2, u3 = prep.base_used[pair]
                    d = wm.usage_delta.get(ni)
                    if d is not None:
                        u0 += d[0]
                        u1 += d[1]
                        u2 += d[2]
                        u3 += d[3]
                    for aid in removed:
                        row = wm.alloc_row(aid)
                        if row is not None and row[0] == ni:
                            vec = row[1]
                            u0 -= float(vec[0])
                            u1 -= float(vec[1])
                            u2 -= float(vec[2])
                            u3 -= float(vec[3])
                    c = prep.caps[pair]
                    ok = (u0 <= c[0] and u1 <= c[1] and u2 <= c[2]
                          and u3 <= c[3])
                if ok:
                    # Port collisions + bandwidth: exact, against
                    # frame + component overlay (None punts the node
                    # to the scalar walk).
                    ok = _verify_node_net(wm, statics, node, ni,
                                          placements, removed)
                    if ok is None:
                        ok = _evaluate_node_plan(view(), plan, nid)
            if ok:
                if plan.node_update.get(nid):
                    result.node_update[nid] = plan.node_update[nid]
                if plan.node_allocation.get(nid):
                    result.node_allocation[nid] = \
                        plan.node_allocation[nid]
                continue
            plan_ok = False
            result.refresh_index = prep.refresh_index
            if plan.all_at_once:
                result.node_update = {}
                result.node_allocation = {}
                break
        if not plan_ok:
            # A rejected claim (or an aborted all_at_once plan) means
            # later plans in the component see an overlay the kernel's
            # all-accepted prefix did not model.
            dev_clean = False
        accepted = _accepted_allocs(result)
        accepted_log.append(accepted)
        if comp_view is not None:
            comp_view.upsert_allocs(accepted)
        if i != last:
            for alloc in accepted:
                wm.fold(alloc)
        claimed |= nodes
        entries.append((i, WindowOutcome(result, fallback), accepted))
    return entries, t0, time.perf_counter() - t0
