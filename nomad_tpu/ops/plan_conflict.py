"""Vectorized cross-plan conflict windows for the group-commit applier.

The leader's plan applier is the serialization point of optimistic
concurrency (server/plan_apply.py): under a contended storm it pays one
verify + one commit per plan.  ``evaluate_window`` restructures the
verify side for a whole *window* of pending plans:

  - the per-node resource fit — the numpy-churn hot loop of
    ``_evaluate_plan_vec`` — is computed for every (plan, node) claim in
    the window with a handful of dense array ops against the base
    snapshot's incremental usage mirror (models/fleet.py UsageMirror);
  - order sensitivity is preserved exactly by a *window overlay* over
    the mirror (``_WindowState``): plans are judged in eval order, and
    each plan's accepted portion is folded into the overlay before the
    next plan's verdicts — so plan i's claims are checked against
    committed state plus every earlier non-conflicting claim in the
    window, exactly the state sequential application would have reached;
  - claims the incremental path cannot serve (node not in the fleet,
    odd network topology) punt to the exact scalar walk against an
    OptimisticSnapshot carrying the same folds, exactly as the per-plan
    verifier punts them.

A plan whose claims overlap an earlier plan in the window (the
order-sensitive prefix conflict) is reported as a ``fallback`` — its
verdicts rode the window overlay rather than the clean dense pass — and
counted by the applier's ``conflict_fallbacks`` stat.

Results are identical to calling ``evaluate_plan`` per plan in eval
order with the accepted portion of each plan folded into the view before
the next — the property the group-commit parity test
(tests/test_plan_batch.py) locks down.
"""
from __future__ import annotations

import time
from typing import Optional

import numpy as np

from nomad_tpu.structs import PlanResult

from nomad_tpu.utils.metrics import metrics

_MISS = object()


class WindowOutcome:
    """One plan's verdict within a window."""

    __slots__ = ("result", "fallback")

    def __init__(self, result: PlanResult, fallback: bool) -> None:
        self.result = result
        # True when this plan's claims overlapped an earlier plan in the
        # window (or an in-flight apply) — the order-sensitive prefix
        # conflict: its verdicts came from the window overlay, not the
        # clean dense pass.
        self.fallback = fallback


class _OverGet:
    """dict-shaped ``.get`` view: window overrides chained over the base
    mirror's dict.  An override of None is a tombstone (entry removed
    within the window)."""

    __slots__ = ("over", "base")

    def __init__(self, over: dict, base: dict) -> None:
        self.over = over
        self.base = base

    def get(self, key, default=None):
        v = self.over.get(key, _MISS)
        if v is _MISS:
            return self.base.get(key, default)
        return default if v is None else v


class _DupGet:
    """``node_dup``-shaped view: duplicate-port counts recomputed from
    the window's materialized per-node port dicts, base passthrough for
    untouched nodes.  Port dicts are tens of entries, so the recompute
    is cheaper than incremental bookkeeping is error-prone."""

    __slots__ = ("ports", "base")

    def __init__(self, ports: dict, base: dict) -> None:
        self.ports = ports
        self.base = base

    def get(self, ni, default=None):
        pc = self.ports.get(ni)
        if pc is None:
            return self.base.get(ni, default)
        dup = sum(1 for c in pc.values() if c > 1)
        return dup if dup else default


class _WindowState:
    """Window overlay over a SYNCED UsageMirror: base state plus the
    accepted portions of earlier plans in the window (and any in-flight
    apply overlay), exposing exactly the reads the verifier needs —
    the same ``net_rows/node_ports/node_dup/node_bw/node_net_keys``
    surface ``plan_apply._verify_node_net`` consumes, plus per-node
    4-dim usage deltas for the fit check.  Never mutates the mirror:
    per-node dicts are copied on first window write.

    Caller holds the mirror lock for the lifetime of this object."""

    def __init__(self, mirror, statics) -> None:
        from nomad_tpu.models.fleet import _net_row, alloc_vec

        self._net_row = _net_row
        self._alloc_vec = alloc_vec
        self.m = mirror
        self.index_of = statics.index_of
        self.usage_delta: dict = {}   # ni -> [f, f, f, f]
        self._rows: dict = {}         # aid -> (ni, vec) | None
        self._net_over: dict = {}     # aid -> net row | None
        self._ports: dict = {}        # ni -> merged {port: count}
        self._bw: dict = {}           # ni -> merged mbits
        self._keys: dict = {}         # ni -> merged {(ip, dev): count}
        # The verifier-facing surface:
        self.net_rows = _OverGet(self._net_over, mirror.net_rows)
        self.node_ports = _OverGet(self._ports, mirror.node_ports)
        self.node_bw = _OverGet(self._bw, mirror.node_bw)
        self.node_net_keys = _OverGet(self._keys, mirror.node_net_keys)
        self.node_dup = _DupGet(self._ports, mirror.node_dup)

    # -- removal accounting (the caller's removed_ids walk) ---------------
    def alloc_row(self, aid):
        """(ni, vec) of a live alloc — window override first, then the
        mirror — or None when absent/removed."""
        v = self._rows.get(aid, _MISS)
        if v is not _MISS:
            return v
        row = self.m.alloc_rows.get(aid)
        return None if row is None else (row[0], row[1])

    # -- copy-on-write materialization ------------------------------------
    def _ports_for(self, ni) -> dict:
        pc = self._ports.get(ni)
        if pc is None:
            pc = self._ports[ni] = dict(self.m.node_ports.get(ni, ()))
        return pc

    def _keys_for(self, ni) -> dict:
        keys = self._keys.get(ni)
        if keys is None:
            keys = self._keys[ni] = dict(
                self.m.node_net_keys.get(ni, ()))
        return keys

    def _bw_add(self, ni, mbits) -> None:
        self._bw[ni] = self.node_bw.get(ni, 0) + mbits

    # -- folds -------------------------------------------------------------
    def fold(self, alloc) -> None:
        """Apply one accepted alloc (placement or eviction) to the
        window overlay — the same old-row-out/new-row-in transition the
        mirror's own delta sync performs on commit."""
        aid = alloc.id
        old = self.alloc_row(aid)
        if old is not None:
            ni0, vec0 = old
            d = self.usage_delta.setdefault(ni0, [0.0] * 4)
            d[0] -= float(vec0[0])
            d[1] -= float(vec0[1])
            d[2] -= float(vec0[2])
            d[3] -= float(vec0[3])
        self._rows[aid] = None
        nr = self.net_rows.get(aid)
        if nr is not None:
            ni0, ports, mbits, key = nr
            if mbits:
                self._bw_add(ni0, -mbits)
            keys = self._keys_for(ni0)
            c = keys.get(key, 0) - 1
            if c > 0:
                keys[key] = c
            else:
                keys.pop(key, None)
            if ports:
                pc = self._ports_for(ni0)
                for p in ports:
                    c = pc.get(p, 0) - 1
                    if c > 0:
                        pc[p] = c
                    else:
                        pc.pop(p, None)
        self._net_over[aid] = None

        if alloc.terminal_status():
            return
        ni = self.index_of.get(alloc.node_id, -1)
        if ni < 0:
            return
        vec = self._alloc_vec(alloc)
        self._rows[aid] = (ni, vec)
        d = self.usage_delta.setdefault(ni, [0.0] * 4)
        d[0] += float(vec[0])
        d[1] += float(vec[1])
        d[2] += float(vec[2])
        d[3] += float(vec[3])
        row = self._net_row(alloc)
        if row is not None:
            ports, mbits, key = row
            self._net_over[aid] = (ni, ports, mbits, key)
            if mbits:
                self._bw_add(ni, mbits)
            keys = self._keys_for(ni)
            keys[key] = keys.get(key, 0) + 1
            if ports:
                pc = self._ports_for(ni)
                for p in ports:
                    pc[p] = pc.get(p, 0) + 1


def _touched(plan) -> set:
    return set(plan.node_update) | set(plan.node_allocation)


def _accepted_allocs(result) -> list:
    allocs = []
    for updates in result.node_update.values():
        allocs.extend(updates)
    for placements in result.node_allocation.values():
        allocs.extend(placements)
    allocs.extend(result.failed_allocs)
    return allocs


def evaluate_window(snap, plans: list) -> list:
    """Verify a window of plans in eval order; returns one WindowOutcome
    per plan, results identical to sequential ``evaluate_plan`` +
    fold-into-overlay per plan.

    ``snap`` may be an OptimisticSnapshot carrying an in-flight apply's
    overlay; it is MUTATED — each plan's accepted portion is folded in so
    the caller's overlay ends up exactly as sequential application would
    leave it.
    """
    from nomad_tpu.server.plan_apply import (
        OptimisticSnapshot,
        evaluate_plan,
    )

    overlay = snap if isinstance(snap, OptimisticSnapshot) \
        else OptimisticSnapshot(snap)
    if len(plans) == 1:
        # No cross-plan structure to exploit: the per-plan path already
        # carries its own vectorized fit (plan_apply._evaluate_plan_vec).
        # Same fallback definition as the window paths — overlap with
        # the in-flight apply's overlay counts.
        fallback = bool(_touched(plans[0])
                        & {n for n in overlay._by_node if n})
        result = evaluate_plan(snap, plans[0])
        if overlay is snap:
            # Only a caller-owned overlay needs the fold; a throwaway
            # one built here is dead work.
            overlay.upsert_allocs(_accepted_allocs(result))
        return [WindowOutcome(result, fallback)]

    start = time.perf_counter()
    outcomes = _evaluate_window_vec(overlay, plans)
    if outcomes is None:
        # No incremental mirror for this snapshot: per-plan exact path
        # against the running overlay, still in eval order.
        outcomes = []
        dirty: set = {n for n in overlay._by_node if n}
        for plan in plans:
            nodes = _touched(plan)
            result = evaluate_plan(overlay, plan)
            outcomes.append(WindowOutcome(result, bool(nodes & dirty)))
            overlay.upsert_allocs(_accepted_allocs(result))
            # Same fallback definition as the vec path's `claimed`:
            # every node an earlier plan TOUCHED (accepted or not), so
            # the stat means one thing regardless of which path ran.
            dirty |= nodes
    metrics.measure_since("nomad.plan.evaluate_window", start)
    return outcomes


def _evaluate_window_vec(overlay, plans: list) -> Optional[list]:
    """The vectorized window pass: dense base fit for every claim, then
    an in-order verdict walk against the window overlay.  Returns None
    when the snapshot cannot take the incremental path at all."""
    from nomad_tpu.models.fleet import alloc_vec, fleet_cache, mirror_for
    from nomad_tpu.server.plan_apply import (
        _evaluate_node_plan,
        _verify_node_net,
    )
    from nomad_tpu.structs import NODE_STATUS_READY

    base = overlay.base
    if getattr(base, "_t", None) is None:
        return None
    if not any(any(p.node_allocation.values()) for p in plans):
        # Evict/update-only window: every per-node verdict is True by
        # definition; don't spin up the mirror's net tracking for it.
        # The fallback stat keeps the uniform definition (claims
        # overlapping an earlier plan's touched nodes) even though the
        # verdicts here are state-independent.
        outcomes = []
        claimed = {n for n in overlay._by_node if n}
        for plan in plans:
            nodes = _touched(plan)
            result = PlanResult(
                node_update={k: v for k, v in plan.node_update.items()
                             if v},
                node_allocation={k: v for k, v
                                 in plan.node_allocation.items() if v},
                failed_allocs=list(plan.failed_allocs))
            outcomes.append(WindowOutcome(result, bool(nodes & claimed)))
            overlay.upsert_allocs(_accepted_allocs(result))
            claimed |= nodes
        return outcomes

    statics = fleet_cache.statics_for(base)
    mirror = mirror_for(statics)
    capacity = statics.capacity
    index_of = statics.index_of

    # The net dicts are mutated in place by concurrent worker syncs;
    # hold the mirror for the whole composite read (same discipline as
    # the per-plan vector pass).
    with mirror.lock:
        if not mirror.sync_net(base):
            return None  # snapshot older than the mirror: scalar truth
        usage = mirror.usage

        # Pass 1: classify every (plan, node) claim; gather the
        # placement-carrying in-fleet ones into flat arrays for ONE
        # dense base-fit pass (usage + reserved + sum-of-placements).
        verdicts: list = [dict() for _ in plans]
        pairs: list = []     # (plan_i, nid, ni, node, placements, removed)
        vec_rows: list = []  # placement resource vectors
        vec_pair: list = []  # pair index per vec row
        for i, plan in enumerate(plans):
            pv = verdicts[i]
            for nid in _touched(plan):
                placements = plan.node_allocation.get(nid)
                if not placements:
                    pv[nid] = True  # evict-only claims always fit
                    continue
                node = base.node_by_id(nid)
                if node is None or node.status != NODE_STATUS_READY \
                        or node.drain:
                    pv[nid] = False
                    continue
                ni = index_of.get(nid, -1)
                if ni < 0:
                    pv[nid] = None  # not in fleet: exact walk
                    continue
                removed = {a.id for a in plan.node_update.get(nid, ())}
                removed.update(a.id for a in placements)  # in-place upd
                pair = len(pairs)
                pairs.append((i, nid, ni, node, placements, removed))
                for a in placements:
                    vec_pair.append(pair)
                    vec_rows.append(alloc_vec(a))

        base_used: list = []
        caps: list = []
        if pairs:
            # Dense fit inputs over every claim at once: the 4 dims
            # Resources.superset checks, float32 like the mirror rows
            # (exact for values < 2^24, i.e. any realistic node).
            ni_arr = np.fromiter((p[2] for p in pairs), dtype=np.int64,
                                 count=len(pairs))
            delta = np.zeros((len(pairs), 4), dtype=np.float32)
            np.add.at(delta, np.asarray(vec_pair, dtype=np.int64),
                      np.asarray(vec_rows, dtype=np.float32)[:, :4])
            used = usage[ni_arr, :4] + statics.reserved[ni_arr, :4] \
                + delta
            base_used = used.tolist()
            caps = capacity[ni_arr, :4].tolist()

        # Pass 2: verdicts in eval order against the window overlay.
        wm = _WindowState(mirror, statics)
        for alloc in overlay._overlay.values():
            wm.fold(alloc)  # in-flight apply: part of "committed" state
        pair_of: dict = {}
        for pair, (i, nid, *_rest) in enumerate(pairs):
            pair_of[(i, nid)] = pair

        outcomes: list = []
        claimed: set = {n for n in overlay._by_node if n}
        for i, plan in enumerate(plans):
            pv = verdicts[i]
            nodes = _touched(plan)
            fallback = bool(nodes & claimed)
            result = PlanResult(failed_allocs=list(plan.failed_allocs))
            for nid in nodes:
                ok = pv.get(nid, _MISS)
                if ok is None:
                    # Vector-ineligible claim: exact walk against the
                    # overlay (identical to the sequential verdict).
                    ok = _evaluate_node_plan(overlay, plan, nid)
                elif ok is _MISS:
                    pair = pair_of[(i, nid)]
                    _i, _nid, ni, node, placements, removed = pairs[pair]
                    u0, u1, u2, u3 = base_used[pair]
                    d = wm.usage_delta.get(ni)
                    if d is not None:
                        u0 += d[0]
                        u1 += d[1]
                        u2 += d[2]
                        u3 += d[3]
                    for aid in removed:
                        row = wm.alloc_row(aid)
                        if row is not None and row[0] == ni:
                            vec = row[1]
                            u0 -= float(vec[0])
                            u1 -= float(vec[1])
                            u2 -= float(vec[2])
                            u3 -= float(vec[3])
                    c = caps[pair]
                    if not (u0 <= c[0] and u1 <= c[1] and u2 <= c[2]
                            and u3 <= c[3]):
                        ok = False
                    else:
                        # Port collisions + bandwidth: exact, against
                        # base + window overlay (None punts the node to
                        # the scalar walk).
                        ok = _verify_node_net(wm, statics, node, ni,
                                              placements, removed)
                        if ok is None:
                            ok = _evaluate_node_plan(overlay, plan, nid)
                if ok:
                    if plan.node_update.get(nid):
                        result.node_update[nid] = plan.node_update[nid]
                    if plan.node_allocation.get(nid):
                        result.node_allocation[nid] = \
                            plan.node_allocation[nid]
                    continue
                result.refresh_index = max(overlay.get_index("nodes"),
                                           overlay.get_index("allocs"))
                if plan.all_at_once:
                    result.node_update = {}
                    result.node_allocation = {}
                    break
            outcomes.append(WindowOutcome(result, fallback))
            accepted = _accepted_allocs(result)
            overlay.upsert_allocs(accepted)
            for alloc in accepted:
                wm.fold(alloc)
            claimed |= nodes
    return outcomes
