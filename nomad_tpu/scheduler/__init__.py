"""Scheduler layer: pure placement logic behind the State/Planner seams.

Registry carries service/batch/system (sequential, parity-faithful) plus the
TPU-native jax-binpack backend (registered lazily to keep JAX import optional
for host-only use).
"""
from .interfaces import (  # noqa: F401
    BUILTIN_SCHEDULERS,
    Factory,
    Planner,
    Scheduler,
    SetStatusError,
    State,
    new_scheduler,
    register_scheduler,
)
from .context import EvalContext  # noqa: F401
from .generic import (  # noqa: F401
    GenericScheduler,
    new_batch_scheduler,
    new_service_scheduler,
)
from .system import SystemScheduler, new_system_scheduler  # noqa: F401
from .harness import Harness, RejectPlan  # noqa: F401
from .stack import GenericStack, SystemStack  # noqa: F401

register_scheduler("service", new_service_scheduler)
register_scheduler("batch", new_batch_scheduler)
register_scheduler("system", new_system_scheduler)
# The sequential iterator-chain system scheduler stays addressable for
# golden-parity tests; "system" is rebound to the vectorized one below
# when the array stack imports.
register_scheduler("system-seq", new_system_scheduler)


def _register_jax() -> None:
    try:
        from .jax_binpack import (
            new_jax_binpack_batch_scheduler,
            new_jax_binpack_scheduler,
        )
        from .system_vec import new_vector_system_scheduler
    except ImportError:  # pragma: no cover - jax always present in CI
        return
    register_scheduler("jax-binpack", new_jax_binpack_scheduler)
    register_scheduler("jax-binpack-batch", new_jax_binpack_batch_scheduler)
    register_scheduler("system", new_vector_system_scheduler)
    global BatchEvalRunner
    from .batch import BatchEvalRunner  # noqa: F401


try:
    import jax  # noqa: F401
    _HAS_JAX = True
except Exception:  # pragma: no cover
    _HAS_JAX = False

if _HAS_JAX:
    try:
        _register_jax()
    except Exception:  # pragma: no cover - keep host plane importable
        pass


def device_available() -> bool:
    """One-time probe: can the JAX backend actually hand out devices?

    Importing jax succeeding does not mean the backend initialises (e.g. a
    plugin platform selected via JAX_PLATFORMS whose plugin isn't on the
    path).  Without this probe a broken device plane would fail every
    device-scheduled eval into the delivery-limit reaper; with it the
    server degrades to the sequential schedulers at startup.
    """
    if not _HAS_JAX:
        return False
    try:
        from nomad_tpu.parallel.devices import default_platform_devices
        return bool(default_platform_devices())
    except Exception:
        return False
