"""Placement stacks: composed iterator chains.

Capability parity with /root/reference/scheduler/stack.go.  Generic =
random source -> job constraints -> drivers -> task-group constraints ->
bin-pack -> job anti-affinity -> limit(max(2, ceil(log2 N))) -> max-score;
System = static source -> constraints -> bin-pack, first fit.

The TPU jax-binpack scheduler replaces `select` with a device dispatch but
keeps this exact pipeline semantics (see nomad_tpu/scheduler/jax_binpack.py).
"""
from __future__ import annotations

import math
import time
from typing import Optional

from nomad_tpu.structs import (
    CONSTRAINT_DISTINCT_HOSTS,
    Constraint,
    Job,
    Resources,
    TaskGroup,
)

from .context import EvalContext
from .feasible import ConstraintIterator, DriverIterator, StaticIterator, \
    new_random_iterator
from .rank import BinPackIterator, FeasibleRankIterator, \
    JobAntiAffinityIterator, RankedNode
from .select import LimitIterator, MaxScoreIterator
from .util import task_group_constraints

SERVICE_JOB_ANTI_AFFINITY_PENALTY = 10.0
BATCH_JOB_ANTI_AFFINITY_PENALTY = 5.0


def _bind_distinct_hosts(constraints: list, job_id: str) -> list:
    """Attach the job id to distinct_hosts constraints so the feasibility
    check can count proposed same-job allocs per node."""
    out = []
    for c in constraints:
        if c.operand == CONSTRAINT_DISTINCT_HOSTS and not c.r_target:
            c = c.copy()
            c.r_target = job_id
        out.append(c)
    return out


class GenericStack:
    """Stack for service/batch placements (quality over speed)."""

    def __init__(self, batch: bool, ctx: EvalContext, rng=None) -> None:
        self.batch = batch
        self.ctx = ctx
        self.rng = rng
        self.job_id = ""

        self.source = StaticIterator(ctx, [])
        self.job_constraint = ConstraintIterator(ctx, self.source)
        self.task_group_drivers = DriverIterator(ctx, self.job_constraint)
        self.task_group_constraint = ConstraintIterator(
            ctx, self.task_group_drivers)
        rank_source = FeasibleRankIterator(ctx, self.task_group_constraint)
        self.bin_pack = BinPackIterator(ctx, rank_source, evict=not batch,
                                        priority=0)
        penalty = BATCH_JOB_ANTI_AFFINITY_PENALTY if batch else \
            SERVICE_JOB_ANTI_AFFINITY_PENALTY
        self.job_anti_aff = JobAntiAffinityIterator(ctx, self.bin_pack,
                                                    penalty, "")
        self.limit = LimitIterator(ctx, self.job_anti_aff, 2)
        self.max_score = MaxScoreIterator(ctx, self.limit)

    def set_nodes(self, base_nodes: list) -> None:
        from .util import shuffle_nodes

        shuffle_nodes(base_nodes, self.rng)
        self.source.set_nodes(base_nodes)

        # Visit "enough": log2(N) candidates for service, 2 for batch
        # (power-of-two-choices).
        limit = 2
        n = len(base_nodes)
        if not self.batch and n > 0:
            limit = max(limit, math.ceil(math.log2(n)))
        self.limit.set_limit(limit)

    def set_job(self, job: Job) -> None:
        self.job_id = job.id
        self.job_constraint.set_constraints(
            _bind_distinct_hosts(job.constraints, job.id))
        self.bin_pack.set_priority(job.priority)
        self.job_anti_aff.set_job(job.id)

    def select(self, tg: TaskGroup) -> tuple[Optional[RankedNode], Resources]:
        self.max_score.reset()
        self.ctx.reset()
        start = time.perf_counter()

        tg_constr = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(tg_constr.drivers)
        self.task_group_constraint.set_constraints(
            _bind_distinct_hosts(tg_constr.constraints, self.job_id))
        self.bin_pack.set_tasks(tg.tasks)

        option = self.max_score.next()

        if option is not None and \
                len(option.task_resources) != len(tg.tasks):
            for task in tg.tasks:
                option.set_task_resources(task, task.resources)

        self.ctx.metrics().allocation_time = time.perf_counter() - start
        return option, tg_constr.size


class SystemStack:
    """Stack for system placements: all nodes, first fit."""

    def __init__(self, ctx: EvalContext) -> None:
        self.ctx = ctx
        self.job_id = ""
        self.source = StaticIterator(ctx, [])
        self.job_constraint = ConstraintIterator(ctx, self.source)
        self.task_group_drivers = DriverIterator(ctx, self.job_constraint)
        self.task_group_constraint = ConstraintIterator(
            ctx, self.task_group_drivers)
        rank_source = FeasibleRankIterator(ctx, self.task_group_constraint)
        self.bin_pack = BinPackIterator(ctx, rank_source, evict=True,
                                        priority=0)

    def set_nodes(self, base_nodes: list) -> None:
        self.source.set_nodes(base_nodes)

    def set_job(self, job: Job) -> None:
        self.job_id = job.id
        self.job_constraint.set_constraints(
            _bind_distinct_hosts(job.constraints, job.id))
        self.bin_pack.set_priority(job.priority)

    def select(self, tg: TaskGroup) -> tuple[Optional[RankedNode], Resources]:
        self.bin_pack.reset()
        self.ctx.reset()
        start = time.perf_counter()

        tg_constr = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(tg_constr.drivers)
        self.task_group_constraint.set_constraints(
            _bind_distinct_hosts(tg_constr.constraints, self.job_id))
        self.bin_pack.set_tasks(tg.tasks)

        option = self.bin_pack.next()

        if option is not None and \
                len(option.task_resources) != len(tg.tasks):
            for task in tg.tasks:
                option.set_task_resources(task, task.resources)

        self.ctx.metrics().allocation_time = time.perf_counter() - start
        return option, tg_constr.size
