"""Scheduler utilities: diffing, materialization, update helpers.

Capability parity with /root/reference/scheduler/util.go.
"""
from __future__ import annotations

import random
import threading
import weakref
from dataclasses import dataclass, field
from types import MappingProxyType
from typing import Callable, Mapping, Optional

from nomad_tpu.structs import (
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    ALLOC_CLIENT_STATUS_PENDING,
    EVAL_STATUS_FAILED,
    NODE_STATUS_READY,
    Allocation,
    Constraint,
    Evaluation,
    Job,
    Node,
    Resources,
    TaskGroup,
    should_drain_node,
)
from nomad_tpu.structs.model import proto_of

from .interfaces import SetStatusError

ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_IN_PLACE = "alloc updating in-place"


@dataclass
class AllocTuple:
    name: str = ""
    task_group: Optional[TaskGroup] = None
    alloc: Optional[Allocation] = None


@dataclass
class DiffResult:
    place: list = field(default_factory=list)
    update: list = field(default_factory=list)
    migrate: list = field(default_factory=list)
    stop: list = field(default_factory=list)
    ignore: list = field(default_factory=list)

    def append(self, other: "DiffResult") -> None:
        self.place += other.place
        self.update += other.update
        self.migrate += other.migrate
        self.stop += other.stop
        self.ignore += other.ignore


def materialize_task_groups(job: Optional[Job]) -> Mapping:
    """Count-expand task groups to named instances job.tg[i].

    Returns a READ-ONLY Mapping (MappingProxyType), memoized per
    (job object, modify_index): store-resident jobs are immutable by
    contract and every store write copies, so re-evals of the same job
    version (node-update storms re-evaluate every affected job) reuse
    the expansion.  The proxy also makes the shared cache
    mutation-proof — callers needing a private mutable copy must
    dict() it.  Identity-stable per job version, which the fresh-diff
    caches key on (diff_allocs cache_fresh)."""
    if job is None:
        return {}
    cached = job.__dict__.get("_materialized")
    if cached is not None and cached[0] == job.modify_index:
        return cached[1]
    out: dict = {}
    for tg in job.task_groups:
        for i in range(tg.count):
            out[f"{job.name}.{tg.name}[{i}]"] = tg
    # Read-only view: a caller mutation would otherwise poison every
    # later eval of this job version through the shared cache.
    view = MappingProxyType(out)
    job.__dict__["_materialized"] = (job.modify_index, view)
    return view


def diff_allocs(job: Optional[Job], tainted_nodes: dict, required: dict,
                allocs: list, cache_fresh: bool = False) -> DiffResult:
    """Set-difference target vs existing allocs into five outcome buckets.

    ``cache_fresh=True`` (generic scheduler only): when there are no
    existing allocs the diff is pure placement and deterministic per job
    version, so the AllocTuple list is memoized on the job object (store
    jobs are immutable; re-evals of the same version — eval storms,
    plan-retry attempts — reuse it).  The cached tuples are shared and
    READ-ONLY; diff_system_allocs must not use this path (it stamps
    per-node targets onto its place tuples)."""
    if cache_fresh and not allocs and job is not None:
        cached = job.__dict__.get("_fresh_place")
        if cached is not None and cached[0] == job.modify_index \
                and cached[1] is required:
            place = cached[2]
        else:
            # A TUPLE, so any future caller that tries to mutate the
            # shared diff (evict_and_place appends, truncation) fails
            # loudly instead of silently poisoning the per-version cache.
            # Mutating paths require existing allocs and never take this
            # branch.
            place = tuple(AllocTuple(name, tg)
                          for name, tg in required.items())
            job.__dict__["_fresh_place"] = (job.modify_index, required,
                                            place)
        result = DiffResult()
        result.place = place
        return result
    result = DiffResult()
    existing = set()
    for exist in allocs:
        name = exist.name
        existing.add(name)
        tg = required.get(name)
        if tg is None:
            result.stop.append(AllocTuple(name, tg, exist))
            continue
        if tainted_nodes.get(exist.node_id):
            result.migrate.append(AllocTuple(name, tg, exist))
            continue
        if job is not None and exist.job is not None and \
                job.modify_index != exist.job.modify_index:
            result.update.append(AllocTuple(name, tg, exist))
            continue
        result.ignore.append(AllocTuple(name, tg, exist))

    for name, tg in required.items():
        if name not in existing:
            result.place.append(AllocTuple(name, tg))
    return result


_ALLOC_STUB_STATIC, _ALLOC_STUB_FACTORIES = proto_of(Allocation)


def _node_alloc_stub(node_id: str) -> Allocation:
    """Template-built Allocation carrying only a target node (the marker
    diff_system_allocs pins placements with) — ``__new__`` + dict copy,
    ~3x cheaper than the generated ``__init__`` at 1k nodes/eval."""
    a = Allocation.__new__(Allocation)
    d = dict(_ALLOC_STUB_STATIC, node_id=node_id)
    for name, fac in _ALLOC_STUB_FACTORIES:
        d[name] = fac()
    a.__dict__ = d
    return a


def diff_system_allocs(job: Job, nodes: list, tainted_nodes: dict,
                       allocs: list) -> DiffResult:
    """Per-node diff for system jobs; place tuples carry the target node.

    Flat single-pass form of "run diff_allocs once per node": same
    buckets in the same (node-major, first-encounter) order, without one
    DiffResult + AllocTuple churn per node — at 1k nodes the per-node
    objects dominated the whole system eval.  Migrations don't apply to
    system jobs: a tainted node's allocs just stop."""
    required = materialize_task_groups(job)
    result = DiffResult()
    place, stop = result.place, result.stop
    update, ignore = result.update, result.ignore

    # Node order: alloc-bearing nodes in first-encounter order, then the
    # remaining provided nodes (dict-insertion semantics of the previous
    # per-node implementation, preserved so rolling-update limits truncate
    # the same allocs).
    allocs_by_node: dict = {}
    order: list = []
    for alloc in allocs:
        lst = allocs_by_node.get(alloc.node_id)
        if lst is None:
            allocs_by_node[alloc.node_id] = lst = []
            order.append(alloc.node_id)
        lst.append(alloc)
    for node in nodes:
        if node.id not in allocs_by_node:
            allocs_by_node[node.id] = []
            order.append(node.id)

    required_items = list(required.items())
    job_mi = job.modify_index if job is not None else None
    for node_id in order:
        nallocs = allocs_by_node[node_id]
        if not nallocs:
            # Fresh node: everything required is missing.
            for name, tg in required_items:
                place.append(AllocTuple(name, tg,
                                        _node_alloc_stub(node_id)))
            continue
        existing = set()
        tainted = tainted_nodes.get(node_id)
        for alloc in nallocs:
            name = alloc.name
            existing.add(name)
            tg = required.get(name)
            if tg is None or tainted:
                stop.append(AllocTuple(name, tg, alloc))
            elif job_mi is not None and alloc.job is not None and \
                    job_mi != alloc.job.modify_index:
                update.append(AllocTuple(name, tg, alloc))
            else:
                ignore.append(AllocTuple(name, tg, alloc))
        for name, tg in required_items:
            if name not in existing:
                place.append(AllocTuple(name, tg,
                                        _node_alloc_stub(node_id)))
    return result


# Ready-set memo: the scan below is O(fleet) and runs once per eval; its
# result only changes when the nodes table changes.  Keyed PER LINEAGE
# in a WeakKeyDictionary — lineage is identity-preserved across
# snapshots/clones and replaced wholesale by snapshot restore, so a dead
# world's entries free themselves when its store drops the token, while
# several live stores in one process (test rigs, multi-server dev
# agents) each keep their own bounded sub-cache.  Any node write bumps
# the nodes index, so a hit is always current.  Callers get a fresh
# list (they shuffle in place).  Locked: workers call this concurrently.
_READY_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_READY_CACHE_MAX = 16  # per lineage
_READY_CACHE_LOCK = threading.Lock()


def ready_nodes_in_dcs(state, datacenters: list) -> list:
    tables = getattr(state, "_t", None)
    key = sub = None
    if tables is not None:
        key = (tables.indexes["nodes"], tuple(sorted(datacenters)))
        with _READY_CACHE_LOCK:
            sub = _READY_CACHE.get(tables.lineage)
            hit = sub.get(key) if sub is not None else None
            if hit is not None:
                return list(hit)
    dc_set = set(datacenters)
    out = []
    for node in state.nodes():
        if node.status != NODE_STATUS_READY:
            continue
        if node.drain:
            continue
        if node.datacenter not in dc_set:
            continue
        out.append(node)
    if key is not None:
        with _READY_CACHE_LOCK:
            sub = _READY_CACHE.get(tables.lineage)
            if sub is None:
                sub = _READY_CACHE[tables.lineage] = {}
            while len(sub) >= _READY_CACHE_MAX:
                sub.pop(next(iter(sub)), None)
            sub[key] = out
        return list(out)
    return out


def retry_max(max_attempts: int, cb: Callable[[], bool]) -> None:
    """Run cb until it returns True; raise SetStatusError past the limit."""
    for _ in range(max_attempts):
        if cb():
            return
    raise SetStatusError(
        f"maximum attempts reached ({max_attempts})", EVAL_STATUS_FAILED)


def tainted_nodes(state, allocs: list) -> dict:
    """node_id -> must-migrate for every node carrying one of the allocs."""
    out: dict = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = state.node_by_id(alloc.node_id)
        if node is None:
            out[alloc.node_id] = True
            continue
        out[alloc.node_id] = should_drain_node(node.status) or node.drain
    return out


def shuffle_nodes(nodes: list, rng=None) -> None:
    (rng or random).shuffle(nodes)


def tasks_updated(a: TaskGroup, b: TaskGroup) -> bool:
    """Do two task groups differ in a way that forbids in-place update?"""
    if len(a.tasks) != len(b.tasks):
        return True
    for at in a.tasks:
        bt = b.lookup_task(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver:
            return True
        if at.config != bt.config:
            return True
        if len(at.resources.networks) != len(bt.resources.networks):
            return True
        for an, bn in zip(at.resources.networks, bt.resources.networks):
            if len(an.dynamic_ports) != len(bn.dynamic_ports):
                return True
    return False


def set_status(planner, ev: Evaluation, next_eval: Optional[Evaluation],
               status: str, description: str = "") -> None:
    new_eval = ev.copy()
    new_eval.status = status
    new_eval.status_description = description
    if next_eval is not None:
        new_eval.next_eval = next_eval.id
    planner.update_eval(new_eval)


def inplace_update(ctx, ev: Evaluation, job: Job, stack,
                   updates: list) -> list:
    """Try to update allocs in place: speculatively evict, re-select on the
    same node, pop the eviction.  Returns the updates that still need a
    destructive (evict + place) path."""
    remaining = []
    inplace = 0
    for update in updates:
        existing_tg = update.alloc.job.lookup_task_group(
            update.task_group.name) if update.alloc.job else None
        if existing_tg is None or tasks_updated(update.task_group, existing_tg):
            remaining.append(update)
            continue

        node = ctx.state().node_by_id(update.alloc.node_id)
        if node is None:
            remaining.append(update)
            continue

        stack.set_nodes([node])
        # Stage an eviction so current usage is discounted during selection.
        ctx.plan().append_update(update.alloc, ALLOC_DESIRED_STATUS_STOP,
                                ALLOC_IN_PLACE)
        option, size = stack.select(update.task_group)
        ctx.plan().pop_update(update.alloc)

        if option is None:
            remaining.append(update)
            continue

        # Network assignments are immutable across in-place updates.
        for task_name, resources in option.task_resources.items():
            existing_res = update.alloc.task_resources.get(task_name)
            if existing_res is not None:
                resources.networks = existing_res.networks

        new_alloc = update.alloc.copy()
        new_alloc.eval_id = ev.id
        new_alloc.job = job
        new_alloc.resources = size
        new_alloc.task_resources = option.task_resources
        new_alloc.metrics = ctx.metrics()
        new_alloc.desired_status = ALLOC_DESIRED_STATUS_RUN
        new_alloc.desired_description = ""
        new_alloc.client_status = ALLOC_CLIENT_STATUS_PENDING
        ctx.plan().append_alloc(new_alloc)
        inplace += 1
    return remaining


def evict_and_place(ctx, diff: DiffResult, allocs: list, desc: str,
                    limit: list) -> bool:
    """Evict up to limit[0] allocs and queue replacements; True if limited.

    limit is a single-element list to emulate the reference's by-pointer
    rolling-update budget shared across migrate + update passes.
    """
    n = len(allocs)
    for i in range(min(n, limit[0])):
        a = allocs[i]
        ctx.plan().append_update(a.alloc, ALLOC_DESIRED_STATUS_STOP, desc)
        diff.place.append(a)
    if n <= limit[0]:
        limit[0] -= n
        return False
    limit[0] = 0
    return True


@dataclass
class TGConstraintTuple:
    constraints: list = field(default_factory=list)
    drivers: set = field(default_factory=set)
    size: Resources = field(default_factory=Resources)


def task_group_constraints(tg: TaskGroup) -> TGConstraintTuple:
    """Aggregate a task group's constraints, drivers and total resources."""
    c = TGConstraintTuple()
    c.constraints += tg.constraints
    for task in tg.tasks:
        c.drivers.add(task.driver)
        c.constraints += task.constraints
        c.size.add(task.resources)
    return c
