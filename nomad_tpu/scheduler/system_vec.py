"""Vectorized system scheduler: node-pinned placement without the
per-node iterator walk.

Capability parity with /root/reference/scheduler/system_sched.go via the
same reconcile logic as the sequential SystemScheduler (diff_system_allocs
etc. — inherited unchanged), but ``_compute_placements`` is re-expressed
TPU-style in three stages:

  1. per-unique-TG feasibility masks compiled once over the whole fleet
     (nomad_tpu/models/constraints.py — the same compiler the jax-binpack
     path uses, cached per fleet generation);
  2. fit + ScoreFit for ALL of a TG's node-pinned placements in one
     numpy pass (system placements name their node, so there is no
     argmax — every decision is O(D) vector math, batched);
  3. the per-placement finish (ports, Allocation/AllocMetric
     construction, plan append) through the native bulk finish
     (native/port_alloc.cpp), falling back to a per-placement Python
     loop from wherever C left off.

Batching stage 2 by task group is fit-order-equivalent to the
sequential (node-major) walk: a node's row accumulates each placed TG's
ask before the next TG's fit check reads it, exactly as the
interleaved order would.  The one divergence: usage for a fit-passing
placement is accumulated before its port/bandwidth assignment, so a
network-assign failure (exhausted bandwidth, rare) leaves that ask
counted — strictly conservative (later fits can only get harder; no
oversubscription).  Plans are otherwise exactly as valid as the
sequential scheduler's (parity-tested in tests/test_system_vec.py).
"""
from __future__ import annotations

import time

from random import randrange as _randrange

import numpy as np

from nomad_tpu.models.constraints import compile_group_mask
from nomad_tpu.models.fleet import build_usage, fleet_cache, mirror_for
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_RUN,
    AllocMetric,
    Allocation,
    generate_uuids,
)
from nomad_tpu.structs.funcs import score_fit_vec

from .jax_binpack import (
    _ALLOC_STATIC,
    _METRIC_STATIC,
    FastPlacementMixin,
    _native_bulk,
    _net_plan_for,
    build_slots_c,
    run_bulk_finish,
)
from .system import SystemScheduler
from .util import task_group_constraints


class VectorSystemScheduler(SystemScheduler, FastPlacementMixin):
    def _compute_job_allocs(self) -> None:
        """Fresh-registration fast path: with no existing allocs the
        system diff is pure node-pinned placement, deterministic per
        (job version, fleet generation) — exactly the shape node-join
        storms re-evaluate over and over.  Memoized as a read-only
        tuple on the job (same pattern as util.diff_allocs
        cache_fresh); anything with existing allocs takes the
        inherited general path."""
        from nomad_tpu.structs import filter_terminal_allocs

        job = self.job
        if job is None:
            return super()._compute_job_allocs()
        allocs = filter_terminal_allocs(
            self.state.allocs_by_job(self.eval.job_id))
        if allocs:
            return super()._compute_job_allocs(allocs)
        # Fresh path truncates nothing; clear any limit left by a prior
        # retry attempt (retry_max reuses this scheduler instance).
        self.limit_reached = False
        statics = fleet_cache.statics_for(self.state)
        cached = job.__dict__.get("_sys_fresh")
        if cached is not None and cached[0] == job.modify_index \
                and cached[1] == statics.gen:
            place = cached[2]
        else:
            from .util import diff_system_allocs

            diff = diff_system_allocs(job, self.nodes, {}, [])
            place = tuple(diff.place)
            job.__dict__["_sys_fresh"] = (job.modify_index, statics.gen,
                                          place)
        if place:
            self._compute_placements(place)

    def _prep_slots(self, place, statics):
        """Stage 1: per-unique-TG masks/asks + per-placement slot and
        node-index arrays.  Pure in (job version, place identity, fleet
        generation) — memoized on the job for re-evals."""
        job = self.job
        tmpl = job.__dict__.get("_sys_prep")
        if tmpl is not None and tmpl[0] == job.modify_index \
                and tmpl[1] == statics.gen and tmpl[2] is place:
            return tmpl[3]

        slots: list = []    # slot -> (tg, mask, dist, ask_vec, size, plan)
        slot_of: dict = {}  # id(tg) -> slot
        group_l: list = []  # placement -> slot
        ni_l: list = []     # placement -> node index
        index_of = statics.index_of
        for missing in place:
            tg = missing.task_group
            s = slot_of.get(id(tg))
            if s is None:
                tg_constr = task_group_constraints(tg)
                mask, dist = compile_group_mask(
                    statics, job.datacenters, job.constraints,
                    tg_constr.constraints, tg_constr.drivers)
                ask_vec = np.asarray(tg_constr.size.as_vector(),
                                     dtype=np.float32)
                slot_of[id(tg)] = s = len(slots)
                slots.append((tg, mask, dist, ask_vec, tg_constr.size,
                              _net_plan_for(tg)))
            group_l.append(s)
            ni = index_of.get(missing.alloc.node_id, -1)
            if ni < 0:
                raise KeyError(
                    f"could not find node {missing.alloc.node_id!r}")
            ni_l.append(ni)
        prep = (slots, group_l, np.asarray(group_l, dtype=np.int64),
                np.asarray(ni_l, dtype=np.int64), [None])
        job.__dict__["_sys_prep"] = (job.modify_index, statics.gen, place,
                                     prep)
        return prep

    def _compute_placements(self, place: list) -> None:
        start = time.perf_counter()
        statics = fleet_cache.statics_for(self.state)
        view = mirror_for(statics).view_at(self.state, self.plan,
                                           self.job.id)
        if view is None:
            view = build_usage(statics, self._proposed_allocs_all(),
                               job_id=self.job.id)

        slots, group_l, group_arr, ni_arr, slots_c_holder = \
            self._prep_slots(place, statics)

        capacity = statics.capacity
        reserved = statics.reserved
        usage = view.usage.copy()       # accumulates as we place
        jc = view.job_counts.copy()
        nodes_arr = statics.nodes
        n_real = statics.n_real

        # --- stage 2: vector fit + ScoreFit per slot --------------------
        chosen = np.full(len(place), -1, dtype=np.int64)
        scores = np.zeros(len(place), dtype=np.float64)
        for s, (tg, mask, dist, ask_vec, size, net_plan) in \
                enumerate(slots):
            sel = np.nonzero(group_arr == s)[0] if len(slots) > 1 \
                else np.arange(len(place))
            nis = ni_arr[sel]
            if len(np.unique(nis)) != len(nis):
                # count > 1 system TG: a node appears several times in
                # one slot.  The batched fit would check every copy
                # against pre-accumulation usage (and the fancy-index
                # add collapses duplicate rows), so fall back to the
                # exact per-placement walk for this slot.
                self._fit_slot_sequential(sel, nis, mask, dist, ask_vec,
                                          usage, jc, capacity, reserved,
                                          n_real, chosen, scores)
                continue
            ok = mask[nis] & (nis < n_real)
            if dist:
                ok &= jc[nis] == 0
            util = reserved[nis] + usage[nis] + ask_vec
            ok &= (util <= capacity[nis]).all(axis=1)
            # ScoreFit (BestFit v3) from the one shared producer
            # (structs/funcs.score_fit_vec — device kernel parity).
            sc_all = score_fit_vec(
                util[:, 0], util[:, 1],
                capacity[nis, 0] - reserved[nis, 0],
                capacity[nis, 1] - reserved[nis, 1])
            sc = np.where(ok, sc_all, 0.0)
            okn = nis[ok]
            usage[okn] += ask_vec
            jc[okn] += 1
            chosen[sel[ok]] = okn
            scores[sel] = sc

        self._finish_vec(place, start, statics, slots, group_l,
                         slots_c_holder, chosen, scores)

    @staticmethod
    def _fit_slot_sequential(sel, nis, mask, dist, ask_vec, usage, jc,
                             capacity, reserved, n_real, chosen, scores):
        """Exact per-placement fit/score for a slot whose placements
        repeat nodes (system count > 1): each copy sees the usage the
        previous copy committed, exactly like the sequential walk."""
        for k in range(len(sel)):
            ni = int(nis[k])
            ok = bool(mask[ni]) and ni < n_real and \
                not (dist and jc[ni] > 0)
            if not ok:
                continue
            util = reserved[ni] + usage[ni] + ask_vec
            if not bool((util <= capacity[ni]).all()):
                continue
            sc = float(score_fit_vec(
                util[0], util[1],
                capacity[ni, 0] - reserved[ni, 0],
                capacity[ni, 1] - reserved[ni, 1]))
            usage[ni] += ask_vec
            jc[ni] += 1
            chosen[sel[k]] = ni
            scores[sel[k]] = sc

    def _finish_vec(self, place, start, statics, slots, group_l,
                    slots_c_holder, chosen, scores) -> None:
        # --- stage 3: finish (native prefix + Python resume) ------------
        nodes_arr = statics.nodes
        self._net_cache = {}
        self._node_net = {}
        self._statics = statics
        self._port_lcg = _randrange(1 << 30)

        plan = self.plan
        job = self.job
        uuids = generate_uuids(len(place))
        per_time = (time.perf_counter() - start) / max(1, len(place))
        metric_proto = dict(_METRIC_STATIC, nodes_evaluated=1,
                            allocation_time=per_time)
        alloc_proto = dict(_ALLOC_STATIC, eval_id=self.eval.id,
                           job_id=job.id, job=job)
        failed_tg: dict = {}
        # TG ids whose recorded failure came from the device mask
        # (chosen < 0) — the only failures _explain_failures may
        # re-narrate; network-assign failures keep their own story.
        mask_rejected: set = set()
        chosen_l = chosen.tolist()
        scores_l = scores.tolist()

        start_p = 0
        native = _native_bulk()
        if native is not None and all(s[5][0] for s in slots):
            slots_c = slots_c_holder[0]
            if slots_c is None:
                slots_c = build_slots_c(
                    (size, plan_tasks)
                    for _tg, _mask, _dist, _ask, size, (_f, plan_tasks)
                    in slots)
                slots_c_holder[0] = slots_c
            start_p, fmap = run_bulk_finish(
                native, self, place, group_l, chosen_l, scores_l,
                uuids, slots_c, alloc_proto, metric_proto,
                coalesce_all=0)  # node-pinned: coalesce chosen-less only
            failed_tg.update(fmap)
            # Native fmap entries are created only for chosen-less
            # placements (coalesce_all=0 semantics).
            mask_rejected.update(fmap.keys())
            for failed in fmap.values():
                failed.metrics.nodes_filtered = 1

        for p in range(start_p, len(place)):
            missing = place[p]
            tg = missing.task_group
            prior_fail = failed_tg.get(id(tg))
            if prior_fail is not None and chosen_l[p] < 0:
                prior_fail.metrics.coalesced_failures += 1
                continue

            s = group_l[p]
            _tg, mask, dist, ask_vec, size, net_plan = slots[s]
            ni = chosen_l[p]
            ok = ni >= 0
            task_resources = None
            if ok:
                node = nodes_arr[ni]
                fast_ok, plan_tasks = net_plan
                if fast_ok:
                    task_resources = self._assign_networks_fast(
                        ni, node, plan_tasks)
                else:
                    task_resources = self._assign_networks(node, tg)
                ok = task_resources is not None

            if not ok:
                prior_fail = failed_tg.get(id(tg))
                if prior_fail is not None:
                    prior_fail.metrics.coalesced_failures += 1
                    continue

            m = AllocMetric.__new__(AllocMetric)
            md = dict(metric_proto)  # factory dicts materialize lazily
            alloc = Allocation.__new__(Allocation)
            d = dict(alloc_proto)
            d["id"] = uuids[p]
            d["name"] = missing.name
            d["task_group"] = tg.name
            d["resources"] = size
            d["metrics"] = m
            d["task_states"] = {}
            if ok:
                md["_lazy_score_key"] = node.id + ".binpack"
                md["_lazy_score_val"] = float(scores_l[p])
                d["node_id"] = node.id
                d["task_resources"] = task_resources
                d["desired_status"] = ALLOC_DESIRED_STATUS_RUN
                d["client_status"] = ALLOC_CLIENT_STATUS_PENDING
                m.__dict__ = md
                alloc.__dict__ = d
                plan.append_alloc(alloc)
            else:
                md["nodes_filtered"] = 1
                d["task_resources"] = {}
                d["desired_status"] = ALLOC_DESIRED_STATUS_FAILED
                d["desired_description"] = \
                    "failed to find a node for placement"
                d["client_status"] = ALLOC_CLIENT_STATUS_FAILED
                m.__dict__ = md
                alloc.__dict__ = d
                plan.append_failed(alloc)
                failed_tg[id(tg)] = alloc
                if ni < 0:
                    mask_rejected.add(id(tg))

        self._explain_failures(mask_rejected, failed_tg, place, chosen_l,
                               nodes_arr, statics)

    def _explain_failures(self, mask_rejected, failed_tg, place, chosen_l,
                          nodes_arr, statics) -> None:
        """Upgrade each task group's first mask-rejected placement to
        the sequential chain's explanation.  System placements are
        node-pinned, so the failure story is that node's
        constraint/fit verdict — run the stack against just that node
        and take its ctx metrics (what the reference system scheduler
        records per failed alloc; later failures stay coalesced onto
        this one).  Only allocs whose ORIGINAL failure was the device
        mask qualify (``mask_rejected``) — a network-assign failure on
        a chosen node keeps its own story."""
        if not failed_tg:
            return
        index_of = statics.index_of
        pending = {k: v for k, v in failed_tg.items()
                   if k in mask_rejected}
        for p, missing in enumerate(place):
            if not pending:
                break
            if chosen_l[p] >= 0:
                continue
            failed = pending.pop(id(missing.task_group), None)
            if failed is None:
                continue
            ni = index_of.get(missing.alloc.node_id, -1)
            if ni < 0:
                continue
            self.stack.set_nodes([nodes_arr[ni]])
            option, _size = self.stack.select(missing.task_group)
            if option is not None:
                # Exact chain would place here (mask over-approximation
                # disagreement): keep the shallow metric rather than
                # invent a story.
                continue
            explained = self.ctx.metrics()
            if not (explained.constraint_filtered or
                    explained.class_filtered):
                # Only constraint/class verdicts are usage-independent;
                # an exhaustion story computed against the FINISHED
                # plan could blame usage that accumulated after this
                # placement's decision point — keep the shallow metric.
                continue
            explained.coalesced_failures = \
                failed.metrics.coalesced_failures
            explained.allocation_time = failed.metrics.allocation_time
            failed.metrics = explained


def new_vector_system_scheduler(state, planner) -> VectorSystemScheduler:
    return VectorSystemScheduler(state, planner)
