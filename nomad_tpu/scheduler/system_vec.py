"""Vectorized system scheduler: node-pinned placement without the
per-node iterator walk.

Capability parity with /root/reference/scheduler/system_sched.go via the
same reconcile logic as the sequential SystemScheduler (diff_system_allocs
etc. — inherited unchanged), but ``_compute_placements`` is re-expressed
TPU-style: the per-task-group feasibility mask is compiled once over the
whole fleet (nomad_tpu/models/constraints.py, the same compiler the
jax-binpack path uses), fit is one vector compare against the fleet
tensors, and the ScoreFit scalar is computed from the same rows — instead
of running the SystemStack iterator chain once per node (O(nodes) chain
setups per eval; this is what made a 1k-node system eval cost ~40 ms).

System placements are *node-pinned* (diff_system_allocs names the node for
every missing alloc), so there is no argmax over the fleet — the device
has nothing to win here and every placement decision is O(D) host math.
The shared FastPlacementMixin supplies the exact port/bandwidth
assignment, so plans are exactly as valid as the sequential scheduler's
(parity-tested in tests/test_system_vec.py).
"""
from __future__ import annotations

import time

from random import randrange as _randrange

import numpy as np

from nomad_tpu.models.constraints import compile_group_mask
from nomad_tpu.models.fleet import build_usage, fleet_cache, mirror_for
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_RUN,
    AllocMetric,
    Allocation,
    generate_uuids,
)

from .jax_binpack import (
    _ALLOC_STATIC,
    _METRIC_FACTORIES,
    _METRIC_STATIC,
    FastPlacementMixin,
    _net_plan_for,
)
from .system import SystemScheduler
from .util import task_group_constraints


class VectorSystemScheduler(SystemScheduler, FastPlacementMixin):
    def _compute_placements(self, place: list) -> None:
        start = time.perf_counter()
        statics = fleet_cache.statics_for(self.state)
        view = mirror_for(statics).view_at(self.state, self.plan,
                                           self.job.id)
        if view is None:
            view = build_usage(statics, self._proposed_allocs_all(),
                               job_id=self.job.id)

        # Per-unique-TG compilation (system jobs typically have few TGs).
        tg_info: dict = {}  # id(tg) -> (mask, dist, ask_vec, size, plan)
        for missing in place:
            tg = missing.task_group
            if id(tg) in tg_info:
                continue
            tg_constr = task_group_constraints(tg)
            mask, dist = compile_group_mask(
                statics, self.job.datacenters, self.job.constraints,
                tg_constr.constraints, tg_constr.drivers)
            ask_vec = np.asarray(tg_constr.size.as_vector(),
                                 dtype=np.float32)
            tg_info[id(tg)] = (mask, dist, ask_vec, tg_constr.size,
                               _net_plan_for(tg))

        capacity = statics.capacity
        reserved = statics.reserved
        usage = view.usage.copy()       # accumulates as we place
        jc = view.job_counts.copy()
        index_of = statics.index_of
        nodes_arr = statics.nodes
        n_real = statics.n_real

        self._net_cache = {}
        self._node_net = {}
        self._statics = statics
        self._port_lcg = _randrange(1 << 30)

        plan = self.plan
        eval_id = self.eval.id
        job = self.job
        uuids = generate_uuids(len(place))
        per_time = (time.perf_counter() - start) / max(1, len(place))
        metric_proto = dict(_METRIC_STATIC, nodes_evaluated=1,
                            allocation_time=per_time)
        alloc_proto = dict(_ALLOC_STATIC, eval_id=eval_id, job_id=job.id,
                           job=job)
        failed_tg: dict = {}

        for p, missing in enumerate(place):
            tg = missing.task_group
            mask, dist, ask_vec, size, net_plan = tg_info[id(tg)]
            ni = index_of.get(missing.alloc.node_id, -1)
            if ni < 0:
                raise KeyError(
                    f"could not find node {missing.alloc.node_id!r}")

            node = nodes_arr[ni]
            task_resources = None
            score = 0.0
            ok = bool(mask[ni]) and ni < n_real and \
                not (dist and jc[ni] > 0)
            if ok:
                util = reserved[ni] + usage[ni] + ask_vec
                ok = bool((util <= capacity[ni]).all())
                if ok:
                    # ScoreFit (BestFit v3) on the same rows the device
                    # kernel uses (structs/funcs score_fit parity).
                    node_cpu = capacity[ni, 0] - reserved[ni, 0]
                    node_mem = capacity[ni, 1] - reserved[ni, 1]
                    if node_cpu > 0 and node_mem > 0:
                        score = 20.0 - (
                            10.0 ** (1.0 - util[0] / node_cpu)
                            + 10.0 ** (1.0 - util[1] / node_mem))
                        score = min(max(score, 0.0), 18.0)
            if ok:
                fast_ok, plan_tasks = net_plan
                if fast_ok:
                    task_resources = self._assign_networks_fast(
                        ni, node, plan_tasks)
                else:
                    task_resources = self._assign_networks(node, tg)
                ok = task_resources is not None

            if not ok:
                prior_fail = failed_tg.get(id(tg))
                if prior_fail is not None:
                    prior_fail.metrics.coalesced_failures += 1
                    continue

            m = AllocMetric.__new__(AllocMetric)
            md = dict(metric_proto)
            for nm, fac in _METRIC_FACTORIES:
                md[nm] = fac()
            alloc = Allocation.__new__(Allocation)
            d = dict(alloc_proto)
            d["id"] = uuids[p]
            d["name"] = missing.name
            d["task_group"] = tg.name
            d["resources"] = size
            d["metrics"] = m
            d["task_states"] = {}
            if ok:
                md["scores"] = {node.id + ".binpack": float(score)}
                d["node_id"] = node.id
                d["task_resources"] = task_resources
                d["desired_status"] = ALLOC_DESIRED_STATUS_RUN
                d["client_status"] = ALLOC_CLIENT_STATUS_PENDING
                m.__dict__ = md
                alloc.__dict__ = d
                plan.append_alloc(alloc)
                usage[ni] += ask_vec
                jc[ni] += 1
            else:
                md["nodes_filtered"] = 1
                d["task_resources"] = {}
                d["desired_status"] = ALLOC_DESIRED_STATUS_FAILED
                d["desired_description"] = \
                    "failed to find a node for placement"
                d["client_status"] = ALLOC_CLIENT_STATUS_FAILED
                m.__dict__ = md
                alloc.__dict__ = d
                plan.append_failed(alloc)
                failed_tg[id(tg)] = alloc


def new_vector_system_scheduler(state, planner) -> VectorSystemScheduler:
    return VectorSystemScheduler(state, planner)
