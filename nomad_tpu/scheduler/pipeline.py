"""Staged eval pipeline: hide the device round-trip AND overlap host work.

On remote-attached TPUs every synchronous dispatch costs a full network
round trip (~100 ms through the axon tunnel) regardless of compute size,
so a strictly sequential eval loop is latency-bound: prep -> RTT ->
finish, one eval per RTT.  This runner splits the eval into two host
stages running on two threads, with up to ``depth`` device dispatches in
flight between them:

  front stage (caller thread)   drain stage (worker thread)
  ---------------------------   ------------------------------------
  reconcile + prep (begin)      collect device results (blocks on the
  dispatch (non-blocking)         wire, GIL released)
  enqueue -> bounded window --> native bulk finish + Python tail
                                plan submit (FIFO = eval order)

While eval N's results cross the wire — and while its C finish loop and
plan submit run — evals N+1..N+depth are reconciled, prepped, and
dispatched, so steady-state throughput is bound by the slower of the
two host stages, not their sum, and never by the RTT.

Host-floor amortization: the drain stage pulls EVERY queued eval it can
and finishes them as one window — a single uuid slab
(structs.generate_uuids) and a single native call
(native/port_alloc.cpp bulk_finish_many) cover the whole window, so the
per-eval Python re-entry cost is paid once per window, not per eval.
Device-side, the dispatch constants (asks/feasibility/usage mirror) stay
resident across the window (DeviceArgs.dev_const + the statics device
cache); input buffers are NOT donated — the usage tensor is the shared
fleet-mirror buffer that in-flight dispatches still read
(models/fleet.py:770), so donation would corrupt the window.

Ordering guarantees, unchanged from the single-threaded runner:
per-job serialization (one in-flight eval per job per round, leftovers
run after a ``state_refresh``) and plan-commit ordering (the drain
stage submits strictly in eval order; even placement-less plans route
through it).

This is the eval-axis analogue of the reference's pipelined
verify/apply (/root/reference/nomad/plan_apply.go:13-37 — plan N+1
verified while plan N's raft apply is in flight) and of its worker-pool
concurrency (/root/reference/nomad/worker.go:50-437): many evals are
optimistically in flight against the same snapshot, and the plan
applier serializes commits.

Use BatchEvalRunner (scheduler/batch.py) when a whole batch is available
up front and shapes are homogeneous — one fused vmap dispatch beats a
pipeline.  Use PipelinedEvalRunner for streams: heterogeneous shapes,
latency-sensitive arrivals, or when plans must commit between evals.
"""
from __future__ import annotations

import queue
import threading
import time

from .batch import BatchEvalRunner

_STOP = object()


class _Item:
    """One eval moving front -> drain.  ``handles`` is None for
    placement-less plans (submit-only)."""

    __slots__ = ("sched", "place", "args", "handles", "start")

    def __init__(self, sched, place, args, handles, start) -> None:
        self.sched = sched
        self.place = place
        self.args = args
        self.handles = handles
        self.start = start


class PipelinedEvalRunner(BatchEvalRunner):
    """Processes a list of evaluations with up to ``depth`` device
    dispatches in flight and the two host stages overlapped.

    Inherits the batch runner's per-job serialization (one in-flight
    eval per job; leftovers run after a ``state_refresh``), status
    handling, and submit/retry logic.  Unlike the batch runner, every
    eval gets its own dispatch, so evals whose plans already carry
    deltas (migrations, in-place updates) pipeline like any other.

    ``latencies`` records per-eval wall seconds (begin -> plan
    submitted) for the bench's percentile reporting.  ``stage_times``
    accumulates per-stage wall seconds (begin/dispatch/collect/finish/
    submit) across the run — the single-eval host-floor profile the
    bench's bottleneck note reports.  ``host_dispatches`` /
    ``device_dispatches`` count which executor each dispatch actually
    used (NOMAD_TPU_EXECUTOR forces it; scheduler/executor.py).
    """

    def __init__(self, state, planner, depth: int = 4,
                 state_refresh=None) -> None:
        super().__init__(state, planner, state_refresh=state_refresh)
        self.depth = max(1, depth)
        self.latencies: list[float] = []
        self.stage_times = {"begin": 0.0, "dispatch": 0.0, "collect": 0.0,
                            "finish": 0.0, "submit": 0.0}
        self.host_dispatches = 0
        self.device_dispatches = 0
        self.windows: list[int] = []  # drained-window sizes (diagnostics)
        self._err_lock = threading.Lock()
        self._drain_err: BaseException | None = None

    def process(self, evals: list) -> None:
        from nomad_tpu.utils.gctune import gc_pause

        with gc_pause():
            self._process_staged(evals)

    # -- front stage ------------------------------------------------------
    def _process_staged(self, evals: list) -> None:
        this_round, leftovers = self._split_rounds(evals)
        q: queue.Queue = queue.Queue(maxsize=self.depth)
        drain = threading.Thread(target=self._drain_loop, args=(q,),
                                 name="eval-pipeline-drain", daemon=True)
        drain.start()
        times = self.stage_times
        try:
            for ev in this_round:
                if self._failed():
                    break
                start = time.perf_counter()
                sched = self._begin_eval(ev, finish_noop=False)
                t_begin = time.perf_counter()
                times["begin"] += t_begin - start
                if sched is None:
                    # Terminal without a plan (bad trigger/status error):
                    # nothing to submit, latency is begin time alone.
                    self.latencies.append(t_begin - start)
                    continue
                if sched.deferred is None:
                    # Placement-less plan: submit-only item, routed
                    # through the drain stage to keep commit order.
                    q.put(_Item(sched, None, None, None, start))
                    continue
                place, args = sched.deferred
                handles = sched.dispatch_device(args, pipelined=True)
                if sched.dispatched_host:
                    self.host_dispatches += 1
                else:
                    self.device_dispatches += 1
                times["dispatch"] += time.perf_counter() - t_begin
                q.put(_Item(sched, place, args, handles, start))
        finally:
            q.put(_STOP)
            drain.join()
        with self._err_lock:
            err = self._drain_err
        if err is not None:
            raise err
        if leftovers:
            self._process_leftovers(leftovers)

    def _failed(self) -> bool:
        with self._err_lock:
            return self._drain_err is not None

    # -- drain stage ------------------------------------------------------
    def _drain_loop(self, q: queue.Queue) -> None:
        stop_seen = False
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    return
                window = [item]
                # Opportunistic window: everything already queued drains
                # as ONE batch (shared uuid slab, one native call).
                while True:
                    try:
                        nxt = q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _STOP:
                        stop_seen = True
                        break
                    window.append(nxt)
                self._drain_window(window)
                if stop_seen:
                    return
        except BaseException as e:
            with self._err_lock:
                self._drain_err = e
            # Keep consuming so the front stage never deadlocks on a
            # full window; items are discarded (their evals get no
            # status — the front stops and the error propagates).  If
            # the window-gather already swallowed the sentinel there is
            # nothing left to wait for — blocking on q.get() here WAS a
            # deadlock (the front is in drain.join() by then).
            if not stop_seen:
                while q.get() is not _STOP:
                    pass

    def _drain_window(self, window: list) -> None:
        from nomad_tpu.structs import generate_uuids
        from nomad_tpu.utils.native import native

        times = self.stage_times
        self.windows.append(len(window))

        # 1) collect: block on each dispatch's results, FIFO.  Result
        # copies were started at dispatch (copy_to_host_async), so
        # waiting on eval N overlaps N+1's transfer too.
        t0 = time.perf_counter()
        work = [it for it in window if it.handles is not None]
        results = {}
        for it in work:
            results[id(it)] = it.sched.collect_device(it.args, it.handles)
        t1 = time.perf_counter()
        times["collect"] += t1 - t0

        # 2) finish: one uuid slab + one native call for the window,
        # then each eval's Python tail.
        slab = generate_uuids(sum(len(it.place) for it in work))
        states = {}
        nargs = []
        off = 0
        for it in work:
            chosen, scores = results[id(it)]
            n = len(it.place)
            fs = it.sched._finish_prepare(
                it.place, it.args, chosen, scores, slab[off:off + n])
            off += n
            states[id(it)] = fs
            nargs.append(it.sched._finish_native_args(fs))
        if native is not None and hasattr(native, "bulk_finish_many") \
                and len(work) > 1 and all(a is not None for a in nargs):
            outs = native.bulk_finish_many(nargs)
            for it, out in zip(work, outs):
                it.sched._finish_consume_native(states[id(it)], out)
        else:
            for it, a in zip(work, nargs):
                if a is not None:
                    it.sched._finish_consume_native(
                        states[id(it)], native.bulk_finish(*a))
        for it in work:
            it.sched._finish_python_tail(states[id(it)])
        t2 = time.perf_counter()
        times["finish"] += t2 - t1

        # 3) submit, strictly in eval order (noop items interleave at
        # their original position).
        for it in window:
            self._finish(it.sched)
            self.latencies.append(time.perf_counter() - it.start)
        times["submit"] += time.perf_counter() - t2
