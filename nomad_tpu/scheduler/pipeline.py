"""Pipelined eval processing: hide the device round-trip behind host work.

On remote-attached TPUs every synchronous dispatch costs a full network
round trip (~100 ms through the axon tunnel) regardless of compute size,
so a strictly sequential eval loop is latency-bound: prep -> RTT -> finish,
one eval per RTT.  This runner keeps a window of ``depth`` evals in
flight — while eval N's results cross the wire, evals N+1..N+depth are
reconciled, prepped, and dispatched — so steady-state throughput is bound
by host work (a few ms/eval), not the RTT.

This is the eval-axis analogue of the reference's pipelined verify/apply
(/root/reference/nomad/plan_apply.go:13-37 — plan N+1 verified while plan
N's raft apply is in flight) and of its worker-pool concurrency
(/root/reference/nomad/worker.go:50-437): many evals are optimistically in
flight against the same snapshot, and the plan applier serializes commits.

Use BatchEvalRunner (scheduler/batch.py) when a whole batch is available
up front and shapes are homogeneous — one fused vmap dispatch beats a
pipeline.  Use PipelinedEvalRunner for streams: heterogeneous shapes,
latency-sensitive arrivals, or when plans must commit between evals.
"""
from __future__ import annotations

import time

from collections import deque

from .batch import BatchEvalRunner


class PipelinedEvalRunner(BatchEvalRunner):
    """Processes a list of evaluations with up to ``depth`` device
    dispatches in flight.

    Inherits the batch runner's per-job serialization (one in-flight eval
    per job; leftovers run after a ``state_refresh``), status handling,
    and submit/retry logic.  Unlike the batch runner, every eval gets its
    own dispatch, so evals whose plans already carry deltas (migrations,
    in-place updates) pipeline like any other.

    ``latencies`` records per-eval wall seconds (begin -> plan submitted)
    for the bench's percentile reporting.
    """

    def __init__(self, state, planner, depth: int = 4,
                 state_refresh=None) -> None:
        super().__init__(state, planner, state_refresh=state_refresh)
        self.depth = max(1, depth)
        self.latencies: list[float] = []

    def process(self, evals: list) -> None:
        from nomad_tpu.utils.gctune import gc_pause

        with gc_pause():
            self._process_pipelined(evals)

    def _process_pipelined(self, evals: list) -> None:
        this_round, leftovers = self._split_rounds(evals)
        window: deque = deque()
        for ev in this_round:
            start = time.perf_counter()
            sched = self._begin_eval(ev)
            if sched is None:
                self.latencies.append(time.perf_counter() - start)
                continue
            place, args = sched.deferred
            handles = sched.dispatch_device(args, pipelined=True)
            window.append((sched, place, args, handles, start))
            if len(window) >= self.depth:
                self._drain_one(window)
        while window:
            self._drain_one(window)
        if leftovers:
            self._process_leftovers(leftovers)

    def _drain_one(self, window: deque) -> None:
        sched, place, args, handles, start = window.popleft()
        chosen, scores = sched.collect_device(args, handles)
        sched.finish_deferred(place, args, chosen, scores)
        self._finish(sched)
        self.latencies.append(time.perf_counter() - start)
