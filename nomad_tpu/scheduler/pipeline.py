"""Staged eval pipeline: hide the device round-trip AND overlap host work.

On remote-attached TPUs every synchronous dispatch costs a full network
round trip (~100 ms through the axon tunnel) regardless of compute size,
so a strictly sequential eval loop is latency-bound: prep -> RTT ->
finish, one eval per RTT.  This runner splits the eval into two host
stages running on two threads, with up to ``depth`` device dispatches in
flight between them:

  front stage (caller thread)   drain stage (worker thread)
  ---------------------------   ------------------------------------
  reconcile + prep (begin)      collect device results (blocks on the
  dispatch (non-blocking)         wire, GIL released)
  enqueue -> bounded window --> native bulk finish + Python tail
                                plan submit (FIFO = eval order)

While eval N's results cross the wire — and while its C finish loop and
plan submit run — evals N+1..N+depth are reconciled, prepped, and
dispatched, so steady-state throughput is bound by the slower of the
two host stages, not their sum, and never by the RTT.

Host-floor amortization: the drain stage pulls EVERY queued eval it can
and finishes them as one window — a single uuid slab
(structs.generate_uuids) and a single native call
(native/port_alloc.cpp bulk_finish_many) cover the whole window, so the
per-eval Python re-entry cost is paid once per window, not per eval.
Device-side, the dispatch constants (asks/feasibility/usage mirror) stay
resident across the window (DeviceArgs.dev_const + the statics device
cache); input buffers are NOT donated — the usage tensor is the shared
fleet-mirror buffer that in-flight dispatches still read
(models/fleet.py:770), so donation would corrupt the window.

Ordering guarantees, unchanged from the single-threaded runner:
per-job serialization (one in-flight eval per job per round, leftovers
run after a ``state_refresh``) and plan-commit ordering (the drain
stage submits strictly in eval order; even placement-less plans route
through it).

This is the eval-axis analogue of the reference's pipelined
verify/apply (/root/reference/nomad/plan_apply.go:13-37 — plan N+1
verified while plan N's raft apply is in flight) and of its worker-pool
concurrency (/root/reference/nomad/worker.go:50-437): many evals are
optimistically in flight against the same snapshot, and the plan
applier serializes commits.

Use BatchEvalRunner (scheduler/batch.py) when a whole batch is available
up front and shapes are homogeneous — one fused vmap dispatch beats a
pipeline.  Use PipelinedEvalRunner for streams: heterogeneous shapes,
latency-sensitive arrivals, or when plans must commit between evals.
"""
from __future__ import annotations

import logging
import queue
import threading
import time

from nomad_tpu import faultinject
from nomad_tpu.obs import trace as trace_mod

from .batch import BatchEvalRunner, _lane_spans, _tnow
from .breaker import ADMIT_HOST, ADMIT_PROBE, GLOBAL_BREAKER

logger = logging.getLogger("nomad_tpu.scheduler.pipeline")

_STOP = object()


class _CollectWorker:
    """Long-lived watchdog worker for deadline-bounded device collects.

    The drain stage feeds it one callable at a time via ``inq`` and
    waits on ``outq`` with the deadline; a ``None`` on ``inq`` exits
    the thread.  The runner replaces the worker after a timeout — a
    hung device call cannot be interrupted, so the old worker keeps its
    references only until that call returns, then sees the sentinel
    and dies (no unbounded thread accumulation under a fault burst).
    """

    def __init__(self) -> None:
        self.inq: queue.Queue = queue.Queue()
        self.outq: queue.Queue = queue.Queue()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="device-collect")
        self.thread.start()

    def _run(self) -> None:
        while True:
            # faultlint-ok(unbounded-wait): idle watchdog-worker
            # parking — exit rides the None sentinel; the collect
            # DEADLINE lives on the outq.get in
            # _collect_device_bounded, not here.
            fn = self.inq.get()
            if fn is None:
                return
            try:
                self.outq.put((True, fn()))
            except BaseException as e:
                self.outq.put((False, e))

    def join(self, timeout: "float | None" = None) -> None:
        """Reap after the exit sentinel.  Only the clean-shutdown path
        may join — an abandoned (hung-collect) worker is deliberately
        left to die on its own when the device call returns."""
        self.thread.join(timeout)


class _Item:
    """One eval moving front -> drain.  ``handles`` is None for
    placement-less plans (submit-only).  ``probe`` marks the breaker's
    half-open probe: the drain stage re-runs it on the host twin and
    asserts parity before closing the breaker."""

    __slots__ = ("sched", "place", "args", "handles", "start", "probe")

    def __init__(self, sched, place, args, handles, start,
                 probe: bool = False) -> None:
        self.sched = sched
        self.place = place
        self.args = args
        self.handles = handles
        self.start = start
        self.probe = probe


class PipelinedEvalRunner(BatchEvalRunner):
    """Processes a list of evaluations with up to ``depth`` device
    dispatches in flight and the two host stages overlapped.

    Inherits the batch runner's per-job serialization (one in-flight
    eval per job; leftovers run after a ``state_refresh``), status
    handling, and submit/retry logic.  Unlike the batch runner, every
    eval gets its own dispatch, so evals whose plans already carry
    deltas (migrations, in-place updates) pipeline like any other.

    ``latencies`` records per-eval wall seconds (begin -> plan
    submitted) for the bench's percentile reporting.  ``stage_times``
    accumulates per-stage wall seconds (begin/dispatch/collect/finish/
    submit) across the run — the single-eval host-floor profile the
    bench's bottleneck note reports.  ``host_dispatches`` /
    ``device_dispatches`` count which executor each dispatch actually
    used (NOMAD_TPU_EXECUTOR forces it; scheduler/executor.py).
    """

    def __init__(self, state, planner, depth: int = 4,
                 state_refresh=None, breaker=None,
                 device_deadline: "float | None" = None) -> None:
        super().__init__(state, planner, state_refresh=state_refresh)
        self.depth = max(1, depth)
        self.latencies: list[float] = []
        self.stage_times = {"begin": 0.0, "dispatch": 0.0, "collect": 0.0,
                            "finish": 0.0, "submit": 0.0}
        self.host_dispatches = 0
        self.device_dispatches = 0
        # Device dispatches that ran node-axis-sharded over a mesh
        # (parallel/mesh.dispatch_mesh resolved one): the bench's
        # sharded rows assert this covers every device dispatch on a
        # multi-device platform.
        self.sharded_dispatches = 0
        self.windows: list[int] = []  # drained-window sizes (diagnostics)
        # Device-executor circuit breaker (scheduler/breaker.py): failed
        # or deadline-blown device dispatches re-run on the host twin
        # and trip the breaker, which then holds the executor on host
        # with periodic half-open re-probes.  Shared process-wide by
        # default — device health is a machine property, not a runner's.
        self.breaker = breaker if breaker is not None else GLOBAL_BREAKER
        # Optional per-collect watchdog (seconds): None = no watchdog
        # thread (zero overhead; only raised errors trip the breaker).
        self.device_deadline = device_deadline
        # Evals re-run on host after a device failure.  ONE producer:
        # every increment goes through _record_rerun (called from both
        # stages, so it takes _count_lock); the registry exports this
        # counter and the breaker exports its own transition counts —
        # no number has two producers (obs/registry.py).
        self.breaker_reruns = 0
        self._count_lock = threading.Lock()
        # Dispatch/collect RTT EWMA (seconds; device dispatches only) —
        # the feedback control plane's congestion gauge for the AIMD
        # depth knob (control/wiring.wire_runner): injected
        # device.dispatch delay or a genuinely slow chip inflates it,
        # and the learned-floor driver retreats ``depth``.  Guarded by
        # _count_lock (front and drain threads both feed samples).
        self._rtt_ewma = 0.0
        # Live in-flight gate: ``depth`` is a CONTROL KNOB now — the
        # controller adjusts it mid-stream, so the bound is enforced by
        # this counter + condition instead of a fixed-maxsize queue
        # (a Queue's maxsize is frozen at construction).
        self._inflight = 0
        self._inflight_cond = threading.Condition(threading.Lock())
        self.parity_checks = 0    # probe evals parity-asserted host/dev
        # Lazy long-lived watchdog worker for deadline-bounded collects
        # (drain thread only; replaced after a timeout, see
        # _collect_device_bounded).
        self._collect_worker: "_CollectWorker | None" = None
        self._err_lock = threading.Lock()
        self._drain_err: BaseException | None = None
        # Registry provider (obs/registry.py): the LIVE runner's stats
        # under nomad.runner.* — replace-on-name keeps exactly one, and
        # the weakref means a retired runner is never pinned (its state
        # snapshot is a whole store generation) just to serve metrics.
        import weakref

        from nomad_tpu.obs import REGISTRY
        ref = weakref.ref(self)
        REGISTRY.register(
            "runner",
            lambda: (lambda r: r.stats() if r is not None else {})(
                ref()))

    def process(self, evals: list) -> None:
        from nomad_tpu.utils.gctune import gc_pause

        with gc_pause():
            self._process_staged(evals)

    # -- front stage ------------------------------------------------------
    def _process_staged(self, evals: list) -> None:
        this_round, leftovers = self._split_rounds(evals)
        q: queue.Queue = queue.Queue()
        drain = threading.Thread(target=self._drain_loop, args=(q,),
                                 name="eval-pipeline-drain", daemon=True)
        drain.start()
        times = self.stage_times
        try:
            for ev in this_round:
                if self._failed():
                    break
                start = time.perf_counter()
                sched = self._begin_eval(ev, finish_noop=False)
                t_begin = time.perf_counter()
                times["begin"] += t_begin - start
                if sched is None:
                    # Terminal without a plan (bad trigger/status error):
                    # nothing to submit, latency is begin time alone.
                    self.latencies.append(t_begin - start)
                    continue
                if sched.deferred is None:
                    # Placement-less plan: submit-only item, routed
                    # through the drain stage to keep commit order.
                    self._admit_inflight()
                    q.put(_Item(sched, None, None, None, start))
                    continue
                # The permit is held from here until the drain consumes
                # the item; if anything raises before the put (a
                # dispatch whose host fallback ALSO fails), release it
                # — _inflight is runner-lifetime state now, and a
                # leaked permit would shrink every later stream's
                # effective depth.
                self._admit_inflight()
                try:
                    place, args = sched.deferred
                    t_disp = _tnow()
                    handles, probe = self._dispatch(sched, args)
                    if sched.dispatched_host:
                        self.host_dispatches += 1
                    else:
                        self.device_dispatches += 1
                        if sched.dispatched_sharded:
                            self.sharded_dispatches += 1
                        self._note_rtt(time.perf_counter() - t_begin)
                    _lane_spans("sched.dispatch", [sched], t_disp,
                                _tnow(), host=sched.dispatched_host)
                    times["dispatch"] += time.perf_counter() - t_begin
                    q.put(_Item(sched, place, args, handles, start,
                                probe=probe))
                except BaseException:
                    self._release_inflight()
                    raise
        finally:
            q.put(_STOP)
            drain.join()
            self._stop_collect_worker()
        with self._err_lock:
            err = self._drain_err
        if err is not None:
            raise err
        if leftovers:
            self._process_leftovers(leftovers)

    def _failed(self) -> bool:
        with self._err_lock:
            return self._drain_err is not None

    def _dispatch(self, sched, args) -> tuple:
        """Route one eval's dispatch through the executor policy AND the
        circuit breaker.  Returns (handles, probe): evals the breaker
        holds run the host twin (identical plans by construction); a
        half-open probe runs the device and is parity-checked in the
        drain stage; a dispatch that raises trips the breaker and falls
        back to host immediately."""
        if sched.choose_host_executor(args, pipelined=True):
            sched.dispatched_host = True
            return sched.dispatch_host(args), False
        admit = self.breaker.admit()
        if admit == ADMIT_HOST:
            sched.dispatched_host = True
            return sched.dispatch_host(args), False
        probe = admit == ADMIT_PROBE
        try:
            if faultinject.ACTIVE:
                faultinject.fire("device.dispatch")
            # force=True: the executor decision was made above (policy
            # + breaker); re-evaluating it inside dispatch_device could
            # route a half-open probe to the host twin and orphan it.
            return sched.dispatch_device(args, pipelined=True,
                                         force=True), probe
        except Exception:
            logger.warning("device dispatch failed; re-running eval on "
                           "the host twin", exc_info=True)
            self.breaker.record_failure(probe=probe)
            self._record_rerun()
            sched.dispatched_host = True
            return sched.dispatch_host(args), False

    def _record_rerun(self) -> None:
        """The single producer of ``breaker_reruns`` (cross-thread:
        front stage on dispatch faults, drain stage on collect faults)."""
        with self._count_lock:
            self.breaker_reruns += 1

    def _note_rtt(self, seconds: float) -> None:
        """Feed one device dispatch/collect wall sample into the RTT
        EWMA (the control plane's congestion gauge)."""
        with self._count_lock:
            prev = self._rtt_ewma
            self._rtt_ewma = seconds if prev <= 0.0 \
                else 0.8 * prev + 0.2 * seconds

    def _admit_inflight(self) -> None:
        """Block until the in-flight window has room under the LIVE
        ``depth`` knob (re-read each pass: the control plane adjusts it
        mid-stream).  A dead drain stage still admits — the front loop
        notices ``_failed()`` and stops, and the teardown put must
        never deadlock behind a gate nobody will drain."""
        while True:
            bound = max(1, int(self.depth))  # re-read: a live knob
            with self._inflight_cond:
                if self._inflight < bound:
                    self._inflight += 1
                    return
                self._inflight_cond.wait(0.05)
            if self._failed():
                with self._inflight_cond:
                    self._inflight += 1
                return

    def _release_inflight(self) -> None:
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    def stats(self) -> dict:
        """Registry provider (obs/registry.py): the runner's dispatch
        mix, stage walls, windows, and breaker interactions."""
        with self._count_lock:
            reruns = self.breaker_reruns
            rtt_ewma = self._rtt_ewma
        dispatches = self.host_dispatches + self.device_dispatches
        return {
            "host_dispatches": self.host_dispatches,
            "device_dispatches": self.device_dispatches,
            "sharded_dispatches": self.sharded_dispatches,
            # Control-plane gauges: the live depth knob, the fraction
            # of dispatches that actually rode the device, and the
            # dispatch/collect RTT EWMA the AIMD depth driver reads.
            "depth": self.depth,
            "device_fraction": self.device_dispatches / dispatches
            if dispatches else 0.0,
            "rtt_ms_ewma": round(rtt_ewma * 1000.0, 4),
            "breaker_reruns": reruns,
            "parity_checks": self.parity_checks,
            "evals": len(self.latencies),
            "windows": len(self.windows),
            "stage_times_ms": {k: round(v * 1000.0, 3)
                               for k, v in self.stage_times.items()},
        }

    # -- drain stage ------------------------------------------------------
    def _drain_loop(self, q: queue.Queue) -> None:
        stop_seen = False
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    return
                self._release_inflight()
                window = [item]
                # Opportunistic window: everything already queued drains
                # as ONE batch (shared uuid slab, one native call).
                while True:
                    try:
                        nxt = q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _STOP:
                        stop_seen = True
                        break
                    self._release_inflight()
                    window.append(nxt)
                self._drain_window(window)
                if stop_seen:
                    return
        except BaseException as e:
            with self._err_lock:
                self._drain_err = e
            # Keep consuming so the front stage never deadlocks on a
            # full window; items are discarded (their evals get no
            # status — the front stops and the error propagates).  If
            # the window-gather already swallowed the sentinel there is
            # nothing left to wait for — blocking on q.get() here WAS a
            # deadlock (the front is in drain.join() by then).
            if not stop_seen:
                while q.get() is not _STOP:
                    self._release_inflight()

    def _drain_window(self, window: list) -> None:
        times = self.stage_times
        self.windows.append(len(window))

        # 1) collect: block on each dispatch's results, FIFO.  Result
        # copies were started at dispatch (copy_to_host_async), so
        # waiting on eval N overlaps N+1's transfer too.  A device
        # collect that fails or blows the deadline re-runs on the host
        # twin and trips the breaker (the window keeps draining).
        t0 = time.perf_counter()
        work = [it for it in window if it.handles is not None]
        results = {}
        for it in work:
            t_col = _tnow()
            results[id(it)] = self._collect_item(it)
            _lane_spans("sched.collect", [it.sched], t_col, _tnow())
        t1 = time.perf_counter()
        times["collect"] += t1 - t0

        # 2) finish: the shared windowed-finish sequence — one uuid slab
        # + one native call + Python tails (BatchEvalRunner._finish_lanes
        # is the single implementation).
        self._finish_lanes([(it.sched, it.place, it.args)
                            + tuple(results[id(it)]) for it in work])
        t2 = time.perf_counter()
        times["finish"] += t2 - t1

        # 3) submit, strictly in eval order (noop items interleave at
        # their original position), as ONE group through the planner's
        # window path when it has one — the drain window is exactly the
        # commit window the group-commit applier amortizes.
        self._submit_window([it.sched for it in window])
        now = time.perf_counter()
        for it in window:
            self.latencies.append(now - it.start)
        times["submit"] += now - t2

    # -- device failure handling (breaker) ---------------------------------
    def _collect_item(self, it: _Item) -> tuple:
        """Collect one item's results, routing device outcomes through
        the circuit breaker.  Probe items additionally run the host
        twin and assert parity before the breaker closes."""
        import numpy as np

        sched = it.sched
        if sched.dispatched_host:
            # faultlint-ok(uninjectable-io): host-lane collect (the
            # work never went to the device); the device seam consults
            # device.collect in _collect_device_bounded.
            return sched.collect_device(it.args, it.handles)
        try:
            t_col = time.perf_counter()
            res = self._collect_device_bounded(it)
            self._note_rtt(time.perf_counter() - t_col)
        except Exception as e:
            logger.warning("device collect failed (%s); re-running eval "
                           "on the host twin", e)
            self.breaker.record_failure(probe=it.probe)
            self._record_rerun()
            return self._host_rerun(it)
        if it.probe:
            host = self._host_rerun(it)
            chosen_d, scores_d = res
            chosen_h, scores_h = host
            # Identical by construction (tests/test_executor_parity.py
            # gates it); a mismatch here means the device path is
            # corrupting plans and MUST fail loudly, not degrade —
            # an explicit raise (not an assert, which -O would strip)
            # so the probe can never close the breaker unverified.
            if not (np.array_equal(np.asarray(chosen_d),
                                   np.asarray(chosen_h)) and
                    np.allclose(np.asarray(scores_d, dtype=np.float64),
                                np.asarray(scores_h, dtype=np.float64))):
                self.breaker.record_failure(probe=it.probe)
                raise RuntimeError(
                    "device/host parity violation on breaker probe")
            self.parity_checks += 1
            self.breaker.record_success(probe=True)
            return host
        self.breaker.record_success()
        return res

    def _collect_device_bounded(self, it: _Item) -> tuple:
        """Device collect with the optional watchdog deadline: a hung
        collect raises TimeoutError.  One long-lived worker is reused
        across collects (no thread churn on the drain hot path) and
        replaced only after a timeout — the abandoned worker drains its
        hung call whenever the device returns, then exits via the
        sentinel so it never lingers past that."""
        def _collect():
            if faultinject.ACTIVE:
                faultinject.fire("device.collect")
            return it.sched.collect_device(it.args, it.handles)

        if self.device_deadline is None:
            return _collect()
        worker = self._collect_worker
        if worker is None:
            worker = self._collect_worker = _CollectWorker()
        worker.inq.put(_collect)
        try:
            ok, val = worker.outq.get(timeout=self.device_deadline)
        except queue.Empty:
            # Hung: abandon this worker (its queues go with it, so the
            # stale result can never be mistaken for a later eval's)
            # and tell it to exit once the device call finally returns.
            self._collect_worker = None
            worker.inq.put(None)
            raise TimeoutError(
                f"device collect exceeded deadline "
                f"({self.device_deadline}s)") from None
        if not ok:
            raise val
        return val

    def _stop_collect_worker(self) -> None:
        worker = self._collect_worker
        if worker is not None:
            self._collect_worker = None
            worker.inq.put(None)
            worker.join(2.0)

    def _host_rerun(self, it: _Item) -> tuple:
        """Re-run one eval's placement on the host twin kernels."""
        handles = it.sched.dispatch_host(it.args)
        # faultlint-ok(uninjectable-io): host-twin rerun AFTER a device
        # fault — injecting here would fault the very fallback the
        # breaker depends on.
        return it.sched.collect_device(it.args, handles)
