"""Scheduler test + bench harness.

Capability parity with the reference's Harness rig
(/root/reference/scheduler/scheduler_test.go:14-177): a real StateStore plus
an in-memory Planner that applies plans directly to state and records
Plans/Evals/CreateEvals; `RejectPlan` injects plan-rejection faults to
exercise the refresh/retry path.  This is the primary TDD loop for both the
Python and the JAX schedulers, and the driver for bench.py.
"""
from __future__ import annotations

import itertools
import threading
from typing import Optional

from nomad_tpu.state import StateStore
from nomad_tpu.structs import Evaluation, Plan, PlanResult

from .interfaces import new_scheduler


class Harness:
    def __init__(self) -> None:
        self.state = StateStore()
        self.planner = None  # optional plan interceptor (e.g. RejectPlan)
        self.plans: list[Plan] = []
        self.evals: list[Evaluation] = []
        self.create_evals: list[Evaluation] = []
        self._lock = threading.Lock()
        self._next_index = itertools.count(1000)

    def next_index(self) -> int:
        return next(self._next_index)

    # -- Planner interface ------------------------------------------------
    def submit_plans(self, plans: list) -> list:
        """Group submit: one window of plans, results in plan order —
        identical to per-plan ``submit_plan`` calls in that order.
        Delegates to an interceptor's group path when it has one (the
        VerifyingPlanner's vectorized conflict window)."""
        with self._lock:
            self.plans.extend(plans)
        if self.planner is not None:
            group = getattr(self.planner, "submit_plans", None)
            if group is not None:
                return group(plans)
            return [self.planner.submit_plan(p) for p in plans]
        return [self._apply_direct(p) for p in plans]

    def submit_plan(self, plan: Plan) -> tuple[PlanResult, Optional[object]]:
        with self._lock:
            self.plans.append(plan)

        if self.planner is not None:
            return self.planner.submit_plan(plan)
        return self._apply_direct(plan)

    def _apply_direct(self, plan: Plan
                      ) -> tuple[PlanResult, Optional[object]]:
        """Apply the full plan directly to the state store."""
        index = self.next_index()
        allocs = []
        for updates in plan.node_update.values():
            allocs.extend(updates)
        for placements in plan.node_allocation.values():
            allocs.extend(placements)
        allocs.extend(plan.failed_allocs)
        self.state.upsert_allocs(index, allocs)

        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            failed_allocs=plan.failed_allocs,
            alloc_index=index,
        )
        return result, None

    def update_eval(self, ev: Evaluation) -> None:
        with self._lock:
            self.evals.append(ev)

    def create_eval(self, ev: Evaluation) -> None:
        with self._lock:
            self.create_evals.append(ev)

    # -- driving ----------------------------------------------------------
    def process(self, scheduler_name: str, ev: Evaluation) -> None:
        sched = new_scheduler(scheduler_name, self.state.snapshot(), self)
        sched.process(ev)

    def snapshot(self):
        return self.state.snapshot()


class RejectPlan:
    """Planner that rejects every plan with a state refresh, simulating
    leader-side plan rejection (fault injection for the retry path)."""

    def __init__(self, harness: Harness) -> None:
        self.harness = harness

    def submit_plan(self, plan: Plan):
        result = PlanResult(refresh_index=self.harness.state.latest_index())
        return result, self.harness.state.snapshot()

    def update_eval(self, ev: Evaluation) -> None:
        pass

    def create_eval(self, ev: Evaluation) -> None:
        pass


class VerifyingPlanner:
    """Leader plan-applier semantics over a Harness: verify each node's
    placements against live state (partial accept + RefreshIndex,
    server/plan_apply.evaluate_plan), commit only the accepted portion,
    and hand back a fresh snapshot when the scheduler must retry — the
    serialization point optimistic eval storms rely on in the real
    server.  Used by the fuzz rigs and bench config 5b (contended
    storm)."""

    def __init__(self, h: Harness) -> None:
        self.h = h
        self.conflicts = 0  # plans that came back partial/rejected
        # Group-commit observability (bench 5b fields):
        self.commits = 0            # commit operations (group or single)
        self.committed_plans = 0    # plans those commits carried
        self.conflict_fallbacks = 0  # window plans needing the exact
        #                              per-plan walk (prefix conflicts)

    def submit_plans(self, plans: list):
        """Group-commit twin of per-plan ``submit_plan``: one vectorized
        cross-plan conflict window (ops/plan_conflict.evaluate_window)
        plus ONE batched store upsert, with one index consumed per plan
        — results and final state byte-identical to calling
        ``submit_plan`` per plan in order."""
        from nomad_tpu.ops.plan_conflict import (_accepted_allocs,
                                                 evaluate_window)

        with self.h._lock:
            # devlint-ok(transfer-under-lock): the harness lock IS the
            # rig's serialization point (verify+commit must be atomic
            # for concurrent fuzz submitters); the device verify's
            # counted window-descriptor fetch under it is test-rig-only
            # — the real applier verifies on its own single thread.
            outcomes = evaluate_window(self.h.state, plans)
            items = []
            out = []
            for plan, outcome in zip(plans, outcomes):
                result = outcome.result
                allocs = _accepted_allocs(result)
                index = self.h.next_index()
                if allocs:
                    items.append((index, allocs))
                result.alloc_index = index
                if result.refresh_index:
                    self.conflicts += 1
                if outcome.fallback:
                    self.conflict_fallbacks += 1
                out.append(result)
            if items:
                self.h.state.upsert_allocs_batched(items)
                self.commits += 1
                self.committed_plans += len(items)
        # ONE post-commit snapshot shared by every refreshing plan —
        # the same view a retrying scheduler would get from the
        # sequential path's state_refresh hook (all of them see the
        # same post-window state).
        refreshed = None
        results = []
        for r in out:
            if r.refresh_index and refreshed is None:
                refreshed = self.h.state.snapshot()
            results.append((r, refreshed if r.refresh_index else None))
        return results

    def submit_plan(self, plan: Plan):
        from nomad_tpu.ops.plan_conflict import _accepted_allocs
        from nomad_tpu.server.plan_apply import evaluate_plan

        # No h.plans bookkeeping here: when reached through
        # Harness.submit_plan (h.planner delegation) the harness has
        # already recorded the plan.
        with self.h._lock:
            result = evaluate_plan(self.h.state, plan)
            allocs = _accepted_allocs(result)
            index = self.h.next_index()
            if allocs:
                self.h.state.upsert_allocs(index, allocs)
                self.commits += 1
                self.committed_plans += 1
            result.alloc_index = index
            if result.refresh_index:
                self.conflicts += 1
        state = self.h.state.snapshot() if result.refresh_index else None
        return result, state

    def update_eval(self, ev: Evaluation) -> None:
        self.h.update_eval(ev)

    def create_eval(self, ev: Evaluation) -> None:
        self.h.create_eval(ev)
