"""Scheduler test + bench harness.

Capability parity with the reference's Harness rig
(/root/reference/scheduler/scheduler_test.go:14-177): a real StateStore plus
an in-memory Planner that applies plans directly to state and records
Plans/Evals/CreateEvals; `RejectPlan` injects plan-rejection faults to
exercise the refresh/retry path.  This is the primary TDD loop for both the
Python and the JAX schedulers, and the driver for bench.py.
"""
from __future__ import annotations

import itertools
import threading
from typing import Optional

from nomad_tpu.state import StateStore
from nomad_tpu.structs import Evaluation, Plan, PlanResult

from .interfaces import new_scheduler


class Harness:
    def __init__(self) -> None:
        self.state = StateStore()
        self.planner = None  # optional plan interceptor (e.g. RejectPlan)
        self.plans: list[Plan] = []
        self.evals: list[Evaluation] = []
        self.create_evals: list[Evaluation] = []
        self._lock = threading.Lock()
        self._next_index = itertools.count(1000)

    def next_index(self) -> int:
        return next(self._next_index)

    # -- Planner interface ------------------------------------------------
    def submit_plan(self, plan: Plan) -> tuple[PlanResult, Optional[object]]:
        with self._lock:
            self.plans.append(plan)

        if self.planner is not None:
            return self.planner.submit_plan(plan)

        # Apply the full plan directly to the state store.
        index = self.next_index()
        allocs = []
        for updates in plan.node_update.values():
            allocs.extend(updates)
        for placements in plan.node_allocation.values():
            allocs.extend(placements)
        allocs.extend(plan.failed_allocs)
        self.state.upsert_allocs(index, allocs)

        result = PlanResult(
            node_update=plan.node_update,
            node_allocation=plan.node_allocation,
            failed_allocs=plan.failed_allocs,
            alloc_index=index,
        )
        return result, None

    def update_eval(self, ev: Evaluation) -> None:
        with self._lock:
            self.evals.append(ev)

    def create_eval(self, ev: Evaluation) -> None:
        with self._lock:
            self.create_evals.append(ev)

    # -- driving ----------------------------------------------------------
    def process(self, scheduler_name: str, ev: Evaluation) -> None:
        sched = new_scheduler(scheduler_name, self.state.snapshot(), self)
        sched.process(ev)

    def snapshot(self):
        return self.state.snapshot()


class RejectPlan:
    """Planner that rejects every plan with a state refresh, simulating
    leader-side plan rejection (fault injection for the retry path)."""

    def __init__(self, harness: Harness) -> None:
        self.harness = harness

    def submit_plan(self, plan: Plan):
        result = PlanResult(refresh_index=self.harness.state.latest_index())
        return result, self.harness.state.snapshot()

    def update_eval(self, ev: Evaluation) -> None:
        pass

    def create_eval(self, ev: Evaluation) -> None:
        pass


class VerifyingPlanner:
    """Leader plan-applier semantics over a Harness: verify each node's
    placements against live state (partial accept + RefreshIndex,
    server/plan_apply.evaluate_plan), commit only the accepted portion,
    and hand back a fresh snapshot when the scheduler must retry — the
    serialization point optimistic eval storms rely on in the real
    server.  Used by the fuzz rigs and bench config 5b (contended
    storm)."""

    def __init__(self, h: Harness) -> None:
        self.h = h
        self.conflicts = 0  # plans that came back partial/rejected

    def submit_plan(self, plan: Plan):
        from nomad_tpu.server.plan_apply import evaluate_plan

        # No h.plans bookkeeping here: when reached through
        # Harness.submit_plan (h.planner delegation) the harness has
        # already recorded the plan.
        with self.h._lock:
            result = evaluate_plan(self.h.state, plan)
            allocs: list = []
            for v in result.node_update.values():
                allocs.extend(v)
            for v in result.node_allocation.values():
                allocs.extend(v)
            allocs.extend(result.failed_allocs)
            index = self.h.next_index()
            if allocs:
                self.h.state.upsert_allocs(index, allocs)
            result.alloc_index = index
            if result.refresh_index:
                self.conflicts += 1
        state = self.h.state.snapshot() if result.refresh_index else None
        return result, state

    def update_eval(self, ev: Evaluation) -> None:
        self.h.update_eval(ev)

    def create_eval(self, ev: Evaluation) -> None:
        self.h.create_eval(ev)
