"""Batched optimistic scheduling: many evaluations, one device dispatch.

This is the TPU-native replacement for the reference's worker-pool
concurrency (reference nomad/worker.go:50-437 — NumCPU goroutines each
processing one eval at a time against its own snapshot).  Here a batch of
evaluations is reconciled on host, their placement sequences are stacked
along a vmap axis, and a single device dispatch plans ALL of them against
the same state snapshot.  Exactly like the reference's optimistic
concurrency, plans may conflict; the plan applier serializes commits and
rejected plans are retried individually (reference nomad/plan_apply.go).

Fast-path contract: an eval joins the fused dispatch only if its plan has no
deltas yet (no migrations/in-place updates), so every lane shares the same
base usage tensor — lanes diverge only through their own placements.  Evals
with plan deltas fall back to their own dispatch (still device-side).
"""
from __future__ import annotations

import numpy as np

from typing import Callable, Optional

from nomad_tpu.obs import trace as trace_mod
from nomad_tpu.structs import (
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    Evaluation,
)

from .generic import VALID_GENERIC_TRIGGERS
from .interfaces import SetStatusError
from .jax_binpack import JaxBinPackScheduler, fetch_results
from .util import set_status

def _tnow() -> float:
    """Tracer-epoch now, 0.0 when tracing is off (obs/trace.py)."""
    t = trace_mod.tracer()
    return t.now() if t is not None else 0.0


def pad_lanes(n: int) -> int:
    """Next power of two >= n (>= 1): the lane-axis bucket of the fused
    dispatch — the vmapped kernels trace once per distinct lane count,
    so the batch size must be bucketed exactly like the group and
    placement axes (models/fleet._pad_to) or a drifting storm recompiles
    per size."""
    return 1 << max(0, (n - 1).bit_length())


def _lane_spans(name: str, scheds, t0: float, t1: float, **tags) -> None:
    """One span per lane sharing the window's [t0, t1] — fused stages
    (dispatch, finish, submit) run once for the whole window, and every
    member eval's tree records the window it rode (the shared
    timestamps make the fusion visible in the exported trace)."""
    tracer = trace_mod.tracer() if trace_mod.ENABLED else None
    if tracer is None:
        # Includes a concurrent disable() racing the ENABLED check:
        # degrade to untraced, never fail the lane.
        return
    for sched in scheds:
        ev = sched.eval
        if ev is not None and ev.trace:
            tracer.record(name, t0, t1 - t0, parent_ctx=ev.trace,
                          eval_id=ev.id, **tags)


class BatchEvalRunner:
    """Fuses a batch of evaluations into one device dispatch.

    Per-job serialization: the eval broker guarantees at most one in-flight
    eval per job, so batches it hands out never collide.  When called
    directly with several evals for the SAME job, only the first joins each
    round; the rest run in follow-up rounds against a refreshed snapshot
    (``state_refresh``) so they see the earlier round's commits — without a
    refresh hook the leftovers would double-place, so they are then failed
    rather than silently over-scheduled.
    """

    # Fused retry rounds before the per-eval sequential fallback: 1 =
    # collect-then-serial (each retry sees every earlier retry's
    # commits).
    FUSED_RETRY_ROUNDS = 2

    def __init__(self, state, planner,
                 state_refresh: Optional[Callable] = None) -> None:
        self.state = state
        self.planner = planner
        self.state_refresh = state_refresh

    def _split_rounds(self, evals: list[Evaluation]
                      ) -> tuple[list, list]:
        """Serialize by job: one eval per job per round; the rest run in
        follow-up rounds against a refreshed snapshot."""
        seen_jobs: set = set()
        this_round, leftovers = [], []
        for ev in evals:
            if ev.job_id in seen_jobs:
                leftovers.append(ev)
            else:
                seen_jobs.add(ev.job_id)
                this_round.append(ev)
        return this_round, leftovers

    def _begin_eval(self, ev: Evaluation, finish_noop: bool = True):
        """Instantiate and reconcile one eval up to its deferred device
        args.  Returns the scheduler ready to dispatch, or None when the
        eval finished without needing a device dispatch (bad trigger,
        status error, or a plan with no placements).

        ``finish_noop=False`` returns the scheduler for a
        placement-less plan instead of submitting it here (deferred is
        None): the staged pipeline routes even those submits through
        its drain stage so plan-commit order stays eval order."""
        tracer = trace_mod.tracer() if trace_mod.ENABLED else None
        if tracer is not None:
            if not ev.trace:
                # Harness/bench evals arrive without a server-stamped
                # anchor: root their tree here so scheduler stages
                # still form one tree per eval.
                ev.trace = tracer.anchor("eval.created",
                                         eval_id=ev.id,
                                         eval_type=ev.type)
            t0 = tracer.now()
            try:
                return self._begin_eval_inner(ev, finish_noop)
            finally:
                tracer.record("sched.begin", t0, tracer.now() - t0,
                              parent_ctx=ev.trace, eval_id=ev.id)
        return self._begin_eval_inner(ev, finish_noop)

    def _begin_eval_inner(self, ev: Evaluation, finish_noop: bool = True):
        sched = JaxBinPackScheduler(self.state, self.planner,
                                    batch=(ev.type == "batch"))
        sched.eval = ev
        if ev.triggered_by not in VALID_GENERIC_TRIGGERS:
            set_status(self.planner, ev, None, EVAL_STATUS_FAILED,
                       f"scheduler cannot handle '{ev.triggered_by}' "
                       "evaluation reason")
            return None
        sched.defer_device = True
        try:
            sched._begin()
        except SetStatusError as e:
            set_status(self.planner, ev, None, e.eval_status, str(e))
            return None
        sched.defer_device = False
        if sched.deferred is None:
            if not finish_noop:
                return sched
            # No placements needed: submit stops/updates directly.
            self._finish(sched)
            return None
        return sched

    def process(self, evals: list[Evaluation]) -> None:
        from nomad_tpu.utils.gctune import gc_pause

        with gc_pause():
            pending = list(evals)
            # Fused retry rounds: lanes whose plans came back partial or
            # rejected re-plan TOGETHER against a refreshed snapshot —
            # under contention the applier's serialized conflicts, not
            # planning, dominate, and one fused round retries them all
            # for one dispatch.  Without a refresh hook (or for the
            # stragglers after the round cap) the exact per-eval
            # sequential retry gives the same terminal guarantee as the
            # single-eval worker path.
            rounds = self.FUSED_RETRY_ROUNDS \
                if self.state_refresh is not None else 1
            for _ in range(rounds):
                retries = [] if self.state_refresh is not None else None
                self._process(pending, retries)
                if not retries:
                    return
                pending = retries
                self.state = self.state_refresh()
            for ev in pending:
                retry = JaxBinPackScheduler(self.state, self.planner,
                                            batch=(ev.type == "batch"))
                retry.process(ev)

    def _process(self, evals: list[Evaluation],
                 retries: Optional[list] = None) -> None:
        from nomad_tpu.ops.binpack import place_sequence_batch

        this_round, leftovers = self._split_rounds(evals)

        pending = []  # (scheduler, place, DeviceArgs)
        for ev in this_round:
            sched = self._begin_eval(ev)
            if sched is None:
                continue
            place, args = sched.deferred
            if sched.plan.node_update or sched.plan.node_allocation:
                # Plan already carries deltas (migrations, in-place
                # updates): base usage differs, run its own dispatch.
                self._run_single(sched, place, args, retries)
                continue
            pending.append((sched, place, args))

        if not pending:
            if leftovers:
                self._process_leftovers(leftovers)
            return

        g_max = max(a.g_pad for _, _, a in pending)
        p_max = max(a.p_pad for _, _, a in pending)
        statics = pending[0][2].statics
        B = len(pending)
        # The lane axis is bucketed to a power of two exactly like the
        # group/placement axes (g_pad/p_pad): the vmapped kernels trace
        # per distinct lane count, and a storm whose batch size drifts
        # 3, 5, 6, ... would recompile per size (~0.5s each) — the
        # recompile-churn class devlint's provenance pass flags.  Pad
        # lanes are all-invalid (feasible/valid False, counts 0) and
        # place nothing; results are consumed per real lane only.
        B_pad = pad_lanes(B)
        rounds_ok = all(a.rounds_eligible for _, _, a in pending)
        k_cap = max(a.k_cap for _, _, a in pending)
        rounds = max(a.rounds for _, _, a in pending)

        # Executor policy (same trade as JaxBinPackScheduler.
        # choose_host_executor, and the same NOMAD_TPU_EXECUTOR
        # override): a fused dispatch pays one device round trip + a
        # [B, G, N] upload; below this op-count the numpy kernels
        # finish before the request would even reach the device.  The
        # host path reads each lane's arrays directly — no stacking.
        from .executor import (EXECUTOR_DEVICE, EXECUTOR_HOST,
                               executor_policy)

        policy = executor_policy()
        steps = rounds * g_max if rounds_ok else p_max
        fused_cost = B * steps * statics.n_real
        if policy == EXECUTOR_HOST or (
                policy != EXECUTOR_DEVICE and
                fused_cost <= JaxBinPackScheduler.HOST_SINGLE_SHOT_COST):
            self._finish_fused_host(pending, rounds_ok, k_cap, rounds,
                                    retries)
            if leftovers:
                self._process_leftovers(leftovers)
            return

        t_disp = _tnow()
        # Harmonize pad shapes across lanes, stack, one dispatch.
        feasible = np.zeros((B_pad, g_max, statics.n_pad), dtype=bool)
        asks = np.zeros((B_pad, g_max, pending[0][2].asks.shape[1]),
                        dtype=np.float32)
        distinct = np.zeros((B_pad, g_max), dtype=bool)
        group_idx = np.zeros((B_pad, p_max), dtype=np.int32)
        valid = np.zeros((B_pad, p_max), dtype=bool)
        job_counts = np.zeros((B_pad, statics.n_pad), dtype=np.int32)
        counts = np.zeros((B_pad, g_max), dtype=np.int32)
        for b, (_s, _p, a) in enumerate(pending):
            feasible[b, :a.g_pad] = a.feasible_h
            asks[b, :a.g_pad] = a.asks
            distinct[b, :a.g_pad] = a.distinct
            group_idx[b, :a.p_pad] = a.group_idx
            valid[b, :a.p_pad] = a.valid
            job_counts[b] = a.view.job_counts
            counts[b, :a.g_pad] = a.counts

        penalty = np.zeros(B_pad, dtype=np.float32)
        penalty[:B] = [a.penalty for _, _, a in pending]

        # Mesh resolution rides the ONE authority (parallel/mesh.py):
        # multi-chip agents automatically get the 2-D (lanes, fleet)
        # storm layout when the shape splits, NOMAD_TPU_MESH overrides.
        from nomad_tpu.parallel.mesh import dispatch_mesh

        mesh = dispatch_mesh(B_pad, statics.n_pad)
        # All fused lanes share the same snapshot base usage (fast-path
        # contract above); use the resident device copies when available
        # (single-device mirror copy, or on a mesh the sharded statics +
        # sharded usage mirror) so fleet tensors are not re-uploaded per
        # dispatch.
        view0 = pending[0][2].view
        if mesh is not None:
            capacity_d, reserved_d = \
                statics.device_capacity_reserved_sharded(mesh)
            base_usage = None
            if view0.usage_device is not None and \
                    statics.mirror is not None:
                base_usage = statics.mirror.device_usage_sharded(
                    mesh, view0.usage)
            if base_usage is None:
                base_usage = view0.usage  # mirror moved on: host upload
        else:
            from nomad_tpu.parallel.devices import put_counted

            capacity_d, reserved_d = statics.device_capacity_reserved()
            base_usage = put_counted(view0.dispatch_usage())
            # The per-dispatch lane stacks are fresh host arrays: place
            # them EXPLICITLY (counted) instead of letting jit commit
            # them implicitly — the fused dispatch's h2d bytes are part
            # of its honest cost, and the transfer-guard sanitizer
            # rejects the implicit form.  (The sharded wrappers below
            # _put their operands themselves.)
            feasible = put_counted(feasible)
            asks = put_counted(asks)
            distinct = put_counted(distinct)
            group_idx = put_counted(group_idx)
            valid = put_counted(valid)
            job_counts = put_counted(job_counts)
            counts = put_counted(counts)
            penalty = put_counted(penalty)
        if rounds_ok:
            # Fast path: top-k rounds — device steps scale with unique
            # groups x rounds, not with placements.
            from .jax_binpack import rounds_to_placements

            if mesh is not None:
                from nomad_tpu.parallel.mesh import \
                    place_rounds_batch_sharded

                chosen_s, score_s, _u = place_rounds_batch_sharded(
                    mesh, capacity_d, reserved_d, base_usage, job_counts,
                    feasible, asks, distinct, counts, penalty,
                    k_cap=k_cap, rounds=rounds)
            else:
                from nomad_tpu.ops.binpack import place_rounds_batch

                chosen_s, score_s, _u = place_rounds_batch(
                    capacity_d, reserved_d, base_usage, job_counts,
                    feasible, asks, distinct, counts, penalty,
                    k_cap=k_cap, rounds=rounds)
            chosen_s, score_s = fetch_results(chosen_s, score_s)
            _lane_spans("sched.dispatch", [s for s, _p, _a in pending],
                        t_disp, _tnow(), fused=B)
            done = []
            for b, (sched, place, args) in enumerate(pending):
                chosen, scores = rounds_to_placements(
                    args, chosen_s[b], score_s[b])
                done.append((sched, place, args, chosen, scores))
            self._finish_window(done, retries)
        else:
            if mesh is not None:
                from nomad_tpu.parallel.mesh import \
                    place_sequence_batch_sharded

                chosen, scores, _usage = place_sequence_batch_sharded(
                    mesh, capacity_d, reserved_d, base_usage, job_counts,
                    feasible, asks, distinct, group_idx, valid, penalty)
            else:
                chosen, scores, _usage = place_sequence_batch(
                    capacity_d, reserved_d, base_usage, job_counts,
                    feasible, asks, distinct, group_idx, valid, penalty)
            chosen, scores = fetch_results(chosen, scores)
            _lane_spans("sched.dispatch", [s for s, _p, _a in pending],
                        t_disp, _tnow(), fused=B)
            self._finish_window(
                [(sched, place, args, chosen[b], scores[b])
                 for b, (sched, place, args) in enumerate(pending)],
                retries)

        if leftovers:
            self._process_leftovers(leftovers)

    def _finish_fused_host(self, pending, rounds_ok, k_cap,
                           rounds, retries=None) -> None:
        """Host-executor twin of the fused dispatch: every lane plans
        against the same snapshot base usage via the numpy kernels, one
        lane at a time (each lane's kernel is vectorized over nodes),
        reading the lanes' own arrays — no [B, G, N] stacking."""
        from nomad_tpu.ops.binpack_host import (place_rounds_host,
                                                place_sequence_host)
        from .jax_binpack import rounds_to_placements

        statics = pending[0][2].statics
        base_usage = pending[0][2].view.usage  # host array
        n_real = statics.n_real
        done = []
        for sched, place, args in pending:
            t_disp = _tnow()
            if rounds_ok:
                chosen_s, score_s, _u = place_rounds_host(
                    statics.capacity, statics.reserved, base_usage,
                    args.view.job_counts, args.feasible_h, args.asks,
                    args.distinct, args.counts, float(args.penalty),
                    k_cap=k_cap, rounds=rounds, n_real=n_real)
                chosen, scores = rounds_to_placements(
                    args, chosen_s, score_s)
            else:
                chosen, scores, _u = place_sequence_host(
                    statics.capacity, statics.reserved, base_usage,
                    args.view.job_counts, args.feasible_h, args.asks,
                    args.distinct, args.group_idx, args.valid,
                    float(args.penalty), n_real=n_real)
            _lane_spans("sched.dispatch", [sched], t_disp, _tnow(),
                        host=True)
            done.append((sched, place, args, chosen, scores))
        self._finish_window(done, retries)

    def _process_leftovers(self, leftovers: list) -> None:
        if self.state_refresh is None:
            for ev in leftovers:
                set_status(self.planner, ev, None, EVAL_STATUS_FAILED,
                           "duplicate eval for job in one batch and no "
                           "state refresh available")
            return
        self.state = self.state_refresh()
        self.process(leftovers)

    def _run_single(self, sched, place, args, retries=None) -> None:
        t0 = _tnow()
        handles = sched.dispatch_device(args)
        # faultlint-ok(uninjectable-io): batch-lane device round-trip;
        # fault rehearsal (and the recovery path it needs) rides the
        # pipelined lane's device.dispatch/collect seam — a documented
        # gap, not an oversight.
        chosen, scores = sched.collect_device(args, handles)
        t1 = _tnow()
        _lane_spans("sched.dispatch", [sched], t0, t1)
        sched.finish_deferred(place, args, chosen, scores)
        _lane_spans("sched.finish", [sched], t1, _tnow())
        self._finish(sched, retries)

    @staticmethod
    def _finish_lanes(lanes: list) -> None:
        """Windowed finish for a list of lanes in lane order — ONE
        shared uuid slab (structs.generate_uuids) and ONE native call
        (native/port_alloc.cpp bulk_finish_many) cover every lane's
        happy-path prefix, then each lane's Python tail runs.  The one
        implementation of the windowed finish sequence, shared by the
        fused batch runner and the staged pipeline's drain stage.
        ``lanes`` is [(sched, place, args, chosen, scores), ...];
        semantics per lane are identical to ``finish_deferred``."""
        from nomad_tpu.structs import generate_uuids

        from .jax_binpack import _native_bulk

        t_fin = _tnow()

        uuid_slab = generate_uuids(
            sum(len(place) for _, place, *_ in lanes))
        states = []
        nargs = []
        off = 0
        for sched, place, args, chosen, scores in lanes:
            fs = sched._finish_prepare(place, args, chosen, scores,
                                       uuid_slab[off:off + len(place)])
            off += len(place)
            states.append(fs)
            nargs.append(sched._finish_native_args(fs))
        native = _native_bulk()
        # Columnar lanes (fs.slab set) batch through ONE
        # bulk_finish_many call; legacy object lanes (columnar contract
        # disabled) and mixed windows fall back to per-lane calls.
        if native is not None and hasattr(native, "bulk_finish_many") \
                and len(lanes) > 1 and all(a is not None for a in nargs) \
                and all(fs.slab is not None for fs in states):
            outs = native.bulk_finish_many(nargs)
            for (sched, *_rest), fs, out in zip(lanes, states, outs):
                sched._finish_consume_native(fs, out)
        else:
            for (sched, *_rest), fs, a in zip(lanes, states, nargs):
                if a is not None:
                    if fs.slab is not None:
                        sched._finish_consume_native(
                            fs, native.bulk_finish_cols(*a))
                    else:
                        sched._finish_consume_native(
                            fs, native.bulk_finish(*a))
        for (sched, *_rest), fs in zip(lanes, states):
            sched._finish_python_tail(fs)
        _lane_spans("sched.finish", [s for s, *_r in lanes],
                    t_fin, _tnow(), window=len(lanes))

    def _finish_window(self, done: list, retries=None) -> None:
        """Windowed finish + group submit for fused lanes
        (``_finish_lanes``), then every lane's plan submits as one group
        through the planner's window path (``submit_plans``) so the
        commit point is paid once per window, not per lane."""
        if not done:
            return
        self._finish_lanes(done)
        self._submit_window([sched for sched, *_rest in done], retries)

    def _submit_window(self, scheds: list, retries=None) -> None:
        """Submit a window of finished lanes' plans, preserving lane
        order and per-lane status semantics (see ``_finish``).  Uses the
        planner's group path when it has one; per-plan submits
        otherwise."""
        t_sub = _tnow()
        submitters = []
        for sched in scheds:
            ev = sched.eval
            try:
                done = sched._submit_begin()
            except SetStatusError as e:  # pragma: no cover - defensive
                set_status(self.planner, ev, sched.next_eval,
                           e.eval_status, str(e))
                continue
            if done is not None:
                set_status(self.planner, ev, sched.next_eval,
                           EVAL_STATUS_COMPLETE)
                continue
            submitters.append(sched)
        if not submitters:
            _lane_spans("sched.submit", scheds, t_sub, _tnow(),
                        window=len(scheds))
            return
        group = getattr(self.planner, "submit_plans", None)
        if group is not None and len(submitters) > 1:
            outs = group([s.plan for s in submitters])
        else:
            outs = [self.planner.submit_plan(s.plan)
                    for s in submitters]
        for sched, (result, state) in zip(submitters, outs):
            ev = sched.eval
            try:
                ok = sched._submit_finish(result, state)
            except SetStatusError as e:  # pragma: no cover - defensive
                set_status(self.planner, ev, sched.next_eval,
                           e.eval_status, str(e))
                continue
            if ok:
                set_status(self.planner, ev, sched.next_eval,
                           EVAL_STATUS_COMPLETE)
            elif retries is not None:
                retries.append(ev)  # no status yet: a later round owns it
            else:
                retry = JaxBinPackScheduler(
                    sched.state, self.planner, batch=(ev.type == "batch"))
                retry.process(ev)
        _lane_spans("sched.submit", scheds, t_sub, _tnow(),
                    window=len(scheds))

    def _finish(self, sched, retries=None) -> None:
        """Submit the plan; on rejection/partial commit either queue the
        eval for the next FUSED retry round (``retries`` list supplied)
        or fall back to the sequential retry loop (fresh scheduler,
        full process)."""
        ev = sched.eval
        try:
            ok = sched._submit()
        except SetStatusError as e:  # pragma: no cover - defensive
            set_status(self.planner, ev, sched.next_eval, e.eval_status,
                       str(e))
            return
        if ok:
            set_status(self.planner, ev, sched.next_eval,
                       EVAL_STATUS_COMPLETE)
        elif retries is not None:
            retries.append(ev)  # no status yet: a later round owns it
        else:
            retry = JaxBinPackScheduler(
                sched.state, self.planner, batch=(ev.type == "batch"))
            retry.process(ev)
