"""Feasibility filtering: lazy node iterators.

Capability parity with /root/reference/scheduler/feasible.go.  These stay the
sequential truth; the TPU backend compiles the same predicates into per-node
boolean mask tensors (nomad_tpu/models/constraints.py) and golden-parity
tests assert both agree node-for-node.
"""
from __future__ import annotations

from typing import Iterable, Optional

from nomad_tpu.structs import CONSTRAINT_DISTINCT_HOSTS, Constraint, Node
from nomad_tpu.utils.predicates import (  # noqa: F401 - re-exported API
    check_constraint_values,
    resolve_constraint_target,
)

from .context import EvalContext


class StaticIterator:
    """Yields nodes in fixed order; base of the System stack."""

    def __init__(self, ctx: EvalContext, nodes: Optional[list]) -> None:
        self.ctx = ctx
        self.nodes = nodes or []
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[Node]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        self.ctx.metrics().evaluate_node()
        return option

    def reset(self) -> None:
        self.seen = 0

    def set_nodes(self, nodes: list) -> None:
        self.nodes = nodes
        self.offset = 0
        self.seen = 0


def new_random_iterator(ctx: EvalContext, nodes: Optional[list],
                        rng=None) -> StaticIterator:
    """Fisher-Yates shuffle then static iteration; base of Generic stack."""
    from .util import shuffle_nodes

    nodes = nodes or []
    shuffle_nodes(nodes, rng)
    return StaticIterator(ctx, nodes)


class DriverIterator:
    """Filters nodes missing the task group's drivers ("driver.<name>"
    node attribute parse-bools to true)."""

    def __init__(self, ctx: EvalContext, source,
                 drivers: Optional[Iterable[str]] = None) -> None:
        self.ctx = ctx
        self.source = source
        self.drivers = set(drivers or ())

    def set_drivers(self, drivers: Iterable[str]) -> None:
        self.drivers = set(drivers)

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None:
                return None
            if self._has_drivers(option):
                return option
            self.ctx.metrics().filter_node(option, "missing drivers")

    def reset(self) -> None:
        self.source.reset()

    def _has_drivers(self, node: Node) -> bool:
        for driver in self.drivers:
            value = node.attributes.get(f"driver.{driver}")
            if value is None:
                return False
            if str(value).strip().lower() not in ("1", "t", "true"):
                return False
        return True


class ConstraintIterator:
    """Filters nodes violating hard constraints."""

    def __init__(self, ctx: EvalContext, source,
                 constraints: Optional[list] = None) -> None:
        self.ctx = ctx
        self.source = source
        self.constraints = constraints or []

    def set_constraints(self, constraints: list) -> None:
        self.constraints = constraints

    def next(self) -> Optional[Node]:
        while True:
            option = self.source.next()
            if option is None:
                return None
            if self._meets_constraints(option):
                return option

    def reset(self) -> None:
        self.source.reset()

    def _meets_constraints(self, node: Node) -> bool:
        for c in self.constraints:
            if not self._meets_constraint(c, node):
                self.ctx.metrics().filter_node(
                    node, f"{c.l_target} {c.operand} {c.r_target}")
                return False
        return True

    def _meets_constraint(self, c: Constraint, node: Node) -> bool:
        if not c.hard:
            return True  # soft constraints only affect ranking
        return check_single_constraint(self.ctx, c, node)


def check_single_constraint(ctx, c: Constraint, node: Node) -> bool:
    """Evaluate one hard constraint against a node (reference:
    feasible.go:197-223,259-376)."""
    if c.operand == CONSTRAINT_DISTINCT_HOSTS:
        # Feasible iff no proposed alloc of this job is on the node.  The
        # job id is carried via r_target by the stack (forward-port of
        # Nomad's ProposedAllocConstraintIterator).
        job_id = c.r_target
        if not job_id:
            return True
        return all(a.job_id != job_id
                   for a in ctx.proposed_allocs(node.id))

    l_val, ok = resolve_constraint_target(c.l_target, node)
    if not ok:
        return False
    r_val, ok = resolve_constraint_target(c.r_target, node)
    if not ok:
        return False
    return check_constraint_values(ctx, c.operand, l_val, r_val)
