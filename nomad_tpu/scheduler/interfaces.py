"""Scheduler contracts + registry.

Capability parity with /root/reference/scheduler/scheduler.go:13-87: the
scheduler layer is pure business logic behind two tiny seams — ``State`` (a
read snapshot) and ``Planner`` (submit plan / update + create eval).  All
plumbing (raft, queues, RPC) stays outside.  The registry carries the built-in
``service``/``batch``/``system`` schedulers plus the TPU-native
``jax-binpack`` backend, dispatched identically by the worker.
"""
from __future__ import annotations

from typing import Callable, Optional, Protocol

from nomad_tpu.structs import Allocation, Evaluation, Job, Node, Plan, PlanResult


class State(Protocol):
    """Immutable view of global state available to schedulers."""

    def nodes(self) -> list: ...
    def allocs_by_job(self, job_id: str) -> list: ...
    def allocs_by_node(self, node_id: str) -> list: ...
    def node_by_id(self, node_id: str) -> Optional[Node]: ...
    def job_by_id(self, job_id: str) -> Optional[Job]: ...


class Planner(Protocol):
    """Plan submission seam implemented by the worker (and test Harness)."""

    def submit_plan(self, plan: Plan) -> tuple[PlanResult, Optional[State]]: ...
    def update_eval(self, ev: Evaluation) -> None: ...
    def create_eval(self, ev: Evaluation) -> None: ...


class Scheduler(Protocol):
    def process(self, ev: Evaluation) -> None: ...


class SetStatusError(Exception):
    """Raised to set the evaluation status on unrecoverable failure."""

    def __init__(self, msg: str, eval_status: str) -> None:
        super().__init__(msg)
        self.eval_status = eval_status


Factory = Callable[[State, Planner], Scheduler]

BUILTIN_SCHEDULERS: dict[str, Factory] = {}


def register_scheduler(name: str, factory: Factory) -> None:
    BUILTIN_SCHEDULERS[name] = factory


def new_scheduler(name: str, state: State, planner: Planner) -> Scheduler:
    factory = BUILTIN_SCHEDULERS.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler {name!r}")
    return factory(state, planner)
