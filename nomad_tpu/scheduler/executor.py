"""Executor policy: which engine runs the placement kernels.

The jax-binpack scheduler picks between two executors per dispatch
(scheduler/jax_binpack.py choose_host_executor):

  host    numpy twin kernels (ops/binpack_host.py) — zero dispatch
          latency, wins whenever the workload is smaller than a device
          round trip (on remote-attached TPUs one dispatch costs a full
          network RTT, ~100 ms, regardless of compute size);
  device  jit kernels (ops/binpack.py) — wins for fused eval storms,
          multi-chip fleets, and pipelined streams deep enough to hide
          the RTT behind host work.

``auto`` (the default) applies the cost model.  ``host`` / ``device``
force one side — the bench's `4_device_pipelined` row, the multi-chip
dry run, and the host/device parity smoke all need a *forcible* device
path, and an operator diagnosing a slow chip wants the same lever
without editing code.

Resolution order (first set wins):

  1. the ``NOMAD_TPU_EXECUTOR`` environment variable — checked per
     dispatch so a bench or operator can flip it without a restart;
  2. the process policy set from agent/server config
     (``server { executor = "..." }``, plumbed via
     ``set_executor_policy`` at server boot);
  3. ``auto``.

The override only selects the executor; plan semantics are identical on
both sides (tests/test_executor_parity.py gates this on every run).
"""
from __future__ import annotations

import os

EXECUTOR_AUTO = "auto"
EXECUTOR_HOST = "host"
EXECUTOR_DEVICE = "device"

VALID_EXECUTORS = (EXECUTOR_AUTO, EXECUTOR_HOST, EXECUTOR_DEVICE)

ENV_VAR = "NOMAD_TPU_EXECUTOR"

_configured: str = EXECUTOR_AUTO


class ExecutorPolicyError(ValueError):
    pass


def _validate(value: str, source: str) -> str:
    v = (value or "").strip().lower()
    if v not in VALID_EXECUTORS:
        raise ExecutorPolicyError(
            f"invalid executor {value!r} from {source}: want one of "
            f"{', '.join(VALID_EXECUTORS)}")
    return v


def validate_executor(value: str, source: str = "config") -> str:
    """Public validation hook for config loaders: normalized value or
    ExecutorPolicyError."""
    return _validate(value, source)


def set_executor_policy(value: str) -> None:
    """Install the process-wide policy (config plumbing; env still
    wins).  Raises ExecutorPolicyError on unknown values so a typo in a
    config file fails the boot instead of silently running ``auto``."""
    global _configured
    _configured = _validate(value, "config")


def executor_policy() -> str:
    """The effective policy right now: env var, then configured value,
    then ``auto``.  Read per dispatch — cheap (one getenv) and it keeps
    the bench's scoped overrides race-free with respect to restarts."""
    env = os.environ.get(ENV_VAR)
    if env:
        return _validate(env, f"${ENV_VAR}")
    return _configured


class executor_override:
    """Scoped force of the executor (bench rows, parity tests).

    Sets the ENV override — the highest-precedence source — and restores
    the previous value on exit, so nesting and config interplay behave
    predictably.  Process-global like the env var itself; use from the
    thread that owns the run (the pipeline's stage threads read the
    policy only at dispatch time, on the submitting thread).
    """

    def __init__(self, value: str) -> None:
        self.value = _validate(value, "executor_override")
        self._saved: str | None = None

    def __enter__(self) -> "executor_override":
        self._saved = os.environ.get(ENV_VAR)
        os.environ[ENV_VAR] = self.value
        return self

    def __exit__(self, *exc) -> None:
        if self._saved is None:
            os.environ.pop(ENV_VAR, None)
        else:
            os.environ[ENV_VAR] = self._saved
