"""TPU-native scheduler backend: batched bin-packing on device.

Registered in the scheduler factory as ``jax-binpack`` (reference seam:
scheduler/scheduler.go:13-17 BuiltinSchedulers + nomad/worker.go:249 —
the worker dispatches it exactly like service/batch/system).

Architecture (NOT a port — reference walks nodes one iterator at a time,
scheduler/stack.go:126-153; we score the whole fleet per placement):

  host (this file)                         device (nomad_tpu/ops/binpack.py)
  ----------------                         ---------------------------------
  reconcile job vs allocs (diff/migrate)   .
  compile constraint masks (numpy)     ──► feasible[G, N] in HBM
  aggregate usage from MVCC store      ──► usage[N, D], job_counts[N]
  placement list (count expansion)     ──► lax.scan: fit -> score -> argmax
  exact port/bandwidth assignment      ◄── chosen[P], scores[P]
  plan construction / submit               .

The device mask is a sound over-approximation of network feasibility; the
exact NetworkIndex port assignment runs host-side on the winner, with a
sequential-stack fallback on the (rare) miss, so plans are exactly as valid
as the reference's (golden parity tests: tests/test_jax_binpack.py).
"""
from __future__ import annotations

import time

import numpy as np

from nomad_tpu.models.constraints import compile_group_mask, group_mask_key
from nomad_tpu.models.fleet import NDIMS, _pad_to, build_usage, fleet_cache
from nomad_tpu.ops.binpack import place_sequence
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_RUN,
    CONSTRAINT_DISTINCT_HOSTS,
    Allocation,
    NetworkIndex,
    allocs_fit,
    generate_uuid,
)

from .generic import GenericScheduler
from .stack import (
    BATCH_JOB_ANTI_AFFINITY_PENALTY,
    SERVICE_JOB_ANTI_AFFINITY_PENALTY,
)
from .util import ready_nodes_in_dcs, task_group_constraints


class DeviceArgs:
    """Everything one eval contributes to a (possibly batched) dispatch."""

    __slots__ = ("statics", "view", "feasible_d", "feasible_h", "asks",
                 "distinct", "group_idx", "valid", "sizes", "slot_of_tg",
                 "penalty", "g_pad", "p_pad", "start",
                 # rounds-mode plan (see ops/binpack.py place_rounds):
                 "counts", "slot_placements", "k_cap", "rounds",
                 "rounds_eligible")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)


class JaxBinPackScheduler(GenericScheduler):
    """GenericScheduler with the placement hot loop moved to TPU.

    ``defer_device=True`` pauses after argument preparation so a batch
    driver (nomad_tpu/scheduler/batch.py) can fuse many evals into one
    device dispatch; ``finish_deferred`` resumes with the device results.
    """

    defer_device = False

    def __init__(self, state, planner, batch: bool) -> None:
        super().__init__(state, planner, batch)
        self.deferred: tuple | None = None  # (place, DeviceArgs)

    def _proposed_allocs_all(self) -> list:
        """All non-terminal allocs under the in-flight plan: existing minus
        planned evictions plus planned placements (EvalContext.ProposedAllocs
        semantics, reference scheduler/context.go:96-126, fleet-wide)."""
        evicted = set()
        for updates in self.plan.node_update.values():
            evicted.update(a.id for a in updates)
        allocs = [a for a in self.state.allocs()
                  if not a.terminal_status() and a.id not in evicted]
        for placements in self.plan.node_allocation.values():
            allocs.extend(placements)
        return allocs

    def _compute_placements(self, place: list) -> None:
        args = self._prepare_device(place)
        if self.defer_device:
            self.deferred = (place, args)
            return
        capacity_d, reserved_d = args.statics.device_capacity_reserved()
        if args.rounds_eligible:
            from nomad_tpu.ops.binpack import place_rounds

            chosen_s, scores_s, _ = place_rounds(
                capacity_d, reserved_d, args.view.usage,
                args.view.job_counts, args.feasible_d, args.asks,
                args.distinct, args.counts, args.penalty,
                k_cap=args.k_cap, rounds=args.rounds)
            chosen, scores = rounds_to_placements(
                args, np.asarray(chosen_s), np.asarray(scores_s))
        else:
            chosen, scores, _ = place_sequence(
                capacity_d, reserved_d, args.view.usage,
                args.view.job_counts, args.feasible_d, args.asks,
                args.distinct, args.group_idx, args.valid, args.penalty)
            chosen, scores = np.asarray(chosen), np.asarray(scores)
        self.finish_deferred(place, args, chosen, scores)

    def _prepare_device(self, place: list) -> DeviceArgs:
        start = time.perf_counter()
        statics = fleet_cache.statics_for(self.state)
        view = build_usage(statics, self._proposed_allocs_all(),
                           job_id=self.job.id)

        # Dedupe task groups by *semantic* key (constraints + drivers + dc +
        # ask): count-expanded groups collapse to one mask row, keeping the
        # device feasibility matrix tiny and its upload cacheable.
        groups: list = []          # slot -> representative TaskGroup
        slot_keys: list = []       # slot -> semantic key
        sizes: list = []           # slot -> total Resources ask
        dedupe: dict = {}          # semantic key -> slot
        slot_of_tg: dict = {}      # id(tg) -> slot
        asks_rows: list = []
        distinct_rows: list = []
        for missing in place:
            tg = missing.task_group
            if id(tg) in slot_of_tg:
                continue
            tg_constr = task_group_constraints(tg)
            ask_vec = tuple(tg_constr.size.as_vector())
            dist = any(c.hard and c.operand == CONSTRAINT_DISTINCT_HOSTS
                       for c in self.job.constraints + tg_constr.constraints)
            key = (group_mask_key(self.job.datacenters, self.job.constraints,
                                  tg_constr.constraints, tg_constr.drivers),
                   ask_vec, dist)
            slot = dedupe.get(key)
            if slot is None:
                slot = len(groups)
                dedupe[key] = slot
                groups.append(tg)
                slot_keys.append(key)
                sizes.append(tg_constr.size)
                asks_rows.append(ask_vec)
                distinct_rows.append(dist)
            slot_of_tg[id(tg)] = slot

        g_pad = _pad_to(len(groups))
        p_pad = _pad_to(len(place))
        asks = np.zeros((g_pad, NDIMS), dtype=np.float32)
        asks[:len(groups)] = asks_rows
        distinct = np.zeros(g_pad, dtype=bool)
        distinct[:len(groups)] = distinct_rows

        # Feasibility matrix: composed per-slot host masks; the single-eval
        # path keeps a device-resident copy per (fleet generation, slot-key
        # tuple), the batch driver stacks the host copies instead.
        feas_key = ("feas", tuple(slot_keys), g_pad)
        cached = statics.device_cache.get(feas_key)
        if cached is None:
            feasible_h = np.zeros((g_pad, statics.n_pad), dtype=bool)
            for g, tg in enumerate(groups):
                tg_constr = task_group_constraints(tg)
                mask, _dist = compile_group_mask(
                    statics, self.job.datacenters, self.job.constraints,
                    tg_constr.constraints, tg_constr.drivers)
                feasible_h[g] = mask
            import jax
            feasible_d = jax.device_put(feasible_h)
            statics.device_cache[feas_key] = (feasible_h, feasible_d)
        else:
            feasible_h, feasible_d = cached

        group_idx = np.zeros(p_pad, dtype=np.int32)
        valid = np.zeros(p_pad, dtype=bool)
        slot_placements: dict = {}
        for p, missing in enumerate(place):
            slot = slot_of_tg[id(missing.task_group)]
            group_idx[p] = slot
            valid[p] = True
            slot_placements.setdefault(slot, []).append(p)

        penalty = BATCH_JOB_ANTI_AFFINITY_PENALTY if self.batch else \
            SERVICE_JOB_ANTI_AFFINITY_PENALTY

        # Rounds-mode plan: place a whole top-k batch of copies per device
        # step instead of one-per-step (ops/binpack.py place_rounds).
        # Greedy-equivalent when the anti-affinity penalty exceeds the
        # worst-case packing-score gain of one extra copy.
        counts = np.zeros(g_pad, dtype=np.int32)
        for slot, ps in slot_placements.items():
            counts[slot] = len(ps)
        avail = statics.capacity[:statics.n_real] - \
            statics.reserved[:statics.n_real]
        min_cpu = float(avail[:, 0].min()) if statics.n_real else 1.0
        min_mem = float(avail[:, 1].min()) if statics.n_real else 1.0
        eligible = statics.n_real > 0
        rounds = 1
        # top_k's k may not exceed the node axis: clamp and let extra
        # rounds make up the difference (a round places <= k_cap copies).
        k_cap = min(
            _pad_to(max((len(ps) for ps in slot_placements.values()),
                        default=1)),
            statics.n_pad)
        for slot, ps in slot_placements.items():
            frac_c = asks[slot, 0] / max(min_cpu, 1.0)
            frac_m = asks[slot, 1] / max(min_mem, 1.0)
            gain_bound = 10.0 * (1.0 - 10.0 ** (-frac_c)) + \
                10.0 * (1.0 - 10.0 ** (-frac_m))
            if gain_bound >= penalty * 0.95:
                eligible = False
                break
            feas_count = int(feasible_h[slot, :statics.n_real].sum())
            per_round = max(min(feas_count, k_cap), 1)
            need = -(-len(ps) // per_round)  # ceil
            if need > 4:
                eligible = False
                break
            rounds = max(rounds, need)

        return DeviceArgs(
            statics=statics, view=view, feasible_d=feasible_d,
            feasible_h=feasible_h, asks=asks, distinct=distinct,
            group_idx=group_idx, valid=valid, sizes=sizes,
            slot_of_tg=slot_of_tg, penalty=penalty, g_pad=g_pad,
            p_pad=p_pad, start=start, counts=counts,
            slot_placements=slot_placements, k_cap=k_cap, rounds=rounds,
            rounds_eligible=eligible)

    def finish_deferred(self, place: list, args: DeviceArgs,
                        chosen: np.ndarray, scores: np.ndarray) -> None:
        """Consume device decisions into the plan (exact host re-checks +
        network assignment + Allocation construction)."""
        statics = args.statics
        sizes = args.sizes
        slot_of_tg = args.slot_of_tg
        device_time = time.perf_counter() - args.start
        # Per-node NetworkIndex cache for this plan: built on first
        # placement on a node, then updated incrementally with each offer
        # (rebuilding from proposed allocs per placement dominated host
        # time at 10k nodes).
        self._net_cache: dict = {}

        failed_tg: dict = {}
        fallback_nodes = None
        # Once any placement deviates from the device's choice, the device
        # scan's usage accounting has diverged from the plan's, so every
        # later device winner must be re-verified host-side with the exact
        # allocs_fit before being trusted.
        usage_diverged = False
        for p, missing in enumerate(place):
            prior_fail = failed_tg.get(id(missing.task_group))
            if prior_fail is not None:
                prior_fail.metrics.coalesced_failures += 1
                continue

            g = slot_of_tg[id(missing.task_group)]
            size = sizes[g]
            node_index = int(chosen[p])
            option_node = statics.nodes[node_index] if node_index >= 0 else None
            from_device = option_node is not None

            task_resources = None
            if option_node is not None and usage_diverged and \
                    not self._still_fits(option_node, size):
                option_node = None
            if option_node is not None:
                task_resources = self._assign_networks(
                    option_node, missing.task_group)
                if task_resources is None:
                    option_node = None
            if option_node is None and from_device:
                # Device over-approximation admitted a node the exact
                # host accounting rejects: sequential fallback.
                usage_diverged = True
                if fallback_nodes is None:
                    fallback_nodes = ready_nodes_in_dcs(
                        self.state, self.job.datacenters)
                self.stack.set_nodes(list(fallback_nodes))
                ranked, size = self.stack.select(missing.task_group)
                if ranked is not None:
                    option_node = ranked.node
                    task_resources = ranked.task_resources
                    # The fallback assigned ports outside our per-node
                    # index cache: rebuild that node's index on next use.
                    self._net_cache.pop(option_node.id, None)
                # stack.select populated fresh ctx metrics (incl. scores).
                metrics = self.ctx.metrics()
            else:
                self.ctx.reset()
                metrics = self.ctx.metrics()
                metrics.nodes_evaluated = statics.n_real
                metrics.allocation_time = device_time / max(1, len(place))
                if option_node is not None:
                    metrics.score_node(option_node, "binpack",
                                       float(scores[p]))

            alloc = Allocation(
                id=generate_uuid(),
                eval_id=self.eval.id,
                name=missing.name,
                job_id=self.job.id,
                job=self.job,
                task_group=missing.task_group.name,
                resources=size,
                metrics=metrics,
            )
            if option_node is not None:
                alloc.node_id = option_node.id
                alloc.task_resources = task_resources
                alloc.desired_status = ALLOC_DESIRED_STATUS_RUN
                alloc.client_status = ALLOC_CLIENT_STATUS_PENDING
                self.plan.append_alloc(alloc)
            else:
                alloc.desired_status = ALLOC_DESIRED_STATUS_FAILED
                alloc.desired_description = \
                    "failed to find a node for placement"
                alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
                self.plan.append_failed(alloc)
                failed_tg[id(missing.task_group)] = alloc

    def _still_fits(self, node, size) -> bool:
        """Exact host-side allocs_fit re-check, used after the plan has
        deviated from the device scan's usage accounting."""
        proposed = self.ctx.proposed_allocs(node.id)
        fit, _dim, _util = allocs_fit(
            node, proposed + [Allocation(resources=size)])
        return fit

    def _assign_networks(self, node, tg):
        """Exact host-side port/bandwidth assignment on the device winner
        (BinPackIterator parity, reference scheduler/rank.go:180-205).
        Returns task name -> Resources, or None if the node can't take it."""
        cache = getattr(self, "_net_cache", None)
        net_idx = cache.get(node.id) if cache is not None else None
        if net_idx is None:
            net_idx = NetworkIndex()
            net_idx.set_node(node)
            net_idx.add_allocs(self.ctx.proposed_allocs(node.id))
            if cache is not None:
                cache[node.id] = net_idx
        staged = []
        out = {}
        for task in tg.tasks:
            task_resources = task.resources.copy()
            if task_resources.networks:
                ask = task_resources.networks[0]
                offer, _err = net_idx.assign_network(ask)
                if offer is None:
                    # Roll back offers staged for earlier tasks of this
                    # group so the cached index stays consistent.
                    for o in staged:
                        net_idx.remove_reserved(o)
                    return None
                net_idx.add_reserved(offer)
                staged.append(offer)
                task_resources.networks = [offer]
            out[task.name] = task_resources
        return out


def rounds_to_placements(args: DeviceArgs, chosen_slots: np.ndarray,
                         score_slots: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
    """Map place_rounds output ([G, rounds*k_cap] per-slot streams) back to
    per-placement arrays in the original placement order."""
    chosen = np.full(args.p_pad, -1, dtype=np.int32)
    scores = np.zeros(args.p_pad, dtype=np.float32)
    for slot, ps in args.slot_placements.items():
        stream = chosen_slots[slot]
        vals = score_slots[slot]
        taken = stream >= 0
        nodes = stream[taken]
        node_scores = vals[taken]
        n = min(len(ps), len(nodes))
        for j in range(n):
            chosen[ps[j]] = nodes[j]
            scores[ps[j]] = node_scores[j]
    return chosen, scores


def new_jax_binpack_scheduler(state, planner) -> JaxBinPackScheduler:
    return JaxBinPackScheduler(state, planner, batch=False)


def new_jax_binpack_batch_scheduler(state, planner) -> JaxBinPackScheduler:
    return JaxBinPackScheduler(state, planner, batch=True)
