"""TPU-native scheduler backend: batched bin-packing on device.

Registered in the scheduler factory as ``jax-binpack`` (reference seam:
scheduler/scheduler.go:13-17 BuiltinSchedulers + nomad/worker.go:249 —
the worker dispatches it exactly like service/batch/system).

Architecture (NOT a port — reference walks nodes one iterator at a time,
scheduler/stack.go:126-153; we score the whole fleet per placement):

  host (this file)                         device (nomad_tpu/ops/binpack.py)
  ----------------                         ---------------------------------
  reconcile job vs allocs (diff/migrate)   .
  compile constraint masks (numpy)     ──► feasible[G, N] in HBM
  aggregate usage from MVCC store      ──► usage[N, D], job_counts[N]
  placement list (count expansion)     ──► lax.scan: fit -> score -> argmax
  exact port/bandwidth assignment      ◄── chosen[P], scores[P]
  plan construction / submit               .

The device mask is a sound over-approximation of network feasibility; the
exact NetworkIndex port assignment runs host-side on the winner, with a
sequential-stack fallback on the (rare) miss, so plans are exactly as valid
as the reference's (golden parity tests: tests/test_jax_binpack.py).
"""
from __future__ import annotations

import time

import numpy as np

from random import randrange as _randrange

from nomad_tpu.models.constraints import compile_group_mask, group_mask_key
from nomad_tpu.models.fleet import (
    NDIMS,
    _pad_to,
    build_usage,
    fleet_cache,
    mirror_for,
    net_base_for,
)
from nomad_tpu.ops.binpack import place_sequence
from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_RUN,
    CONSTRAINT_DISTINCT_HOSTS,
    AllocMetric,
    Allocation,
    NetworkIndex,
    NetworkResource,
    Resources,
    allocs_fit,
    generate_uuids,
)
from nomad_tpu.structs.alloc_slab import (
    AllocSlab,
    SlabAlloc,
    columnar_enabled,
)
from nomad_tpu.structs.model import MAX_DYNAMIC_PORT, MIN_DYNAMIC_PORT

from .generic import GenericScheduler
from .stack import (
    BATCH_JOB_ANTI_AFFINITY_PENALTY,
    SERVICE_JOB_ANTI_AFFINITY_PENALTY,
)
from .util import ready_nodes_in_dcs, task_group_constraints


from nomad_tpu.structs.model import proto_of as _proto_of


_ALLOC_STATIC, _ALLOC_FACTORIES = _proto_of(Allocation)
_METRIC_STATIC, _METRIC_FACTORIES = _proto_of(AllocMetric)
_RES_STATIC, _RES_FACTORIES = _proto_of(Resources)
_NET_STATIC, _NET_FACTORIES = _proto_of(NetworkResource)

# Native bulk finish (native/port_alloc.cpp bulk_finish): available only
# when the C extension built.  Resolved once — the answer can't change
# within a process.  (AllocMetric's factory dicts are materialized
# lazily by AllocMetric.__getattr__, so the C side no longer creates
# them at all.)
_NATIVE_BULK_CACHE: list = []


def _native_bulk():
    if not _NATIVE_BULK_CACHE:
        from nomad_tpu.utils.native import HAS_NATIVE, native

        ok = HAS_NATIVE and hasattr(native, "bulk_finish")
        _NATIVE_BULK_CACHE.append(native if ok else None)
    return _NATIVE_BULK_CACHE[0]


def build_bulk_args(sched, place, group_l, chosen_l, scores_l,
                    uuids, slots_c, alloc_proto, metric_proto,
                    coalesce_all: int, port_lcg: int) -> tuple:
    """The native.bulk_finish argument tuple for one eval — the ONE
    producer of that layout, shared by the per-eval call
    (run_bulk_finish) and the pipeline's windowed bulk_finish_many
    (scheduler/pipeline.py drains a window of evals through a single
    native call)."""
    plan = sched.plan
    statics = sched._statics
    return (
        place if type(place) is list else list(place),
        group_l, chosen_l, scores_l, uuids, slots_c,
        statics.nodes, sched._node_net, statics.net_base,
        sched._net_base_for,
        sched.state.allocs_node_index(), sched.ctx, plan.node_update,
        plan.node_allocation, plan.failed_allocs,
        alloc_proto, metric_proto,
        Allocation, AllocMetric, Resources, NetworkResource,
        (ALLOC_DESIRED_STATUS_RUN, ALLOC_CLIENT_STATUS_PENDING,
         ALLOC_DESIRED_STATUS_FAILED, ALLOC_CLIENT_STATUS_FAILED,
         "failed to find a node for placement"),
        coalesce_all, port_lcg, MIN_DYNAMIC_PORT,
        MAX_DYNAMIC_PORT)


def run_bulk_finish(native, sched, place, group_l, chosen_l, scores_l,
                    uuids, slots_c, alloc_proto, metric_proto,
                    coalesce_all: int):
    """One marshalling point for native.bulk_finish (the C finish-loop
    happy path), shared by the generic and system schedulers.  ``sched``
    supplies the per-eval placement state (_node_net/_net_base_for/
    _port_lcg via FastPlacementMixin, plan, state, ctx).  Returns
    (resume index, failed-TG map); updates sched._port_lcg."""
    start_p, sched._port_lcg, fmap = native.bulk_finish(
        *build_bulk_args(sched, place, group_l, chosen_l, scores_l,
                         uuids, slots_c, alloc_proto, metric_proto,
                         coalesce_all, sched._port_lcg))
    return start_p, fmap


def build_slots_c(slot_plans) -> list:
    """Slot table for the native bulk finish (native/port_alloc.cpp):
    one (size_obj, [(task_name, res_proto_dict, net_c), ...]) entry per
    slot, where net_c is None or (mbits, net_proto_dict, dyn_labels).
    ``slot_plans`` yields (size, plan_tasks) pairs (see _net_plan_for).
    Shared by the generic and system schedulers so the layout the C
    side consumes has exactly one producer."""
    slots_c = []
    for size, plan_tasks in slot_plans:
        tasks_c = []
        for tname, res, ask in plan_tasks:
            if res is None:
                res_proto = dict(_RES_STATIC)
            else:
                res_proto = dict(
                    _RES_STATIC, cpu=res.cpu, memory_mb=res.memory_mb,
                    disk_mb=res.disk_mb, iops=res.iops)
            net_c = None
            if ask is not None:
                net_c = (int(ask.mbits),
                         dict(_NET_STATIC, mbits=ask.mbits),
                         list(ask.dynamic_ports))
            tasks_c.append((tname, res_proto, net_c))
        slots_c.append((size, tasks_c))
    return slots_c


def _net_plan_for(tg):
    """Per-slot network plan for the bulk finish path:
    (fast_ok, [(task_name, base_resources, net_ask | None), ...]).
    fast_ok means every ask is a single network with only dynamic ports —
    the shape the O(1)-per-placement assigner handles; anything richer
    routes through the exact NetworkIndex."""
    plan_tasks = []
    fast_ok = True
    for task in tg.tasks:
        r = task.resources
        ask = None
        if r is not None and r.networks:
            if len(r.networks) != 1 or r.networks[0].reserved_ports:
                fast_ok = False
            ask = r.networks[0]
        plan_tasks.append((task.name, r, ask))
    return fast_ok, plan_tasks


def fetch_results(*arrays) -> list:
    """Fetch device outputs with overlapped copies: start every
    device->host transfer asynchronously, then block once.  Two sequential
    fetches cost two full round trips on remote-attached TPUs (~100 ms
    each through the axon tunnel); this costs one.  The blocking fetch is
    EXPLICIT (jax.device_get via devices.fetch_host, counted) — this and
    collect_device are the sanctioned d2h seams of the scheduler, the
    ones the transfer-guard sanitizer and devlint's transfer-discipline
    pass leave open."""
    from nomad_tpu.parallel.devices import fetch_host

    for a in arrays:
        try:
            a.copy_to_host_async()
        except AttributeError:  # plain numpy already on host
            pass
    return [fetch_host(a) for a in arrays]


def _fit_rounds(statics, view, feasible_h, asks, slot_placements,
                k_cap: int, rounds: int) -> tuple[int, bool]:
    """Fit-aware rounds refresh, run on EVERY dispatch (the prep cache
    can't carry it — usage moves without the job/fleet generation
    moving).  One round places at most one copy per currently-fitting
    node, so the static (constraint-only) estimate goes stale as the
    fleet fills: with 100 copies, 160 constraint-feasible nodes but
    only 60 with room, rounds=1 strands 40 copies that the next round
    would place.  Still an estimate — nodes filling MID-dispatch can
    strand copies; the finish loop's sequential fallback rescues those
    exactly.  Returns (rounds, rounds_eligible); need > 16 rounds means
    the eval is scan-shaped and the sequence kernel takes it."""
    n = statics.n_real
    if n == 0 or not slot_placements:
        return rounds, True
    if max(len(ps) for ps in slot_placements.values()) <= rounds:
        # No slot can need more rounds than it has copies (need =
        # ceil(count / fitting) <= count), so the per-slot fit walk
        # cannot raise ``rounds`` — skip it.  This is the 100k-1M-node
        # heterogeneous-storm shape (thousands of count-1 slots): the
        # walk would cost O(slots x nodes x dims) numpy per eval for a
        # guaranteed no-op answer.
        return rounds, True
    cap = statics.capacity[:n]
    res = statics.reserved[:n]
    usage = np.asarray(view.usage)[:n]
    for slot, ps in slot_placements.items():
        fit = ((usage + res + asks[slot]) <= cap).all(axis=-1)
        fit_count = int((fit & feasible_h[slot, :n]).sum())
        if fit_count == 0:
            # Nothing can place for this slot right now: one cheap
            # dispatch suffices — the finish fallback coalesces and
            # explains the failures.
            continue
        need = -(-len(ps) // min(fit_count, k_cap))  # ceil
        if need > 16:
            # Scan-shaped (huge count on a tiny fitting set): the exact
            # sequence kernel takes it.
            return rounds, False
        rounds = max(rounds, need)
    # Bucket to powers of two: ``rounds`` is a static jit arg, and a
    # value drifting 1,2,3,... as the fleet fills would recompile the
    # kernel at every new value; buckets cap it at 5 signatures.
    if rounds > 1:
        rounds = 1 << (rounds - 1).bit_length()
    return min(rounds, 16), True


def _refresh_rounds(args: "DeviceArgs") -> "DeviceArgs":
    """Per-dispatch rounds refinement applied to every DeviceArgs (both
    the prep-cache hit and the fresh build) — ONE call site per return
    so the policy cannot desynchronize."""
    if args.rounds_eligible:
        args.rounds, args.rounds_eligible = _fit_rounds(
            args.statics, args.view, args.feasible_h, args.asks,
            args.slot_placements, args.k_cap, args.rounds)
    return args


class DeviceArgs:
    """Everything one eval contributes to a (possibly batched) dispatch."""

    __slots__ = ("statics", "view", "feasible_d", "feasible_h", "asks",
                 "distinct", "group_idx", "valid", "sizes", "slot_of_tg",
                 "penalty", "g_pad", "p_pad", "start", "net_plans",
                 "n_groups", "n_place",
                 # rounds-mode plan (see ops/binpack.py place_rounds):
                 "counts", "slot_placements", "k_cap", "rounds",
                 "rounds_eligible",
                 # finish-loop derivations shared via the prep cache:
                 # fast_all = every slot takes the O(1) network path;
                 # group_l = group_idx[:n_place].tolist(); slots_c is a
                 # one-element holder lazily filled with the native
                 # bulk-finish slot table (built on first finish);
                 # col_meta is the columnar twin — a one-element holder
                 # for (names, tg_names, slot_mbits, slot_ndyn,
                 # slot_has, port_off), the per-job-version constants of
                 # the AllocSlab contract (built on first columnar
                 # finish; shared read-only across the job's slabs —
                 # AllocSlab.patch_row copies before mutating).
                 "fast_all", "group_l", "slots_c", "col_meta",
                 # dev_const: lazily filled device copies of the
                 # dispatch-constant arrays (asks/distinct/counts or
                 # group_idx/valid), shared through the prep cache so a
                 # pipelined stream re-dispatching the same job version
                 # uploads them once, not per eval.  Kilobytes per job —
                 # unlike feasible_d these may ride the job-held cache
                 # without meaningfully pinning HBM.
                 "dev_const",
                 # feas_key: the statics.device_cache key of this eval's
                 # feasibility entry — the stable identity the sharded
                 # residency (FleetStatics.device_feasible_sharded) keys
                 # mesh-resident [G, N] rows on.
                 "feas_key")

    def __init__(self, **kw) -> None:
        for k, v in kw.items():
            setattr(self, k, v)


class _FinishState:
    """Per-eval state carried across the split finish phases
    (_finish_prepare -> native bulk -> _finish_python_tail) so the
    staged pipeline can batch the native phase of a whole drained
    window into one C call."""

    __slots__ = ("place", "args", "chosen_l", "scores_l", "uuids",
                 "alloc_proto", "metric_proto", "failed_tg", "start_p",
                 # Columnar contract: the AllocSlab the native phase
                 # fills (None = legacy object-emitting native path).
                 "slab")


class FastPlacementMixin:
    """Host-side placement machinery shared by the device-backed generic
    scheduler and the vectorized system scheduler: fleet-wide proposed
    allocs, exact + O(1) network assignment, and post-divergence fit
    re-checks.  Host classes provide self.state/self.plan/self.ctx and
    per-eval `_statics`/`_net_cache`/`_node_net`/`_port_lcg`."""

    def _proposed_allocs_all(self) -> list:
        """All non-terminal allocs under the in-flight plan: existing minus
        planned evictions plus planned placements (EvalContext.ProposedAllocs
        semantics, reference scheduler/context.go:96-126, fleet-wide)."""
        evicted = set()
        for updates in self.plan.node_update.values():
            evicted.update(a.id for a in updates)
        allocs = [a for a in self.state.allocs()
                  if not a.terminal_status() and a.id not in evicted]
        for placements in self.plan.node_allocation.values():
            allocs.extend(placements)
        return allocs

    def _net_base_for(self, node_index: int, node):
        """Node-static network base (frozen used-ports, reserved bw, bw
        capacity, ip, device) or None for topologies needing the exact
        path.  Cached on the fleet statics (models/fleet.net_base_for,
        shared with the plan verifier); also the callback the native
        bulk finish uses on a base-cache miss."""
        return net_base_for(self._statics, node_index, node)

    def _node_net_init(self, node_index: int, node):
        """Fast per-node network state: [used_ports, bw_used, bw_avail,
        ip, device], or None when the topology needs the exact path
        (multi-network nodes).  The reserved-only base is node-static and
        cached on the fleet statics; per-eval state adds proposed allocs'
        offers on top."""
        base = self._net_base_for(node_index, node)
        if base is None:
            return None
        used = set(base[0])
        bw_used = base[1]
        # O(1) emptiness probes (live, not precomputed: the plan grows
        # during the finish loop): only nodes with store allocs or plan
        # deltas need the exact proposed-alloc walk.
        node_id = node.id
        plan = self.plan
        if self.state.has_allocs_on_node(node_id) or \
                node_id in plan.node_update or \
                node_id in plan.node_allocation:
            for alloc in self.ctx.proposed_allocs(node_id):
                for tr in alloc.task_resources.values():
                    for offer in tr.networks:
                        used.update(offer.reserved_ports)
                        bw_used += offer.mbits
        return [used, bw_used, base[2], base[3], base[4]]

    def _assign_networks_fast(self, node_index: int, node, plan_tasks):
        """O(1) port/bandwidth assignment for single-network dynamic-port
        asks.  Returns task name -> Resources, or None to trigger the
        sequential fallback (exact semantics preserved: bandwidth bound +
        port uniqueness per node IP, reference nomad/structs/network.go)."""
        st = self._node_net.get(node_index)
        if st is None:
            st = self._node_net_init(node_index, node)
            if st is None:
                # Complex topology: exact path.
                return self._assign_networks(
                    node, None, plan_tasks=plan_tasks)
            self._node_net[node_index] = st
        used, bw_used, bw_avail, ip, device = st

        out = {}
        span = MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT
        staged_bw = 0
        mirrored = []   # offers mirrored into the cached exact-path index
        net_cache = self._net_cache
        for name, res, ask in plan_tasks:
            if ask is None:
                r = Resources.__new__(Resources)
                r.__dict__ = dict(
                    _RES_STATIC, networks=[],
                    cpu=res.cpu, memory_mb=res.memory_mb,
                    disk_mb=res.disk_mb, iops=res.iops) \
                    if res is not None else dict(_RES_STATIC, networks=[])
                out[name] = r
                continue
            if bw_used + staged_bw + ask.mbits > bw_avail:
                # Roll back staged ports — and the offers already mirrored
                # into the cached exact-path NetworkIndex, which would
                # otherwise carry phantom reservations into later
                # exact-path assignments on this node.
                for tr in out.values():
                    for offer in tr.networks:
                        used.difference_update(offer.reserved_ports)
                for offer in mirrored:
                    net_cache[node.id].remove_reserved(offer)
                return None
            ports = []
            lcg = self._port_lcg
            for _label in ask.dynamic_ports:
                # LCG instead of random.randrange: one multiply per port
                # (the plan seed is random, spreading ports like the
                # reference's random picks; exact value is untested API).
                lcg = (lcg * 1103515245 + 12345) & 0x3FFFFFFF
                port = MIN_DYNAMIC_PORT + lcg % span
                while port in used:
                    port = MIN_DYNAMIC_PORT + (port - MIN_DYNAMIC_PORT
                                               + 1) % span
                used.add(port)
                ports.append(port)
            self._port_lcg = lcg
            offer = NetworkResource.__new__(NetworkResource)
            offer.__dict__ = dict(
                _NET_STATIC, device=device, ip=ip, mbits=ask.mbits,
                reserved_ports=ports,
                dynamic_ports=list(ask.dynamic_ports))
            staged_bw += ask.mbits
            r = Resources.__new__(Resources)
            r.__dict__ = dict(
                _RES_STATIC, cpu=res.cpu, memory_mb=res.memory_mb,
                disk_mb=res.disk_mb, iops=res.iops, networks=[offer])
            out[name] = r
            # Keep an exact-path NetworkIndex for this node (if one was
            # built for a non-fast slot) coherent with our offers.
            if net_cache:
                idx = net_cache.get(node.id)
                if idx is not None:
                    idx.add_reserved(offer)
                    mirrored.append(offer)
        st[1] = bw_used + staged_bw
        return out

    def _node_index_of(self, node) -> int:
        statics = getattr(self, "_statics", None)
        if statics is not None:
            return statics.index_of.get(node.id, -1)
        return -1

    def _still_fits(self, node, size) -> bool:
        """Exact host-side allocs_fit re-check, used after the plan has
        deviated from the device scan's usage accounting."""
        proposed = self.ctx.proposed_allocs(node.id)
        fit, _dim, _util = allocs_fit(
            node, proposed + [Allocation(resources=size)])
        return fit

    def _assign_networks(self, node, tg, plan_tasks=None):
        """Exact host-side port/bandwidth assignment on the device winner
        (BinPackIterator parity, reference scheduler/rank.go:180-205).
        Returns task name -> Resources, or None if the node can't take it."""
        cache = getattr(self, "_net_cache", None)
        net_idx = cache.get(node.id) if cache is not None else None
        if net_idx is None:
            net_idx = NetworkIndex()
            net_idx.set_node(node)
            net_idx.add_allocs(self.ctx.proposed_allocs(node.id))
            if cache is not None:
                cache[node.id] = net_idx
        if plan_tasks is not None:
            items = [(name, res) for name, res, _ask in plan_tasks]
        else:
            items = [(t.name, t.resources) for t in tg.tasks]
        staged = []
        out = {}
        for task_name, res in items:
            task_resources = res.copy() if res is not None else Resources()
            if task_resources.networks:
                ask = task_resources.networks[0]
                offer, _err = net_idx.assign_network(ask)
                if offer is None:
                    # Roll back offers staged for earlier tasks of this
                    # group so the cached index stays consistent.
                    for o in staged:
                        net_idx.remove_reserved(o)
                    return None
                net_idx.add_reserved(offer)
                staged.append(offer)
                task_resources.networks = [offer]
            out[task_name] = task_resources
        # Keep the fast per-node state (if built) coherent with these
        # exact-path offers.
        node_net = getattr(self, "_node_net", None)
        if node_net:
            st = node_net.get(self._node_index_of(node))
            if st is not None:
                for o in staged:
                    st[0].update(o.reserved_ports)
                    st[1] += o.mbits
        return out


class JaxBinPackScheduler(GenericScheduler, FastPlacementMixin):
    """GenericScheduler with the placement hot loop moved to TPU.

    ``defer_device=True`` pauses after argument preparation so a batch
    driver (nomad_tpu/scheduler/batch.py) can fuse many evals into one
    device dispatch; ``finish_deferred`` resumes with the device results.
    """

    defer_device = False

    def __init__(self, state, planner, batch: bool) -> None:
        super().__init__(state, planner, batch)
        self.deferred: tuple | None = None  # (place, DeviceArgs)

    def _compute_placements(self, place: list) -> None:
        args = self._prepare_device(place)
        if self.defer_device:
            self.deferred = (place, args)
            return
        handles = self.dispatch_device(args)
        # faultlint-ok(uninjectable-io): synchronous compute lane (no
        # pipeline, no breaker); the injectable device seam is the
        # pipelined runner's dispatch/collect pair.
        chosen, scores = self.collect_device(args, handles)
        self.finish_deferred(place, args, chosen, scores)

    # Executor policy: estimated elementwise-op count (scan steps x node
    # axis) below which the numpy host kernels beat shipping the work to
    # the device.  A device dispatch has a fixed floor — one network round
    # trip (~100 ms) on remote-attached TPUs, ~100 us locally — so tiny
    # workloads always stay host-side; mid-size ones stay host-side only
    # when the caller isn't pipelining dispatches (a pipeline hides the
    # round trip behind host work, a single-shot eval eats it whole).
    HOST_ALWAYS_COST = 1 << 18       # ~sub-ms of numpy
    HOST_SINGLE_SHOT_COST = 1 << 25  # ~tens of ms, still << 1 RTT

    def choose_host_executor(self, args: "DeviceArgs",
                             pipelined: bool) -> bool:
        from .executor import (EXECUTOR_DEVICE, EXECUTOR_HOST,
                               executor_policy)

        policy = executor_policy()
        if policy == EXECUTOR_HOST:
            return True
        if policy == EXECUTOR_DEVICE:
            return False
        steps = args.rounds * args.n_groups if args.rounds_eligible \
            else args.n_place
        cost = steps * args.statics.n_real
        if cost <= self.HOST_ALWAYS_COST:
            return True
        return not pipelined and cost <= self.HOST_SINGLE_SHOT_COST

    # Which executor the last dispatch_device call actually used: True
    # host, False device, None when no dispatch ran yet.  The pipelined
    # runner reads this to report an honest device_fraction.
    dispatched_host: "bool | None" = None
    # Whether the last device dispatch ran node-axis-sharded over a
    # mesh (parallel/mesh.dispatch_mesh resolved one) — the runner's
    # sharded_dispatches counter and the bench's sharded rows read it.
    dispatched_sharded: "bool | None" = None

    def _dev_const(self, args: "DeviceArgs", key: str,
                   host_arrays: tuple) -> list:
        """Device-resident copies of dispatch-constant host arrays,
        cached on the DeviceArgs' shared dev_const holder (one upload
        per job version per platform, ensure_on_default re-validates
        across re-pins)."""
        from nomad_tpu.parallel.devices import ensure_on_default

        holder = args.dev_const.setdefault(key, [None] * len(host_arrays))
        for i, h in enumerate(host_arrays):
            holder[i] = ensure_on_default(holder[i], h)
        return holder

    def _dev_const_repl(self, args: "DeviceArgs", key: tuple, mesh,
                        host_arrays: tuple) -> list:
        """Mesh-replicated twins of the dispatch-constant arrays for
        the sharded path, cached on the same prep-shared dev_const
        holder as the default-device copies (one upload per job version
        per mesh — uploading kilobytes per EVAL measurably taxed the
        pipelined hot path, which is why _dev_const exists)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from nomad_tpu.parallel.mesh import _put

        holder = args.dev_const.setdefault(key, [None] * len(host_arrays))
        repl = NamedSharding(mesh, P())
        for i, h in enumerate(host_arrays):
            holder[i] = _put(h if holder[i] is None else holder[i], repl)
        return holder

    def dispatch_host(self, args: "DeviceArgs") -> tuple:
        """Run the placement kernels eagerly with numpy
        (ops/binpack_host.py) — same semantics, zero dispatch latency."""
        from nomad_tpu.ops.binpack_host import (place_rounds_host,
                                                place_sequence_host)

        self.dispatched_sharded = False
        statics = args.statics
        if args.rounds_eligible:
            chosen, scores, _ = place_rounds_host(
                statics.capacity, statics.reserved, args.view.usage,
                args.view.job_counts, args.feasible_h, args.asks,
                args.distinct, args.counts, args.penalty,
                k_cap=args.k_cap, rounds=args.rounds,
                n_real=statics.n_real)
        else:
            chosen, scores, _ = place_sequence_host(
                statics.capacity, statics.reserved, args.view.usage,
                args.view.job_counts, args.feasible_h, args.asks,
                args.distinct, args.group_idx, args.valid, args.penalty,
                n_real=statics.n_real)
        return chosen, scores

    def dispatch_device(self, args: "DeviceArgs",
                        pipelined: bool = False,
                        force: bool = False) -> tuple:
        """Start the device dispatch for prepared args WITHOUT blocking:
        the computation and its device->host result copies are left in
        flight, so a pipelined caller (scheduler/pipeline.py) can prep
        and dispatch the next eval while this one crosses the wire —
        on remote-attached TPUs a synchronous dispatch costs a full
        network round trip (~100 ms through the axon tunnel) no matter
        how small the compute.  Small workloads skip the device entirely
        (choose_host_executor) and come back as ready numpy arrays.

        ``force=True`` skips the executor check: the caller already
        decided (the pipelined runner's breaker admission must not be
        re-litigated here — a mid-flight policy flip would otherwise
        run host under an in-flight device probe and orphan it)."""
        if not force and self.choose_host_executor(args, pipelined):
            self.dispatched_host = True
            return self.dispatch_host(args)
        self.dispatched_host = False
        from nomad_tpu.parallel.mesh import dispatch_mesh

        mesh = dispatch_mesh(1, args.statics.n_pad)
        if mesh is not None:
            return self._dispatch_device_sharded(args, mesh)
        self.dispatched_sharded = False
        capacity_d, reserved_d = args.statics.device_capacity_reserved()
        feas_cached = args.feasible_d  # [host, device-or-None], lazy
        from nomad_tpu.parallel.devices import ensure_on_default, \
            put_counted
        feas_cached[1] = ensure_on_default(feas_cached[1], feas_cached[0])
        feasible_d = feas_cached[1]
        # Per-eval varying operands are placed EXPLICITLY (counted by
        # the transfer odometer): usage/job_counts genuinely change per
        # eval, so their upload is the honest per-eval transfer cost —
        # left to jit they were IMPLICIT transfers the odometer missed
        # and the transfer-guard sanitizer now rejects (devlint
        # transfer-in-hot-loop).  The penalty scalar is
        # dispatch-constant per job and rides the dev_const cache.
        usage_d = put_counted(args.view.dispatch_usage())
        jc_d = put_counted(args.view.job_counts)
        (pen_d,) = self._dev_const(
            args, "pen", (np.float32(args.penalty),))
        if args.rounds_eligible:
            from nomad_tpu.ops.binpack import place_rounds

            asks_d, distinct_d, counts_d = self._dev_const(
                args, "rounds", (args.asks, args.distinct, args.counts))
            chosen_s, scores_s, _ = place_rounds(
                capacity_d, reserved_d, usage_d, jc_d, feasible_d,
                asks_d, distinct_d, counts_d, pen_d,
                k_cap=args.k_cap, rounds=args.rounds)
        else:
            asks_d, distinct_d, group_idx_d, valid_d = self._dev_const(
                args, "seq", (args.asks, args.distinct, args.group_idx,
                              args.valid))
            chosen_s, scores_s, _ = place_sequence(
                capacity_d, reserved_d, usage_d, jc_d, feasible_d,
                asks_d, distinct_d, group_idx_d, valid_d, pen_d)
        for a in (chosen_s, scores_s):
            try:
                a.copy_to_host_async()
            except AttributeError:  # pragma: no cover - non-array backend
                pass
        return chosen_s, scores_s

    def _dispatch_device_sharded(self, args: "DeviceArgs", mesh) -> tuple:
        """Single-eval device dispatch with the node axis sharded over
        ``mesh`` — the first-class multi-chip path: capacity/reserved,
        this eval's feasibility rows, and the usage mirror's copy are
        all mesh-RESIDENT (uploaded once per fleet generation / job
        version / sync under the unified ShardedResidency policy), and
        the cross-shard argmax / top-k winner selection is resolved by
        XLA collectives (parallel/mesh.py kernels).  Placements are
        byte-identical to the unsharded kernels (tier-1
        tests/test_parallel.py pins it, ties included)."""
        from nomad_tpu.parallel.mesh import (place_rounds_sharded,
                                             place_sequence_sharded)

        self.dispatched_sharded = True
        statics = args.statics
        capacity_d, reserved_d = \
            statics.device_capacity_reserved_sharded(mesh)
        feasible_d = statics.device_feasible_sharded(
            mesh, args.feas_key, args.feasible_h)
        view = args.view
        usage = None
        if view.usage_device is not None and statics.mirror is not None:
            # The mirror's sharded twin IS this view's usage (the view
            # carried no plan deltas); None = the mirror moved past the
            # view, so the view's own host array uploads instead.
            usage = statics.mirror.device_usage_sharded(mesh, view.usage)
        if usage is None:
            usage = view.usage
        # Dispatch-constant penalty rides the prep-shared dev_const
        # holder like the asks (one replicated upload per job version
        # per mesh); the sharded wrappers _put every remaining operand
        # explicitly, so the whole sharded dispatch is implicit-free.
        (pen_d,) = self._dev_const_repl(
            args, ("pen", mesh), mesh, (np.float32(args.penalty),))
        if args.rounds_eligible:
            asks_d, distinct_d, counts_d = self._dev_const_repl(
                args, ("rounds", mesh), mesh,
                (args.asks, args.distinct, args.counts))
            chosen_s, scores_s, _u = place_rounds_sharded(
                mesh, capacity_d, reserved_d, usage, view.job_counts,
                feasible_d, asks_d, distinct_d, counts_d,
                pen_d, k_cap=args.k_cap, rounds=args.rounds)
        else:
            asks_d, distinct_d, group_idx_d, valid_d = \
                self._dev_const_repl(
                    args, ("seq", mesh), mesh,
                    (args.asks, args.distinct, args.group_idx,
                     args.valid))
            chosen_s, scores_s, _u = place_sequence_sharded(
                mesh, capacity_d, reserved_d, usage, view.job_counts,
                feasible_d, asks_d, distinct_d, group_idx_d,
                valid_d, pen_d)
        for a in (chosen_s, scores_s):
            try:
                a.copy_to_host_async()
            except AttributeError:  # pragma: no cover - non-array backend
                pass
        return chosen_s, scores_s

    def collect_device(self, args: "DeviceArgs", handles: tuple
                       ) -> tuple[np.ndarray, np.ndarray]:
        """Block on a dispatch's results and map them to per-placement
        (chosen, scores) arrays.  The d2h fetch is explicit and counted
        (devices.fetch_host) — this is a sanctioned collect seam."""
        from nomad_tpu.parallel.devices import fetch_host

        chosen, scores = (fetch_host(h) for h in handles)
        if args.rounds_eligible:
            chosen, scores = rounds_to_placements(args, chosen, scores)
        return chosen, scores

    def _derive_sem(self, job_sem_key, tg, job_triples, job_dist,
                    dcs_sorted):
        """One TG's semantic tuple: (job_key, dedupe key, ask vector,
        distinct_hosts, total Resources, net plan).  The single-task
        unconstrained shape (count expansion's output, and the dominant
        shape at 1k-group scale) takes a fused fast path with no
        intermediate object churn; its key exactly matches what the
        general path (group_mask_key) would produce for the same
        content, so fast- and general-path groups dedupe together."""
        tasks = tg.tasks
        if len(tasks) == 1 and not tg.constraints \
                and not tasks[0].constraints:
            task = tasks[0]
            r = task.resources
            ask = None
            mbits = ports = 0
            fast_ok = True
            if r is not None and r.networks:
                nets = r.networks
                if len(nets) != 1 or nets[0].reserved_ports:
                    fast_ok = False
                ask = nets[0]
                for n in nets:
                    mbits += n.mbits
                    ports += len(n.reserved_ports) + len(n.dynamic_ports)
            if r is None:
                size = Resources()
                ask_vec = (0, 0, 0, 0, 0, 0)
            else:
                # Networks are shared, not copied: `size` is only ever
                # read (as_vector/allocs_fit accumulate into their own
                # temporaries), same aliasing as the one-size-per-slot
                # sharing finish_deferred already does.
                size = Resources(cpu=r.cpu, memory_mb=r.memory_mb,
                                 disk_mb=r.disk_mb, iops=r.iops,
                                 networks=list(r.networks))
                ask_vec = (r.cpu, r.memory_mb, r.disk_mb, r.iops,
                           mbits, ports)
            key = ((dcs_sorted, job_triples, (task.driver,)), ask_vec,
                   job_dist)
            return (job_sem_key, key, ask_vec, job_dist, size,
                    (fast_ok, [(task.name, r, ask)]))
        tg_constr = task_group_constraints(tg)
        ask_vec = tuple(tg_constr.size.as_vector())
        dist = job_dist or any(
            c.hard and c.operand == CONSTRAINT_DISTINCT_HOSTS
            for c in tg_constr.constraints)
        key = (group_mask_key(self.job.datacenters, self.job.constraints,
                              tg_constr.constraints, tg_constr.drivers),
               ask_vec, dist)
        return (job_sem_key, key, ask_vec, dist, tg_constr.size,
                _net_plan_for(tg))

    def _prepare_device(self, place: list) -> DeviceArgs:
        start = time.perf_counter()
        statics = fleet_cache.statics_for(self.state)
        # Incremental usage: atomically sync the fleet's mirror to this
        # eval's snapshot (O(changed allocs) via the store changelog) and
        # take a view with this plan's in-flight deltas applied.  Falls
        # back to the from-scratch O(allocs) build only when the snapshot
        # is older than the mirror (another worker synced past us).
        view = mirror_for(statics).view_at(self.state, self.plan,
                                           self.job.id)
        if view is None:
            view = build_usage(statics, self._proposed_allocs_all(),
                               job_id=self.job.id)

        # Prep template cache: everything below is a pure function of
        # (job version, place list, fleet statics, batch flag).  The
        # fresh-placement diff (util.diff_allocs cache_fresh) hands out
        # an identity-stable place list per job version, so re-evals of
        # the same job against the same fleet (eval storms, plan-retry
        # attempts, node-update re-evals) skip the 1k-group derivation
        # entirely.  Cached fields are shared READ-ONLY across evals.
        job = self.job
        tmpl = job.__dict__.get("_prep_cache")
        if tmpl is not None and tmpl[0] == job.modify_index \
                and tmpl[1] == statics.gen and tmpl[2] is place \
                and tmpl[3] == self.batch:
            # Feasibility is re-fetched from the CURRENT statics'
            # device_cache (kw carries only the key): caching the
            # [host, device] entry on the job would pin evicted fleet
            # generations' HBM buffers for the job's lifetime.
            feas = statics.device_cache.get(tmpl[4])
            if feas is not None:
                return _refresh_rounds(DeviceArgs(
                    statics=statics, view=view, start=start,
                    feasible_d=feas, feasible_h=feas[0], **tmpl[5]))

        # Dedupe task groups by *semantic* key (constraints + drivers + dc +
        # ask): count-expanded groups collapse to one mask row, keeping the
        # device feasibility matrix tiny and its upload cacheable.  The
        # derived key/ask/net-plan is cached ON the TaskGroup object —
        # store-resident objects are immutable by contract (state/store.py)
        # and every store write copies, so identity is a sound cache key;
        # re-deriving it per eval dominated prep at 1k groups/job.
        groups: list = []          # slot -> representative TaskGroup
        slot_keys: list = []       # slot -> semantic key
        sizes: list = []           # slot -> total Resources ask
        net_plans: list = []       # slot -> (fast_ok, plan_tasks)
        dedupe: dict = {}          # semantic key -> slot
        slot_of_tg: dict = {}      # id(tg) -> slot
        asks_rows: list = []
        distinct_rows: list = []
        job_sem_key = (id(job), job.modify_index)
        # Job-level pieces of the semantic key, derived once per eval (the
        # per-TG loop below is the host hot path at 1k groups/job).
        jc = job.constraints
        job_triples = tuple(sorted(
            (c.l_target, c.operand, c.r_target) for c in jc
            if c.hard and c.operand != CONSTRAINT_DISTINCT_HOSTS))
        job_dist = any(c.hard and c.operand == CONSTRAINT_DISTINCT_HOSTS
                       for c in jc)
        dcs_sorted = tuple(sorted(job.datacenters))
        for missing in place:
            tg = missing.task_group
            if id(tg) in slot_of_tg:
                continue
            sem = tg.__dict__.get("_sem_cache")
            if sem is None or sem[0] != job_sem_key:
                sem = self._derive_sem(job_sem_key, tg, job_triples,
                                       job_dist, dcs_sorted)
                tg.__dict__["_sem_cache"] = sem
            _jk, key, ask_vec, dist, size, net_plan = sem
            slot = dedupe.get(key)
            if slot is None:
                slot = len(groups)
                dedupe[key] = slot
                groups.append(tg)
                slot_keys.append(key)
                sizes.append(size)
                net_plans.append(net_plan)
                asks_rows.append(ask_vec)
                distinct_rows.append(dist)
            slot_of_tg[id(tg)] = slot

        g_pad = _pad_to(len(groups))
        p_pad = _pad_to(len(place))
        asks = np.zeros((g_pad, NDIMS), dtype=np.float32)
        asks[:len(groups)] = asks_rows
        distinct = np.zeros(g_pad, dtype=bool)
        distinct[:len(groups)] = distinct_rows

        # Feasibility matrix: composed per-slot host masks; the single-eval
        # path keeps a device-resident copy per (fleet generation, slot-key
        # tuple), the batch driver stacks the host copies instead.
        feas_key = ("feas", tuple(slot_keys), g_pad)
        cached = statics.device_cache.get(feas_key)
        if cached is None:
            feasible_h = np.zeros((g_pad, statics.n_pad), dtype=bool)
            for g, tg in enumerate(groups):
                tg_constr = task_group_constraints(tg)
                mask, _dist = compile_group_mask(
                    statics, self.job.datacenters, self.job.constraints,
                    tg_constr.constraints, tg_constr.drivers)
                feasible_h[g] = mask
            # Device copy is lazy (filled on first device dispatch) so
            # host-executor evals never touch the device at all.
            cached = [feasible_h, None]
            statics.device_cache[feas_key] = cached
        feasible_h = cached[0]

        group_idx = np.zeros(p_pad, dtype=np.int32)
        valid = np.zeros(p_pad, dtype=bool)
        slot_placements: dict = {}
        for p, missing in enumerate(place):
            slot = slot_of_tg[id(missing.task_group)]
            group_idx[p] = slot
            valid[p] = True
            slot_placements.setdefault(slot, []).append(p)

        penalty = BATCH_JOB_ANTI_AFFINITY_PENALTY if self.batch else \
            SERVICE_JOB_ANTI_AFFINITY_PENALTY

        # Rounds-mode plan: place a whole top-k batch of copies per device
        # step instead of one-per-step (ops/binpack.py place_rounds).
        # Greedy-equivalent when the anti-affinity penalty exceeds the
        # worst-case packing-score gain of one extra copy.
        counts = np.zeros(g_pad, dtype=np.int32)
        for slot, ps in slot_placements.items():
            counts[slot] = len(ps)
        avail = statics.capacity[:statics.n_real] - \
            statics.reserved[:statics.n_real]
        min_cpu = float(avail[:, 0].min()) if statics.n_real else 1.0
        min_mem = float(avail[:, 1].min()) if statics.n_real else 1.0
        eligible = statics.n_real > 0
        rounds = 1
        # top_k's k may not exceed the node axis: clamp and let extra
        # rounds make up the difference (a round places <= k_cap copies).
        k_cap = min(
            _pad_to(max((len(ps) for ps in slot_placements.values()),
                        default=1)),
            statics.n_pad)
        for slot, ps in slot_placements.items():
            frac_c = asks[slot, 0] / max(min_cpu, 1.0)
            frac_m = asks[slot, 1] / max(min_mem, 1.0)
            gain_bound = 10.0 * (1.0 - 10.0 ** (-frac_c)) + \
                10.0 * (1.0 - 10.0 ** (-frac_m))
            if gain_bound >= penalty * 0.95:
                eligible = False
                break
            # Rounds themselves are estimated fit-aware per dispatch by
            # _refresh_rounds — the one producer of that policy.

        kw = dict(
            asks=asks, distinct=distinct,
            group_idx=group_idx, valid=valid, sizes=sizes,
            slot_of_tg=slot_of_tg, penalty=penalty, g_pad=g_pad,
            p_pad=p_pad, net_plans=net_plans, counts=counts,
            n_groups=len(groups), n_place=len(place),
            slot_placements=slot_placements, k_cap=k_cap, rounds=rounds,
            rounds_eligible=eligible,
            fast_all=all(np_[0] for np_ in net_plans),
            group_l=group_idx[:len(place)].tolist(), slots_c=[None],
            col_meta=[None], dev_const={}, feas_key=feas_key)
        # Keyed on the fleet GENERATION, not the statics object: a strong
        # statics ref here would pin evicted generations (device
        # feasibility buffers included) for as long as the job lives.
        # Same reason the feasibility entry is cached by KEY.
        job.__dict__["_prep_cache"] = (job.modify_index, statics.gen, place,
                                       self.batch, feas_key, kw)
        return _refresh_rounds(DeviceArgs(
            statics=statics, view=view, start=start,
            feasible_d=cached, feasible_h=feasible_h, **kw))

    def finish_deferred(self, place: list, args: DeviceArgs,
                        chosen: np.ndarray, scores: np.ndarray,
                        uuids: "list | None" = None) -> None:
        """Consume device decisions into the plan (exact host re-checks +
        network assignment + Allocation construction).

        Split into three phases so the staged pipeline
        (scheduler/pipeline.py) can run a whole drained window's native
        phase in ONE C call (native.bulk_finish_many) and pass a shared
        uuid slab: prepare (host state init), native happy-path prefix,
        Python tail.  This entry point runs them back-to-back — the
        single-eval semantics are unchanged."""
        fs = self._finish_prepare(place, args, chosen, scores, uuids)
        nargs = self._finish_native_args(fs)
        if nargs is not None:
            native = _native_bulk()
            if fs.slab is not None:
                self._finish_consume_native(
                    fs, native.bulk_finish_cols(*nargs))
            else:
                self._finish_consume_native(
                    fs, native.bulk_finish(*nargs))
        self._finish_python_tail(fs)

    def _finish_prepare(self, place: list, args: DeviceArgs,
                        chosen, scores,
                        uuids: "list | None" = None) -> "_FinishState":
        """Host-side finish state for one eval: per-plan network caches,
        alloc/metric protos, list-form device choices, uuids (minted
        here unless the pipeline passed a shared slab slice)."""
        statics = args.statics
        device_time = time.perf_counter() - args.start
        per_time = device_time / max(1, len(place))
        # Per-node NetworkIndex cache for this plan (exact path) and the
        # fast per-node [used_ports, bw_used, bw_avail, ip, device] state.
        self._net_cache: dict = {}
        self._node_net: dict = {}
        self._statics = statics
        self._port_lcg = _randrange(1 << 30)

        fs = _FinishState()
        fs.place = place
        fs.args = args
        fs.chosen_l = chosen if type(chosen) is list else chosen.tolist()
        fs.scores_l = scores if type(scores) is list else scores.tolist()
        fs.uuids = uuids if uuids is not None else \
            generate_uuids(len(place))
        # Template-based construction (see _proto_of): the finish loop
        # builds one AllocMetric + Allocation per placement.
        fs.metric_proto = dict(_METRIC_STATIC,
                               nodes_evaluated=statics.n_real,
                               allocation_time=per_time)
        fs.alloc_proto = dict(_ALLOC_STATIC, eval_id=self.eval.id,
                              job_id=self.job.id, job=self.job)
        fs.failed_tg = {}
        fs.start_p = 0
        fs.slab = None
        return fs

    def _finish_native_args(self, fs: "_FinishState") -> "tuple | None":
        """Native argument tuple for this eval's happy-path prefix —
        columnar (bulk_finish_cols + an AllocSlab, ``fs.slab`` set) by
        default, the legacy object-emitting bulk_finish tuple when the
        columnar contract is disabled — or None when the native path
        can't take it (extension absent, or a slot needs the exact
        NetworkIndex)."""
        args = fs.args
        native = _native_bulk()
        if native is None or not args.fast_all:
            return None
        slots_c = args.slots_c[0]
        if slots_c is None:
            # Built once per (job version, fleet) and shared through
            # the prep cache — the slot table only depends on the
            # deduped net plans and sizes.
            slots_c = build_slots_c(
                (args.sizes[g], args.net_plans[g][1])
                for g in range(args.n_groups))
            args.slots_c[0] = slots_c
        if not columnar_enabled() or \
                not hasattr(native, "bulk_finish_cols"):
            return build_bulk_args(
                self, fs.place, args.group_l, fs.chosen_l, fs.scores_l,
                fs.uuids, slots_c, fs.alloc_proto, fs.metric_proto,
                1,  # coalesce_all: generic TG placements interchangeable
                self._port_lcg)
        meta = args.col_meta[0]
        if meta is None:
            # Per-job-version constants of the columnar contract:
            # per-row names, per-slot network totals, and the prefix
            # offsets into the flat port column.  The place list is
            # identity-stable per job version (util.diff_allocs
            # cache_fresh), so these ride the prep cache like slots_c.
            place = fs.place
            names = [m.name for m in place]
            tg_names = [m.task_group.name for m in place]
            slot_mbits = []
            slot_ndyn = []
            slot_has = []
            for _size, tasks in slots_c:
                mb = nd = 0
                any_net = False
                for _t, _rp, net_c in tasks:
                    if net_c is not None:
                        any_net = True
                        mb += net_c[0]
                        nd += len(net_c[2])
                slot_mbits.append(mb)
                slot_ndyn.append(nd)
                slot_has.append(any_net)
            port_off = np.zeros(len(place) + 1, dtype=np.int64)
            if place:
                np.cumsum(np.asarray(slot_ndyn, dtype=np.int64)[
                    np.asarray(args.group_l, dtype=np.int64)],
                    out=port_off[1:])
            meta = (names, tg_names, slot_mbits, slot_ndyn, slot_has,
                    port_off)
            args.col_meta[0] = meta
        names, tg_names, slot_mbits, slot_ndyn, slot_has, port_off = meta
        slab = AllocSlab(
            eval_id=self.eval.id, job=self.job, slots=slots_c,
            metric_proto=fs.metric_proto, groups=args.group_l,
            ids=fs.uuids, names=names, tgs=tg_names,
            scores=fs.scores_l, port_off=port_off,
            n_rows=len(fs.place),
            slot_mbits=slot_mbits, slot_has_net=slot_has)
        fs.slab = slab
        lazy_proto = {
            "eval_id": self.eval.id, "job_id": self.job.id,
            "job": self.job,
            "desired_status": ALLOC_DESIRED_STATUS_RUN,
            "client_status": ALLOC_CLIENT_STATUS_PENDING,
            "_slab": slab,
        }
        return (fs.chosen_l, args.group_l, fs.uuids, names, tg_names,
                slot_mbits, slot_ndyn, slab.ports, slab.node_ids,
                slab.ips, slab.devs, lazy_proto, SlabAlloc,
                self._statics.nodes, self._node_net,
                self._statics.net_base, self._net_base_for,
                self.state.allocs_node_index(), self.ctx,
                self.plan.node_update, self.plan.node_allocation,
                self._port_lcg, MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)

    def _finish_consume_native(self, fs: "_FinishState",
                               result: tuple) -> None:
        """Fold one native finish result back into the finish state.
        Columnar path: (n_done, lcg) — the slab seals its happy prefix.
        Object path: (n_done, lcg, failed map); fmap stays empty under
        generic semantics — the C loop bails on a task group's first
        chosen-less placement so the Python tail can rescue or explain
        it."""
        if fs.slab is not None:
            fs.start_p, self._port_lcg = result
            fs.slab.seal(fs.start_p)
            return
        fs.start_p, self._port_lcg, fmap = result
        fs.failed_tg.update(fmap)

    def _finish_python_tail(self, fs: "_FinishState") -> None:
        """Per-placement Python finish loop from fs.start_p: exact host
        re-checks, network assignment, Allocation construction.  The
        native prefix (parity-tested in tests/test_native_finish.py)
        handled [0, start_p); this loop owns complex topologies,
        divergence recovery and failure explanation."""
        place = fs.place
        args = fs.args
        statics = args.statics
        sizes = args.sizes
        slot_of_tg = args.slot_of_tg
        net_plans = args.net_plans
        chosen_l = fs.chosen_l
        scores_l = fs.scores_l
        uuids = fs.uuids
        nodes_arr = statics.nodes
        plan = self.plan
        metric_proto = fs.metric_proto
        alloc_proto = fs.alloc_proto
        failed_tg = fs.failed_tg

        def fast_metric(score_key=None, score=0.0) -> AllocMetric:
            # Lazy form: factory dicts + the scores dict materialize on
            # first read (AllocMetric.__getattr__).
            m = AllocMetric.__new__(AllocMetric)
            d = dict(metric_proto)
            if score_key is not None:
                d["_lazy_score_key"] = score_key
                d["_lazy_score_val"] = score
            m.__dict__ = d
            return m

        # slot -> explained failure metrics: identical groups share one
        # fleet-walk verdict (usage is monotone within a finish pass).
        failed_slots: dict = {}
        fallback_nodes = None
        # Once any placement deviates from the device's choice, the device
        # scan's usage accounting has diverged from the plan's, so every
        # later device winner must be re-verified host-side with the exact
        # allocs_fit before being trusted.
        usage_diverged = False
        # One-shot vectorized recovery: on the first divergence the whole
        # remaining tail is re-planned by the exact host kernel instead
        # of falling into a per-placement sequential walk.
        redispatched = False

        p = fs.start_p
        while p < len(place):
            missing = place[p]
            tg = missing.task_group
            prior_fail = failed_tg.get(id(tg))
            if prior_fail is not None:
                prior_fail.metrics.coalesced_failures += 1
                p += 1
                continue

            g = slot_of_tg[id(tg)]
            size = sizes[g]
            node_index = chosen_l[p]
            option_node = nodes_arr[node_index] if node_index >= 0 else None
            from_device = option_node is not None

            task_resources = None
            if option_node is not None and usage_diverged and \
                    not self._still_fits(option_node, size):
                option_node = None
            if option_node is not None:
                fast_ok, plan_tasks = net_plans[g]
                if fast_ok:
                    task_resources = self._assign_networks_fast(
                        node_index, option_node, plan_tasks)
                else:
                    task_resources = self._assign_networks(option_node, tg)
                if task_resources is None:
                    option_node = None
            if option_node is None and not redispatched and \
                    (usage_diverged or from_device):
                # The device's remaining choices are stale (the plan
                # deviated from the kernel's assumed trajectory):
                # re-plan place[p:] in ONE exact host-kernel pass
                # against usage rebuilt from state + the in-flight
                # plan, then re-enter this iteration with the fresh
                # choice.  Turns the post-divergence tail from
                # per-placement sequential walks (~ms each under
                # contention) into a single vector pass.  A plain
                # chosen=-1 with NO divergence skips this — the rerun
                # would reproduce the same inputs and the same -1.
                redispatched = True
                fresh_c, fresh_s = self._redispatch_remaining(
                    place, args, p)
                chosen_l[p:] = fresh_c
                scores_l[p:] = fresh_s
                usage_diverged = False  # choices now exact vs the plan
                continue  # re-handle p with the fresh choice
            if option_node is None:
                # Sequential fallback, two jobs in one: when the device
                # picked a node the exact host accounting rejects
                # (over-approximation divergence) it re-selects; when
                # the device found NO candidate it produces the
                # reference's failure explanation — the stack chain
                # fills ctx metrics with per-constraint/class/dimension
                # filter and exhaustion counts (monitor.go
                # dumpAllocStatus is downstream of this data).
                if from_device:
                    # Device usage accounting included a placement the
                    # plan won't make: re-verify later winners exactly.
                    usage_diverged = True
                prior_verdict = failed_slots.get(g)
                if prior_verdict is not None:
                    # A semantically identical group already walked the
                    # fleet and failed; usage only grows within one
                    # finish pass, so the verdict (and its explanation)
                    # still holds — copy it instead of re-walking
                    # O(fleet x allocs) per identical group.  The
                    # source object lives on ANOTHER group's failed
                    # alloc and accumulates that group's coalesce
                    # count: zero it on the copy.
                    metrics = prior_verdict.copy()
                    metrics.coalesced_failures = 0
                else:
                    if fallback_nodes is None:
                        fallback_nodes = ready_nodes_in_dcs(
                            self.state, self.job.datacenters)
                    self.stack.set_nodes(list(fallback_nodes))
                    ranked, size = self.stack.select(tg)
                    if ranked is not None:
                        if not from_device:
                            # Host placed what the device didn't:
                            # diverged in the other direction.
                            usage_diverged = True
                        option_node = ranked.node
                        task_resources = ranked.task_resources
                        # The fallback assigned ports outside our
                        # per-node state: rebuild both on next use.
                        self._net_cache.pop(option_node.id, None)
                        self._node_net.pop(
                            statics.index_of.get(option_node.id), None)
                    # select populated fresh ctx metrics (incl. scores).
                    metrics = self.ctx.metrics()
                    if ranked is None:
                        failed_slots[g] = metrics
            else:
                metrics = fast_metric(option_node.id + ".binpack",
                                      scores_l[p])

            alloc = Allocation.__new__(Allocation)
            d = dict(alloc_proto)
            d["id"] = uuids[p]
            d["name"] = missing.name
            d["task_group"] = tg.name
            d["resources"] = size
            d["metrics"] = metrics
            d["task_states"] = {}
            if option_node is not None:
                d["node_id"] = option_node.id
                d["task_resources"] = task_resources
                d["desired_status"] = ALLOC_DESIRED_STATUS_RUN
                d["client_status"] = ALLOC_CLIENT_STATUS_PENDING
                alloc.__dict__ = d
                plan.append_alloc(alloc)
            else:
                d["task_resources"] = {}
                d["desired_status"] = ALLOC_DESIRED_STATUS_FAILED
                d["desired_description"] = \
                    "failed to find a node for placement"
                d["client_status"] = ALLOC_CLIENT_STATUS_FAILED
                alloc.__dict__ = d
                plan.append_failed(alloc)
                failed_tg[id(tg)] = alloc
            p += 1

    def _redispatch_remaining(self, place: list, args: DeviceArgs,
                              p: int) -> tuple[list, list]:
        """Re-plan place[p:] with the exact host sequence kernel against
        usage rebuilt from state + the in-flight plan (the same math the
        device runs, so results splice straight into the finish loop)."""
        from nomad_tpu.ops.binpack_host import place_sequence_host

        statics = args.statics
        view = build_usage(statics, self._proposed_allocs_all(),
                           job_id=self.job.id)
        rem = len(place) - p
        group_idx = np.asarray(args.group_idx[p:p + rem], dtype=np.int32)
        valid = np.ones(rem, dtype=bool)
        chosen, scores, _u = place_sequence_host(
            statics.capacity, statics.reserved, view.usage,
            view.job_counts, args.feasible_h, args.asks, args.distinct,
            group_idx, valid, np.float32(args.penalty),
            n_real=statics.n_real)
        return np.asarray(chosen).tolist(), np.asarray(scores).tolist()


def rounds_to_placements(args: DeviceArgs, chosen_slots: np.ndarray,
                         score_slots: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
    """Map place_rounds output ([G, rounds*k_cap] per-slot streams) back to
    per-placement arrays in the original placement order (vectorized:
    one fancy-index assignment per slot, no per-placement Python)."""
    chosen = np.full(args.p_pad, -1, dtype=np.int32)
    scores = np.zeros(args.p_pad, dtype=np.float32)
    for slot, ps in args.slot_placements.items():
        stream = chosen_slots[slot]
        taken = stream >= 0
        nodes = stream[taken]
        node_scores = score_slots[slot][taken]
        n = min(len(ps), len(nodes))
        idx = np.asarray(ps[:n], dtype=np.int64)
        chosen[idx] = nodes[:n]
        scores[idx] = node_scores[:n]
    return chosen, scores


def new_jax_binpack_scheduler(state, planner) -> JaxBinPackScheduler:
    return JaxBinPackScheduler(state, planner, batch=False)


def new_jax_binpack_batch_scheduler(state, planner) -> JaxBinPackScheduler:
    return JaxBinPackScheduler(state, planner, batch=True)
