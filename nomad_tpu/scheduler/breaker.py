"""Device-executor circuit breaker.

A remote-attached TPU can fail in ways the cost model never sees: the
tunnel drops, a dispatch hangs past any useful deadline, the runtime
starts erroring every call.  Retrying the device per-eval would stall
the whole pipeline window each time; the host twin kernels
(ops/binpack_host.py) produce identical plans, so the right degradation
is to *hold the executor on host* and re-probe the device periodically.

Classic three-state breaker, specialized for the eval pipeline:

  closed     device dispatches flow normally; ``failure_threshold``
             consecutive failures trip it open.
  open       every would-be device dispatch is held on the host twin
             (zero user-visible failures — plans are identical by
             construction).  After ``cooldown`` seconds the next
             admission becomes a half-open probe.
  half-open  exactly one in-flight probe eval runs on the device AND
             the host twin; the pipeline asserts result parity.  Probe
             success closes the breaker; failure re-opens it and
             restarts the cooldown.

``admit()`` is called by the pipeline's front stage per would-be device
dispatch and returns one of ``"device" | "probe" | "host"``; outcomes
come back through ``record_success`` / ``record_failure``.  All state
transitions are counted (``stats()``) and surface on the runner next to
the host/device dispatch counts.
"""
from __future__ import annotations

import logging
import threading
import time

from nomad_tpu.obs import flight, registry

logger = logging.getLogger("nomad_tpu.scheduler.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

ADMIT_DEVICE = "device"
ADMIT_PROBE = "probe"
ADMIT_HOST = "host"


class DeviceCircuitBreaker:
    def __init__(self, failure_threshold: int = 2,
                 cooldown: float = 15.0,
                 probe_timeout: float = 60.0,
                 clock=time.monotonic) -> None:
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.cooldown = cooldown
        # A probe whose outcome is never recorded (its window was
        # discarded by an unrelated drain error) must not pin the
        # breaker half-open-on-host forever: past this age it is
        # presumed lost and a fresh probe is issued.
        self.probe_timeout = probe_timeout
        self._clock = clock
        self._lock = threading.Lock()
        # All below guarded by _lock.
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0
        self._counts = {"opens": 0, "closes": 0, "probes": 0,
                        "host_holds": 0, "failures": 0}

    # -- admission (pipeline front stage) ----------------------------------
    def admit(self) -> str:
        """Route one would-be device dispatch: ``device`` (closed),
        ``probe`` (first admission after the cooldown — caller must run
        host twin too and assert parity), or ``host`` (held)."""
        with self._lock:
            if self._state == CLOSED:
                return ADMIT_DEVICE
            if self._state == OPEN and not self._probe_inflight and \
                    self._clock() - self._opened_at >= self.cooldown:
                self._state = HALF_OPEN
                self._start_probe()
                logger.info("device breaker: half-open, probing device")
                return ADMIT_PROBE
            if self._state == HALF_OPEN:
                if not self._probe_inflight:
                    # A previous probe resolved before this admission;
                    # treat a lingering half-open as probe-able.
                    self._start_probe()
                    return ADMIT_PROBE
                if self._clock() - self._probe_started >= \
                        self.probe_timeout:
                    # The in-flight probe's outcome was lost (window
                    # discarded): re-probe rather than hold on host
                    # forever.
                    self._start_probe()
                    logger.warning("device breaker: probe outcome never "
                                   "recorded; issuing a fresh probe")
                    return ADMIT_PROBE
            self._counts["host_holds"] += 1
            return ADMIT_HOST

    def _start_probe(self) -> None:
        # Caller holds the lock.
        self._probe_inflight = True
        self._probe_started = self._clock()
        self._counts["probes"] += 1

    # -- outcomes (pipeline stages) ----------------------------------------
    def record_success(self, probe: bool = False) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if probe:
                self._probe_inflight = False
                if self._state != CLOSED:
                    self._state = CLOSED
                    self._counts["closes"] += 1
                    logger.info("device breaker: probe succeeded; closed")

    def record_failure(self, probe: bool = False) -> None:
        opened = False
        with self._lock:
            self._counts["failures"] += 1
            if probe:
                self._probe_inflight = False
                self._state = OPEN
                self._opened_at = self._clock()
                self._counts["opens"] += 1
                opened = True
                logger.warning("device breaker: probe failed; re-opened")
            else:
                self._consecutive_failures += 1
                if self._state == CLOSED and \
                        self._consecutive_failures >= \
                        self.failure_threshold:
                    self._state = OPEN
                    self._opened_at = self._clock()
                    self._counts["opens"] += 1
                    opened = True
                    logger.warning(
                        "device breaker: open after %d consecutive "
                        "device failures; holding executor on host "
                        "(re-probe in %.1fs)",
                        self._consecutive_failures, self.cooldown)
        if opened and flight.INSTALLED:
            # Flight-recorder trigger (obs/flight.py), OUTSIDE the
            # breaker lock: the device executor just went unhealthy —
            # dump spans + stacks + metrics while the evidence is warm.
            flight.trip("breaker.open", self.stats())

    # -- introspection -----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counts)
            out["state"] = self._state
            return out

    def reset(self) -> None:
        with self._lock:
            self._state = CLOSED
            self._consecutive_failures = 0
            self._probe_inflight = False
            self._probe_started = 0.0
            self._opened_at = 0.0
            for k in self._counts:
                self._counts[k] = 0


# Process-default breaker: the device's health is a property of the
# machine (one tunnel, one runtime), not of any single runner, so
# successive PipelinedEvalRunner instances share trip state by default.
# Tests wanting isolation pass their own instance.
GLOBAL_BREAKER = DeviceCircuitBreaker()

# The breaker is exactly the kind of process-wide singleton the global
# metrics registry exists for: one producer, visible at
# /v1/agent/metrics as nomad.breaker.* from any colocated agent.
registry.REGISTRY.register("breaker", GLOBAL_BREAKER.stats)
