"""System scheduler: run a job on every feasible node.

Capability parity with /root/reference/scheduler/system_sched.go.
"""
from __future__ import annotations

import logging
from typing import Optional

from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    Allocation,
    Evaluation,
    filter_terminal_allocs,
    generate_uuid,
)

from .context import EvalContext
from .interfaces import SetStatusError
from .stack import SystemStack
from .util import (
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    diff_system_allocs,
    evict_and_place,
    inplace_update,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
)

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5

logger = logging.getLogger("nomad_tpu.scheduler.system")


class SystemScheduler:
    def __init__(self, state, planner) -> None:
        self.state = state
        self.planner = planner

        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[SystemStack] = None
        self.nodes: list = []
        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None

    def process(self, ev: Evaluation) -> None:
        self.eval = ev

        if ev.triggered_by not in (EVAL_TRIGGER_JOB_REGISTER,
                                   EVAL_TRIGGER_NODE_UPDATE,
                                   EVAL_TRIGGER_JOB_DEREGISTER):
            set_status(self.planner, ev, self.next_eval, EVAL_STATUS_FAILED,
                       f"scheduler cannot handle '{ev.triggered_by}' "
                       "evaluation reason")
            return

        try:
            retry_max(MAX_SYSTEM_SCHEDULE_ATTEMPTS, self._process)
        except SetStatusError as e:
            set_status(self.planner, ev, self.next_eval, e.eval_status,
                       str(e))
            return

        set_status(self.planner, ev, self.next_eval, EVAL_STATUS_COMPLETE)

    def _process(self) -> bool:
        self.job = self.state.job_by_id(self.eval.job_id)
        self.nodes = ready_nodes_in_dcs(self.state, self.job.datacenters) \
            if self.job is not None else []

        self.plan = self.eval.make_plan(self.job)
        self.ctx = EvalContext(self.state, self.plan, logger)
        self.stack = SystemStack(self.ctx)
        if self.job is not None:
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if self.plan.is_noop():
            return True

        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(
                self.job.update.stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        if new_state is not None:
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            logger.debug("eval %s: attempted %d placements, %d placed",
                         self.eval.id, expected, actual)
            return False
        return True

    def _compute_job_allocs(self, allocs: Optional[list] = None) -> None:
        if allocs is None:
            allocs = filter_terminal_allocs(
                self.state.allocs_by_job(self.eval.job_id))
        tainted = tainted_nodes(self.state, allocs)

        diff = diff_system_allocs(self.job, self.nodes, tainted, allocs)

        for tup in diff.stop:
            self.plan.append_update(tup.alloc, ALLOC_DESIRED_STATUS_STOP,
                                    ALLOC_NOT_NEEDED)

        diff.update = inplace_update(self.ctx, self.eval, self.job,
                                     self.stack, diff.update)

        limit = [len(diff.update)]
        if self.job is not None and self.job.update.rolling():
            limit = [self.job.update.max_parallel]
        self.limit_reached = evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit)

        if diff.place:
            self._compute_placements(diff.place)

    def _compute_placements(self, place: list) -> None:
        node_by_id = {n.id: n for n in self.nodes}
        failed_tg: dict = {}

        for missing in place:
            node = node_by_id.get(missing.alloc.node_id)
            if node is None:
                raise KeyError(
                    f"could not find node {missing.alloc.node_id!r}")

            self.stack.set_nodes([node])
            option, size = self.stack.select(missing.task_group)

            if option is None:
                prior_fail = failed_tg.get(id(missing.task_group))
                if prior_fail is not None:
                    prior_fail.metrics.coalesced_failures += 1
                    continue

            alloc = Allocation(
                id=generate_uuid(),
                eval_id=self.eval.id,
                name=missing.name,
                job_id=self.job.id,
                job=self.job,
                task_group=missing.task_group.name,
                resources=size,
                metrics=self.ctx.metrics(),
            )
            if option is not None:
                alloc.node_id = option.node.id
                alloc.task_resources = option.task_resources
                alloc.desired_status = ALLOC_DESIRED_STATUS_RUN
                alloc.client_status = ALLOC_CLIENT_STATUS_PENDING
                self.plan.append_alloc(alloc)
            else:
                alloc.desired_status = ALLOC_DESIRED_STATUS_FAILED
                alloc.desired_description = \
                    "failed to find a node for placement"
                alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
                self.plan.append_failed(alloc)
                failed_tg[id(missing.task_group)] = alloc


def new_system_scheduler(state, planner) -> SystemScheduler:
    return SystemScheduler(state, planner)
