"""Generic scheduler for service + batch jobs.

Capability parity with /root/reference/scheduler/generic_sched.go:
reconcile job vs existing allocs, place/update/migrate/stop, retry on plan
conflict (5 attempts service / 2 batch), rolling-update limits with
follow-up evals.
"""
from __future__ import annotations

import logging
from typing import Optional

from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_FAILED,
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_FAILED,
    ALLOC_DESIRED_STATUS_RUN,
    ALLOC_DESIRED_STATUS_STOP,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_TRIGGER_JOB_DEREGISTER,
    EVAL_TRIGGER_JOB_REGISTER,
    EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_ROLLING_UPDATE,
    Allocation,
    Evaluation,
    filter_terminal_allocs,
    generate_uuid,
)

from .context import EvalContext
from .interfaces import SetStatusError
from .stack import GenericStack
from .util import (
    ALLOC_MIGRATING,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    diff_allocs,
    evict_and_place,
    inplace_update,
    materialize_task_groups,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
)

MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

# Triggers the generic scheduler accepts (shared with the batch runner).
VALID_GENERIC_TRIGGERS = (
    EVAL_TRIGGER_JOB_REGISTER, EVAL_TRIGGER_NODE_UPDATE,
    EVAL_TRIGGER_JOB_DEREGISTER, EVAL_TRIGGER_ROLLING_UPDATE,
)

logger = logging.getLogger("nomad_tpu.scheduler.generic")


class GenericScheduler:
    def __init__(self, state, planner, batch: bool) -> None:
        self.state = state
        self.planner = planner
        self.batch = batch

        self.eval: Optional[Evaluation] = None
        self.job = None
        self.plan = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None
        self.limit_reached = False
        self.next_eval: Optional[Evaluation] = None

    # -- entry point ------------------------------------------------------
    def process(self, ev: Evaluation) -> None:
        self.eval = ev

        if ev.triggered_by not in VALID_GENERIC_TRIGGERS:
            set_status(self.planner, ev, self.next_eval, EVAL_STATUS_FAILED,
                       f"scheduler cannot handle '{ev.triggered_by}' "
                       "evaluation reason")
            return

        limit = MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch else \
            MAX_SERVICE_SCHEDULE_ATTEMPTS
        try:
            retry_max(limit, self._process)
        except SetStatusError as e:
            set_status(self.planner, ev, self.next_eval, e.eval_status,
                       str(e))
            return

        set_status(self.planner, ev, self.next_eval, EVAL_STATUS_COMPLETE)

    # -- one attempt ------------------------------------------------------
    def _begin(self) -> None:
        """Reconcile phase: build plan/ctx/stack and compute job allocs.
        Split from submission so a batch driver can pause between the two
        (nomad_tpu/scheduler/batch.py)."""
        self.job = self.state.job_by_id(self.eval.job_id)
        self.plan = self.eval.make_plan(self.job)
        self.ctx = EvalContext(self.state, self.plan, logger)
        self.stack = GenericStack(self.batch, self.ctx)
        if self.job is not None:
            self.stack.set_job(self.job)

        self._compute_job_allocs()

    def _process(self) -> bool:
        self._begin()
        return self._submit()

    def _submit(self) -> bool:
        done = self._submit_begin()
        if done is not None:
            return done
        result, new_state = self.planner.submit_plan(self.plan)
        return self._submit_finish(result, new_state)

    def _submit_begin(self) -> "Optional[bool]":
        """Pre-submission step: noop short-circuit + rolling-update
        follow-up eval.  Returns True when there is nothing to submit,
        None when the plan should go to the planner — split out so a
        window driver (scheduler/batch.py) can gather many plans and
        submit them as one group."""
        if self.plan.is_noop():
            return True

        # Rolling-update limit: schedule a follow-up eval after the stagger.
        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(
                self.job.update.stagger)
            self.planner.create_eval(self.next_eval)
        return None

    def _submit_finish(self, result, new_state) -> bool:
        """Interpret one submitted plan's response (the post-submission
        half of ``_submit``)."""
        if new_state is not None:
            # Forced refresh: stale data, try again.
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            logger.debug("eval %s: attempted %d placements, %d placed",
                         self.eval.id, expected, actual)
            return False
        return True

    # -- reconciliation ---------------------------------------------------
    def _compute_job_allocs(self) -> None:
        groups = materialize_task_groups(self.job)

        allocs = self.state.allocs_by_job(self.eval.job_id)
        allocs = filter_terminal_allocs(allocs)

        tainted = tainted_nodes(self.state, allocs)
        diff = diff_allocs(self.job, tainted, groups, allocs,
                           cache_fresh=True)

        for tup in diff.stop:
            self.plan.append_update(tup.alloc, ALLOC_DESIRED_STATUS_STOP,
                                    ALLOC_NOT_NEEDED)

        diff.update = inplace_update(self.ctx, self.eval, self.job,
                                     self.stack, diff.update)

        limit = [len(diff.update) + len(diff.migrate)]
        if self.job is not None and self.job.update.rolling():
            limit = [self.job.update.max_parallel]

        self.limit_reached = evict_and_place(
            self.ctx, diff, diff.migrate, ALLOC_MIGRATING, limit)
        self.limit_reached = evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit) \
            or self.limit_reached

        if diff.place:
            self._compute_placements(diff.place)

    def _compute_placements(self, place: list) -> None:
        nodes = ready_nodes_in_dcs(self.state, self.job.datacenters)
        self.stack.set_nodes(nodes)

        failed_tg: dict = {}
        for missing in place:
            # Coalesce repeated failures of the same task group.
            prior_fail = failed_tg.get(id(missing.task_group))
            if prior_fail is not None:
                prior_fail.metrics.coalesced_failures += 1
                continue

            option, size = self.stack.select(missing.task_group)

            alloc = Allocation(
                id=generate_uuid(),
                eval_id=self.eval.id,
                name=missing.name,
                job_id=self.job.id,
                job=self.job,
                task_group=missing.task_group.name,
                resources=size,
                metrics=self.ctx.metrics(),
            )
            if option is not None:
                alloc.node_id = option.node.id
                alloc.task_resources = option.task_resources
                alloc.desired_status = ALLOC_DESIRED_STATUS_RUN
                alloc.client_status = ALLOC_CLIENT_STATUS_PENDING
                self.plan.append_alloc(alloc)
            else:
                alloc.desired_status = ALLOC_DESIRED_STATUS_FAILED
                alloc.desired_description = \
                    "failed to find a node for placement"
                alloc.client_status = ALLOC_CLIENT_STATUS_FAILED
                self.plan.append_failed(alloc)
                failed_tg[id(missing.task_group)] = alloc


def new_service_scheduler(state, planner) -> GenericScheduler:
    return GenericScheduler(state, planner, batch=False)


def new_batch_scheduler(state, planner) -> GenericScheduler:
    return GenericScheduler(state, planner, batch=True)
