"""Ranking iterators: bin-packing + job anti-affinity.

Capability parity with /root/reference/scheduler/rank.go.  `score_fit` here
is the scalar path; nomad_tpu/ops/binpack.py is the vectorized device path.
"""
from __future__ import annotations

from typing import Optional

from nomad_tpu.structs import (
    Allocation,
    NetworkIndex,
    Node,
    Resources,
    Task,
    allocs_fit,
    score_fit,
)

from .context import EvalContext


class RankedNode:
    __slots__ = ("node", "score", "task_resources", "proposed")

    def __init__(self, node: Node) -> None:
        self.node = node
        self.score = 0.0
        self.task_resources: dict = {}
        self.proposed: Optional[list] = None

    def proposed_allocs(self, ctx: EvalContext) -> list:
        if self.proposed is None:
            self.proposed = ctx.proposed_allocs(self.node.id)
        return self.proposed

    def set_task_resources(self, task: Task, resources: Resources) -> None:
        self.task_resources[task.name] = resources


class FeasibleRankIterator:
    """Upgrades a feasibility iterator into the ranking chain."""

    def __init__(self, ctx: EvalContext, source) -> None:
        self.ctx = ctx
        self.source = source

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        return RankedNode(option)

    def reset(self) -> None:
        self.source.reset()


class StaticRankIterator:
    """Fixed list of ranked nodes; used in tests."""

    def __init__(self, ctx: EvalContext, nodes: list) -> None:
        self.ctx = ctx
        self.nodes = nodes
        self.offset = 0
        self.seen = 0

    def next(self) -> Optional[RankedNode]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        return option

    def reset(self) -> None:
        self.seen = 0


class BinPackIterator:
    """Scores nodes by BestFit-v3 after assigning network offers per task."""

    def __init__(self, ctx: EvalContext, source, evict: bool = False,
                 priority: int = 0) -> None:
        self.ctx = ctx
        self.source = source
        self.evict = evict
        self.priority = priority
        self.tasks: list = []

    def set_priority(self, p: int) -> None:
        self.priority = p

    def set_tasks(self, tasks: list) -> None:
        self.tasks = tasks

    def next(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next()
            if option is None:
                return None

            proposed = option.proposed_allocs(self.ctx)

            # Index existing network usage
            net_idx = NetworkIndex()
            net_idx.set_node(option.node)
            net_idx.add_allocs(proposed)

            # Assign resources (and network offers) per task
            total = Resources()
            exhausted = False
            for task in self.tasks:
                task_resources = task.resources.copy()
                if task_resources.networks:
                    ask = task_resources.networks[0]
                    offer, err = net_idx.assign_network(ask)
                    if offer is None:
                        self.ctx.metrics().exhausted_node(
                            option.node, f"network: {err}")
                        exhausted = True
                        break
                    net_idx.add_reserved(offer)
                    task_resources.networks = [offer]
                option.set_task_resources(task, task_resources)
                total.add(task_resources)
            if exhausted:
                continue

            proposed = proposed + [Allocation(resources=total)]
            fit, dim, util = allocs_fit(option.node, proposed, net_idx)
            if not fit:
                self.ctx.metrics().exhausted_node(option.node, dim)
                continue

            fitness = score_fit(option.node, util)
            option.score += fitness
            self.ctx.metrics().score_node(option.node, "binpack", fitness)
            return option

    def reset(self) -> None:
        self.source.reset()


class JobAntiAffinityIterator:
    """Penalizes co-placement with allocs of the same job to spread load."""

    def __init__(self, ctx: EvalContext, source, penalty: float,
                 job_id: str = "") -> None:
        self.ctx = ctx
        self.source = source
        self.penalty = penalty
        self.job_id = job_id

    def set_job(self, job_id: str) -> None:
        self.job_id = job_id

    def next(self) -> Optional[RankedNode]:
        option = self.source.next()
        if option is None:
            return None
        proposed = option.proposed_allocs(self.ctx)
        collisions = sum(1 for a in proposed if a.job_id == self.job_id)
        if collisions > 0:
            penalty = -1.0 * collisions * self.penalty
            option.score += penalty
            self.ctx.metrics().score_node(
                option.node, "job-anti-affinity", penalty)
        return option

    def reset(self) -> None:
        self.source.reset()
