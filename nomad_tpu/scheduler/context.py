"""Per-evaluation placement context.

Capability parity with /root/reference/scheduler/context.go: carries the
state snapshot, the in-flight plan, per-placement metrics, and the
regex/version-constraint caches.  ``proposed_allocs`` is the optimistic view:
existing allocs minus planned evictions plus planned placements.
"""
from __future__ import annotations

import logging
from typing import Optional

from nomad_tpu.structs import (
    AllocMetric,
    Plan,
    filter_terminal_allocs,
    remove_allocs,
)

logger = logging.getLogger("nomad_tpu.scheduler")


class EvalContext:
    def __init__(self, state, plan: Plan,
                 log: Optional[logging.Logger] = None) -> None:
        self._state = state
        self._plan = plan
        self._logger = log or logger
        self._metrics = AllocMetric()
        self.regexp_cache: dict = {}
        self.constraint_cache: dict = {}

    def state(self):
        return self._state

    def set_state(self, state) -> None:
        self._state = state

    def plan(self) -> Plan:
        return self._plan

    def logger(self) -> logging.Logger:
        return self._logger

    def metrics(self) -> AllocMetric:
        return self._metrics

    def reset(self) -> None:
        """Invoked after each placement: fresh metrics."""
        self._metrics = AllocMetric()

    def proposed_allocs(self, node_id: str) -> list:
        """Existing allocs - planned evictions + planned placements."""
        existing = filter_terminal_allocs(self._state.allocs_by_node(node_id))
        update = self._plan.node_update.get(node_id, [])
        proposed = remove_allocs(existing, update) if update else existing
        return list(proposed) + list(self._plan.node_allocation.get(node_id, []))
