"""MVCC in-memory state store with O(1) snapshots.

Capability parity with /root/reference/nomad/state/state_store.go (go-memdb
immutable-radix MVCC): tables ``index, nodes, jobs, evals, allocs``; per-table
raft-index bookkeeping; secondary indexes (allocs by node/job/eval, evals by
job); snapshot in O(1); change notification for blocking queries.

Implementation is copy-on-write at table granularity instead of radix trees:
a snapshot freezes the current table dicts; the first write to a table after a
snapshot copies that table's dict (and the touched secondary-index buckets).
The store never mutates an object in place — every upsert stores a copy and
every reader must treat returned objects as immutable, exactly the contract
the reference documents (state_store.go:17-19).

The store is also the source feeding the device-resident fleet tensors: it
exposes a monotonically increasing per-table index that the state->HBM bridge
uses as its RefreshIndex-style fence (see nomad_tpu/models/fleet.py).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Optional

from nomad_tpu import faultinject
from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    valid_node_status,
)

TABLES = ("nodes", "jobs", "evals", "allocs")


class _Waiter:
    """One parked watch subscription (a callback, never a thread)."""

    __slots__ = ("token", "key", "min_index", "deliver", "timed",
                 "deadline")

    def __init__(self, token: str, key, min_index: int, deliver,
                 timed: bool, deadline: Optional[float]) -> None:
        self.token = token
        self.key = key
        self.min_index = min_index
        self.deliver = deliver   # deliver(timed_out: bool), exactly once
        self.timed = timed       # True = armed on the timeout wheel
        self.deadline = deadline  # absolute monotonic; None = untimed


class StateWatch:
    """Shared watch fan-out keyed by (key, min_index).

    Parity role: nomad/state/notify.go NotifyGroup — blocking queries
    register on keys like ("allocs",) or ("alloc-node", node_id) and are
    woken when a write touches the key.

    Beyond the reference (the event-driven serving plane): waiters are
    *callbacks* in ONE shared registry instead of one parked
    Event-holding thread each.  ``subscribe(key, deliver, min_index,
    ttl)`` parks a callback that the single notifier drains when the
    key's table index advances past ``min_index``; timeouts ride one
    shared TTL wheel (server/ttlwheel.py) instead of per-waiter timers;
    and every exit path — wakeup, timeout, unsubscribe, conn death —
    removes the waiter, so an abandoned long-poll can never leak a
    registry entry (``live_waiters()`` is the gauge; the regression
    test churns abandoned polls and asserts it returns to zero).  The
    legacy ``watch``/``stop_watch`` Event API rides the same registry.

    The ``watch.deliver`` fault site fires per matured wakeup: ``drop``
    leaves the waiter parked (a lost wakeup — the wheel timeout still
    delivers later, so even injected loss cannot leak), ``delay``
    stalls the notifier like a slow fan-out.
    """

    def __init__(self, index_of=None) -> None:
        self._lock = threading.Lock()
        self._waiters: dict = {}    # token -> _Waiter
        self._by_key: dict = {}     # key -> {token: _Waiter}
        self._seq = 0
        self._wheel = None          # lazy: most stores never park timed waiters
        self._index_of = index_of   # key -> current table index (lost-wakeup recheck)
        # Counters, guarded by _lock.
        self.delivered = 0          # matured wakeups delivered
        self.timeouts = 0           # wheel-expired deliveries
        self.dropped_wakeups = 0    # injected watch.deliver drops

    # -- subscription ------------------------------------------------------
    def subscribe(self, key, deliver, min_index: int = 0,
                  ttl: Optional[float] = None) -> str:
        """Park ``deliver(timed_out)`` until a write moves ``key`` past
        ``min_index`` (0 = any touch) or ``ttl`` expires on the shared
        wheel (None = caller owns the timeout and MUST unsubscribe).
        Exactly-once: wakeup, timeout and unsubscribe race safely.  The
        post-register index recheck closes the lost-wakeup window — a
        write landing between the caller's check and this call delivers
        immediately (possibly on the calling thread)."""
        with self._lock:
            self._seq += 1
            token = f"w{self._seq}"
            waiter = _Waiter(token, key, min_index, deliver,
                             ttl is not None,
                             time.monotonic() + ttl
                             if ttl is not None else None)
            self._waiters[token] = waiter
            self._by_key.setdefault(key, {})[token] = waiter
            if ttl is not None:
                self._wheel_locked().arm(token, ttl)
        if min_index > 0 and self._index_of is not None:
            current = self._index_of(key)
            if current > min_index:
                popped = self._pop(token)
                if popped is not None:
                    with self._lock:
                        self.delivered += 1
                    popped.deliver(False)
        return token

    def unsubscribe(self, token: str) -> bool:
        """Deregister; True when the waiter was still parked (its
        callback will never fire)."""
        return self._pop(token) is not None

    def watch(self, key) -> threading.Event:
        """Legacy Event API: one event per caller, riding the shared
        registry (no wheel entry — stop_watch/notify clean up)."""
        ev = threading.Event()
        token = self.subscribe(key, lambda timed_out: ev.set())
        ev._watch_token = token  # for stop_watch
        return ev

    def stop_watch(self, key, ev: threading.Event) -> None:
        token = getattr(ev, "_watch_token", None)
        if token is not None:
            self.unsubscribe(token)

    # -- notification ------------------------------------------------------
    def notify(self, *keys, index: Optional[int] = None) -> None:
        """A write touched ``keys`` at ``index``: drain every matured
        waiter (min_index 0, or index unknown, or index past
        min_index).  Runs on the writer's thread, outside the store
        lock; callbacks must be quick (set an event / re-enqueue a
        dispatch)."""
        matured: list = []
        with self._lock:
            for key in keys:
                bucket = self._by_key.get(key)
                if not bucket:
                    continue
                for token in list(bucket):
                    waiter = bucket[token]
                    if waiter.min_index and index is not None and \
                            index <= waiter.min_index:
                        continue
                    matured.append(waiter)
                    del bucket[token]
                    self._waiters.pop(token, None)
                if not bucket:
                    self._by_key.pop(key, None)
        for waiter in matured:
            if faultinject.ACTIVE:
                try:
                    faultinject.fire("watch.deliver",
                                     method=str(waiter.key[0]))
                except Exception:
                    # Injected lost wakeup: re-park the waiter — its
                    # wheel timeout (or the caller's own wait) still
                    # delivers, so loss degrades to latency, never a
                    # stuck or leaked waiter.  Re-ARM timed waiters:
                    # the original wheel entry may have fired into the
                    # pop-to-re-park gap, and a timed waiter without a
                    # timer would violate exactly that guarantee.
                    with self._lock:
                        self.dropped_wakeups += 1
                        self._waiters[waiter.token] = waiter
                        self._by_key.setdefault(waiter.key, {})[
                            waiter.token] = waiter
                        if waiter.timed:
                            self._wheel_locked().arm(
                                waiter.token,
                                max(waiter.deadline -
                                    time.monotonic(), 0.001))
                    continue
            self._deliver(waiter, timed_out=False)

    def notify_all(self) -> None:
        """Wake every watcher — used when the whole world may have
        changed (snapshot restore)."""
        with self._lock:
            waiters = list(self._waiters.values())
            self._waiters.clear()
            self._by_key.clear()
        for waiter in waiters:
            self._deliver(waiter, timed_out=False)

    # -- internals ---------------------------------------------------------
    def _wheel_locked(self):
        if self._wheel is None:
            # Lazy import: state must not import nomad_tpu.server at
            # module load (fsm -> state would cycle); by first timed
            # subscribe the server package is long imported.
            from nomad_tpu.server.ttlwheel import TTLWheel
            self._wheel = TTLWheel(self._on_timeout,
                                   name="watch-timeout-wheel")
        return self._wheel

    def _pop(self, token: str) -> Optional[_Waiter]:
        with self._lock:
            waiter = self._waiters.pop(token, None)
            if waiter is None:
                return None
            bucket = self._by_key.get(waiter.key)
            if bucket is not None:
                bucket.pop(token, None)
                if not bucket:
                    self._by_key.pop(waiter.key, None)
            if waiter.timed and self._wheel is not None:
                self._wheel.cancel(token)
        return waiter

    def _deliver(self, waiter: _Waiter, timed_out: bool) -> None:
        with self._lock:
            if timed_out:
                self.timeouts += 1
            else:
                self.delivered += 1
            if waiter.timed and not timed_out and self._wheel is not None:
                self._wheel.cancel(waiter.token)
        waiter.deliver(timed_out)

    def _on_timeout(self, token: str) -> None:
        """Wheel callback: the waiter's wait expired undelivered."""
        waiter = self._pop(token)
        if waiter is not None:
            self._deliver(waiter, timed_out=True)

    # -- introspection / lifecycle ----------------------------------------
    def live_waiters(self) -> int:
        """The leak gauge: parked waiters right now."""
        with self._lock:
            return len(self._waiters)

    def stats(self) -> dict:
        with self._lock:
            return {
                "live_waiters": len(self._waiters),
                "delivered": self.delivered,
                "timeouts": self.timeouts,
                "dropped_wakeups": self.dropped_wakeups,
            }

    def shutdown(self) -> None:
        """Stop the timeout wheel (server teardown); parked waiters are
        delivered as timed out so no caller is left hanging."""
        with self._lock:
            wheel = self._wheel
            waiters = list(self._waiters.values())
            self._waiters.clear()
            self._by_key.clear()
        if wheel is not None:
            wheel.stop()
        for waiter in waiters:
            self._deliver(waiter, timed_out=True)


class _LineageToken:
    """Weakref-able identity token (bare ``object()`` is not)."""

    __slots__ = ("__weakref__",)


class _Tables:
    """One immutable-once-shared generation of all table + index dicts."""

    __slots__ = ("tables", "indexes", "allocs_by_node", "allocs_by_job",
                 "allocs_by_eval", "evals_by_job", "alloc_log",
                 "alloc_log_base", "lineage")

    def __init__(self) -> None:
        self.tables = {name: {} for name in TABLES}
        self.indexes = {name: 0 for name in TABLES}
        self.allocs_by_node: dict = {}
        self.allocs_by_job: dict = {}
        self.allocs_by_eval: dict = {}
        self.evals_by_job: dict = {}
        # Alloc changelog: append-only [(index, (alloc_id, ...))], index
        # ascending — the feed for the incremental state->HBM usage
        # mirror (nomad_tpu/models/fleet.py UsageMirror).  Entries with
        # index <= alloc_log_base have been compacted away; a mirror
        # older than that must rebuild.  The list object is intentionally
        # shared across generations (readers filter by their snapshot's
        # allocs index; appends only ever add higher indexes).
        self.alloc_log: list = []
        self.alloc_log_base: int = 0
        # Lineage token: identity preserved across clones and changelog
        # compaction, REPLACED by snapshot restore — a mirror synced under
        # a different lineage must rebuild even if the raft index matches
        # (the world was swapped wholesale).  Weakref-able on purpose:
        # per-lineage caches (scheduler/util._READY_CACHE) key on it with
        # a WeakKeyDictionary so a dead world's entries free themselves.
        self.lineage: object = _LineageToken()

    def clone(self) -> "_Tables":
        new = _Tables.__new__(_Tables)
        new.tables = {k: v for k, v in self.tables.items()}
        new.indexes = dict(self.indexes)
        new.allocs_by_node = self.allocs_by_node
        new.allocs_by_job = self.allocs_by_job
        new.allocs_by_eval = self.allocs_by_eval
        new.evals_by_job = self.evals_by_job
        new.alloc_log = self.alloc_log
        new.alloc_log_base = self.alloc_log_base
        new.lineage = self.lineage
        return new


class _ReadMixin:
    """Shared read API between the live store and snapshots."""

    _t: _Tables

    # -- nodes ------------------------------------------------------------
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._t.tables["nodes"].get(node_id)

    def nodes(self) -> Iterable[Node]:
        return list(self._t.tables["nodes"].values())

    # -- jobs -------------------------------------------------------------
    def job_by_id(self, job_id: str) -> Optional[Job]:
        return self._t.tables["jobs"].get(job_id)

    def jobs(self) -> Iterable[Job]:
        return list(self._t.tables["jobs"].values())

    def jobs_by_scheduler(self, sched_type: str) -> list:
        return [j for j in self._t.tables["jobs"].values()
                if j.type == sched_type]

    # -- evals ------------------------------------------------------------
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._t.tables["evals"].get(eval_id)

    def evals(self) -> Iterable[Evaluation]:
        return list(self._t.tables["evals"].values())

    def evals_by_job(self, job_id: str) -> list:
        table = self._t.tables["evals"]
        return [table[i] for i in self._t.evals_by_job.get(job_id, ())]

    # -- allocs -----------------------------------------------------------
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._t.tables["allocs"].get(alloc_id)

    def allocs(self) -> Iterable[Allocation]:
        return list(self._t.tables["allocs"].values())

    def allocs_by_node(self, node_id: str) -> list:
        table = self._t.tables["allocs"]
        return [table[i] for i in self._t.allocs_by_node.get(node_id, ())]

    def has_allocs_on_node(self, node_id: str) -> bool:
        """O(1) emptiness probe — the scheduler finish path calls this
        once per placed node to skip proposed-alloc scans on fresh
        nodes."""
        return bool(self._t.allocs_by_node.get(node_id))

    def allocs_node_index(self) -> dict:
        """The raw node_id -> alloc-id-collection index, READ-ONLY.

        Handed to the native bulk finish (native/port_alloc.cpp) so the
        per-node emptiness probe is a C dict lookup instead of a Python
        call per placement.  Safe to borrow for an eval: writers copy
        shared indexes before mutating (copy-on-write, _writable_index)."""
        return self._t.allocs_by_node

    def allocs_by_job(self, job_id: str) -> list:
        table = self._t.tables["allocs"]
        return [table[i] for i in self._t.allocs_by_job.get(job_id, ())]

    def allocs_by_eval(self, eval_id: str) -> list:
        table = self._t.tables["allocs"]
        return [table[i] for i in self._t.allocs_by_eval.get(eval_id, ())]

    # -- indexes ----------------------------------------------------------
    def get_index(self, table: str) -> int:
        return self._t.indexes.get(table, 0)

    def latest_index(self) -> int:
        return max(self._t.indexes.values(), default=0)

    # -- identity ---------------------------------------------------------
    def fingerprint(self, changelog_since: int = 0) -> str:
        """Canonical digest of the full store: every table's objects
        (sorted by id), the per-table raft indexes, and the alloc
        changelog above ``changelog_since``.

        Two stores that evolved through the same committed write
        sequence digest identically; any divergence — a lost committed
        write, a duplicated alloc, a drifted index — differs here.
        The crash-recovery proofs byte-compare a rebooted store
        against a replay of the recorded committed prefix with it
        (``changelog_since`` skips entries a snapshot restore
        legitimately compacted away: a restored store's changelog
        starts empty)."""
        import hashlib

        import msgpack

        t = self._t
        # The changelog list object is shared across generations
        # (append-only, see _Tables.alloc_log); bound it by this
        # view's own allocs index so entries appended AFTER the view
        # was taken never leak into its digest.
        upto = t.indexes.get("allocs", 0)
        payload = {
            "indexes": {name: t.indexes.get(name, 0) for name in TABLES},
            "tables": {
                name: sorted(
                    (obj.to_dict() for obj in t.tables[name].values()),
                    key=lambda d: d.get("id", ""))
                for name in TABLES
            },
            "changelog": [
                (index, sorted(ids))
                for index, ids in t.alloc_log
                if changelog_since < index <= upto
            ],
        }
        return hashlib.sha256(
            msgpack.packb(payload, use_bin_type=True)).hexdigest()


class StateSnapshot(_ReadMixin):
    """A frozen point-in-time view of the store (O(1) to create)."""

    def __init__(self, tables: _Tables) -> None:
        self._t = tables


class StateStore(_ReadMixin):
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._t = _Tables()
        self._gen_shared = False    # generation container shared w/ snapshot
        self._shared: set = set()   # table names shared with a snapshot
        self._idx_shared = set()    # secondary index names shared
        # The watch's index resolver must NOT close a store<->watch
        # reference cycle: the store teardown story is refcount-only
        # (tests/test_gc_untrack.py), so the fan-out holds the store
        # weakly and a dead store resolves to 0 (recheck no-ops).
        import weakref
        store_ref = weakref.ref(self)

        def _index_of(key) -> int:
            store = store_ref()
            return store._watch_index(key) if store is not None else 0
        self.watch = StateWatch(index_of=_index_of)

    def _watch_index(self, key) -> int:
        """Current table index behind a watch key (the fan-out's
        lost-wakeup recheck).  Unkeyed/odd keys report the latest index
        so a recheck can only over-deliver, never under-deliver."""
        kind = key[0] if isinstance(key, tuple) and key else key
        if kind in TABLES:
            return self.get_index(kind)
        table = {"node": "nodes", "job": "jobs", "eval": "evals",
                 "alloc-node": "allocs"}.get(kind)
        if table is not None:
            return self.get_index(table)
        return self.latest_index()

    # -- snapshot / restore ----------------------------------------------
    def snapshot(self) -> StateSnapshot:
        with self._lock:
            self._gen_shared = True
            self._shared = set(TABLES)
            self._idx_shared = {"allocs_by_node", "allocs_by_job",
                                "allocs_by_eval", "evals_by_job"}
            return StateSnapshot(self._t)

    def fingerprint(self, changelog_since: int = 0) -> str:
        # A live store digests a frozen generation: concurrent raft
        # applies (a follower catching up while a soak compares
        # replicas) must not mutate tables mid-iteration or tear the
        # view.
        return self.snapshot().fingerprint(changelog_since)

    def restore(self) -> "StateRestore":
        """Bulk-load rig used by FSM snapshot restore: stage into a fresh
        generation, swap atomically on commit."""
        return StateRestore(self)

    def stats(self) -> dict:
        """Registry provider (obs/registry.py): table sizes, per-table
        indexes, changelog length, and the watch fan-out's gauges —
        the store's share of /v1/agent/metrics."""
        with self._lock:
            t = self._t
            out = {
                "tables": {name: len(table)
                           for name, table in t.tables.items()},
                "indexes": dict(t.indexes),
                "alloc_log": len(t.alloc_log),
            }
        out["watch"] = self.watch.stats()
        return out

    # -- write plumbing ---------------------------------------------------
    def _writable_table(self, name: str) -> dict:
        if self._gen_shared:
            self._t = self._t.clone()
            self._gen_shared = False
        if name in self._shared:
            self._t.tables[name] = dict(self._t.tables[name])
            self._shared.discard(name)
        return self._t.tables[name]

    def _writable_index(self, name: str) -> dict:
        if self._gen_shared:
            self._t = self._t.clone()
            self._gen_shared = False
        if name in self._idx_shared:
            setattr(self._t, name, dict(getattr(self._t, name)))
            self._idx_shared.discard(name)
        return getattr(self._t, name)

    @staticmethod
    def _index_add(idx: dict, key: str, item_id: str) -> None:
        bucket = idx.get(key)
        bucket = set() if bucket is None else set(bucket)
        bucket.add(item_id)
        idx[key] = bucket

    @staticmethod
    def _index_remove(idx: dict, key: str, item_id: str) -> None:
        bucket = idx.get(key)
        if bucket is None:
            return
        bucket = set(bucket)
        bucket.discard(item_id)
        if bucket:
            idx[key] = bucket
        else:
            idx.pop(key, None)

    def _bump(self, table: str, index: int) -> None:
        self._t.indexes[table] = index

    _ALLOC_LOG_MAX = 16384

    def _log_alloc_change(self, index: int, alloc_ids) -> None:
        """Record changed alloc ids for incremental mirror sync.  Called
        under the store lock AFTER _writable_table (generation private)."""
        log = self._t.alloc_log
        log.append((index, tuple(alloc_ids)))
        if len(log) > self._ALLOC_LOG_MAX:
            keep = self._ALLOC_LOG_MAX // 2
            # New list: older generations keep the one they saw.
            self._t.alloc_log_base = log[-keep - 1][0]
            self._t.alloc_log = log[-keep:]

    # -- nodes ------------------------------------------------------------
    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            table = self._writable_table("nodes")
            existing = table.get(node.id)
            new = node.copy()
            if existing is not None:
                new.create_index = existing.create_index
            else:
                new.create_index = index
            new.modify_index = index
            table[new.id] = new
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node.id), index=index)

    def upsert_node_slab(self, index: int, slab) -> None:
        """Bulk-register a columnar node table (structs/node_slab.py):
        every slab row lands in one lock hold with ONE coalesced watch
        notification, and rows are stored as the slab's lazy SlabNode
        objects WITHOUT the per-node defensive copy — the caller hands
        the slab over and its columns are immutable from then on (the
        same ownership transfer the columnar alloc wire makes).  This
        is the 100k-1M-node fleet load path: per-row cost is one small
        lazy object, not ~8 (Resources/NetworkResource/attr dicts).

        Rows replace any existing node with the same id wholesale
        (fresh create_index) — the intended use is initial fleet load
        or whole-generation extension, not the incremental per-node
        upsert contract, which stays on ``upsert_node``."""
        slab.index = index
        with self._lock:
            table = self._writable_table("nodes")
            for r in range(slab.n):
                node = slab.node(r)
                # Rows materialized BEFORE this upsert carry the
                # slab's previous index in their eager dict: stamp
                # every stored row explicitly.  Dict pokes, not
                # attribute writes — a public-field setattr would flag
                # the row mutated and disqualify the fleet fast path.
                d = node.__dict__
                d["create_index"] = index
                d["modify_index"] = index
                table[node.id] = node
            self._bump("nodes", index)
        self.watch.notify(("nodes",), index=index)

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            table = self._writable_table("nodes")
            if node_id not in table:
                raise KeyError(f"node not found: {node_id}")
            del table[node_id]
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node_id), index=index)

    def update_node_status(self, index: int, node_id: str,
                           status: str) -> None:
        if not valid_node_status(status):
            raise ValueError(f"invalid node status {status!r}")
        with self._lock:
            table = self._writable_table("nodes")
            existing = table.get(node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            new = existing.copy()
            new.status = status
            new.modify_index = index
            table[node_id] = new
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node_id), index=index)

    def update_node_drain(self, index: int, node_id: str,
                          drain: bool) -> None:
        with self._lock:
            table = self._writable_table("nodes")
            existing = table.get(node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            new = existing.copy()
            new.drain = drain
            new.modify_index = index
            table[node_id] = new
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node_id), index=index)

    # -- jobs -------------------------------------------------------------
    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            table = self._writable_table("jobs")
            existing = table.get(job.id)
            new = job.copy()
            if existing is not None:
                new.create_index = existing.create_index
            else:
                new.create_index = index
            new.modify_index = index
            table[new.id] = new
            self._bump("jobs", index)
        self.watch.notify(("jobs",), ("job", job.id), index=index)

    def delete_job(self, index: int, job_id: str) -> None:
        with self._lock:
            table = self._writable_table("jobs")
            if job_id not in table:
                raise KeyError(f"job not found: {job_id}")
            del table[job_id]
            self._bump("jobs", index)
        self.watch.notify(("jobs",), ("job", job_id), index=index)

    # -- evals ------------------------------------------------------------
    def upsert_evals(self, index: int, evals: list) -> None:
        with self._lock:
            table = self._writable_table("evals")
            by_job = self._writable_index("evals_by_job")
            for ev in evals:
                existing = table.get(ev.id)
                new = ev.copy()
                if existing is not None:
                    new.create_index = existing.create_index
                else:
                    new.create_index = index
                new.modify_index = index
                table[new.id] = new
                self._index_add(by_job, new.job_id, new.id)
            self._bump("evals", index)
        self.watch.notify(("evals",), index=index)

    def delete_eval(self, index: int, eval_ids: list,
                    alloc_ids: list) -> None:
        """Reap evals + allocs in one txn (reference: Eval.Reap)."""
        touched_nodes = []
        with self._lock:
            evals = self._writable_table("evals")
            by_job = self._writable_index("evals_by_job")
            for eid in eval_ids:
                ev = evals.pop(eid, None)
                if ev is not None:
                    self._index_remove(by_job, ev.job_id, eid)
            allocs = self._writable_table("allocs")
            a_node = self._writable_index("allocs_by_node")
            a_job = self._writable_index("allocs_by_job")
            a_eval = self._writable_index("allocs_by_eval")
            removed = []
            for aid in alloc_ids:
                alloc = allocs.pop(aid, None)
                if alloc is not None:
                    self._index_remove(a_node, alloc.node_id, aid)
                    self._index_remove(a_job, alloc.job_id, aid)
                    self._index_remove(a_eval, alloc.eval_id, aid)
                    touched_nodes.append(alloc.node_id)
                    removed.append(aid)
            self._bump("evals", index)
            self._bump("allocs", index)
            if removed:
                self._log_alloc_change(index, removed)
        # sorted(): the dedup set's iteration order is hash-seeded, and
        # the notify key order escapes to watch subscribers — replicas
        # must fan out identically for the same log entry.
        keys = [("evals",), ("allocs",)]
        keys += [("alloc-node", n) for n in sorted(set(touched_nodes))]
        self.watch.notify(*keys, index=index)

    # -- allocs -----------------------------------------------------------
    def upsert_allocs(self, index: int, allocs: list) -> None:
        """Scheduler/plan-authoritative write: preserves client-owned fields
        of any existing alloc (reference: state_store.go:601-637).  One
        item of the batched path — the merge semantics live in exactly
        one place (upsert_allocs_batched)."""
        if not allocs:
            # The batched path skips empty items; a bare index write
            # must still move the table fence — on a PRIVATE generation
            # (_writable_table clones when shared), never in place under
            # a live snapshot.
            with self._lock:
                self._writable_table("allocs")
                self._bump("allocs", index)
            self.watch.notify(("allocs",), index=index)
            return
        self.upsert_allocs_batched([(index, allocs)])

    def upsert_allocs_batched(self, items: list) -> None:
        """Group-commit write: ``items`` is ``[(index, allocs), ...]`` in
        eval order, applied under ONE lock hold with one coalesced watch
        notification — byte-identical final state to calling
        ``upsert_allocs(index, allocs)`` per item in order (same
        create/modify indexes, same changelog entries, same last-writer-
        wins on duplicate alloc ids), minus the per-plan lock/notify
        churn.  The raft path passes one shared entry index per item;
        the harness path passes per-plan indexes so sequential replays
        stay index-exact.

        Columnar contract (structs/alloc_slab.py): slab-backed allocs
        store as lazy SlabAlloc copies — one small dict copy plus the
        scalar stamps below; the heavy fields (task_resources/metrics)
        never materialize on this path, and the secondary indexes bump
        off the eager scalar columns alone."""
        touched_nodes = []
        last_index = 0  # highest index bumped; rides the watch notify
        # Buckets already copied within THIS call: _index_add/_remove
        # copy the shared bucket set on every touch (snapshot safety);
        # across a whole window that is O(bucket x allocs) churn for
        # buckets that are only shared once.  Copy each bucket the
        # first time the window touches it, then mutate the private
        # copy in place.
        fresh: dict = {}  # (id(index dict), key) -> private bucket

        def add(idx: dict, key: str, item_id: str) -> None:
            bucket = fresh.get((id(idx), key))
            if bucket is None:
                base = idx.get(key)
                bucket = set() if base is None else set(base)
                idx[key] = fresh[(id(idx), key)] = bucket
            bucket.add(item_id)

        def remove(idx: dict, key: str, item_id: str) -> None:
            bucket = fresh.get((id(idx), key))
            if bucket is None:
                base = idx.get(key)
                if base is None:
                    return
                bucket = idx[key] = fresh[(id(idx), key)] = set(base)
            bucket.discard(item_id)
            if not bucket:
                idx.pop(key, None)
                fresh.pop((id(idx), key), None)

        with self._lock:
            table = self._writable_table("allocs")
            a_node = self._writable_index("allocs_by_node")
            a_job = self._writable_index("allocs_by_job")
            a_eval = self._writable_index("allocs_by_eval")
            for index, allocs in items:
                if not allocs:
                    continue
                for alloc in allocs:
                    existing = table.get(alloc.id)
                    new = alloc.copy()
                    if existing is not None:
                        new.create_index = existing.create_index
                        new.client_status = existing.client_status
                        new.client_description = \
                            existing.client_description
                        # Skip the task_states carry-over when BOTH
                        # sides are canonically empty slab rows: the
                        # getter would materialize an empty dict and
                        # the setter would flag the row off the
                        # columnar snapshot encoding for no semantic
                        # difference (a shared {} vs a lazy {}).
                        if existing.__dict__.get("task_states") \
                                is not None or \
                                "_slab" not in existing.__dict__:
                            new.task_states = existing.task_states
                        remove(a_node, existing.node_id, alloc.id)
                    else:
                        new.create_index = index
                    new.modify_index = index
                    table[new.id] = new
                    add(a_node, new.node_id, new.id)
                    add(a_job, new.job_id, new.id)
                    if new.eval_id:
                        add(a_eval, new.eval_id, new.id)
                    touched_nodes.append(new.node_id)
                self._bump("allocs", index)
                self._log_alloc_change(index, [a.id for a in allocs])
                last_index = index
        # sorted(): same determinism contract as delete_eval — notify
        # fan-out order must not depend on the process hash seed.
        keys = [("allocs",)] + [("alloc-node", n)
                                for n in sorted(set(touched_nodes))]
        self.watch.notify(*keys, index=last_index)

    def update_alloc_from_client(self, index: int,
                                 alloc: Allocation) -> None:
        """Client-authoritative merge: only client status fields move
        (reference: state_store.go:556-597)."""
        with self._lock:
            table = self._writable_table("allocs")
            existing = table.get(alloc.id)
            if existing is None:
                raise KeyError(f"alloc not found: {alloc.id}")
            new = existing.copy()
            new.client_status = alloc.client_status
            new.client_description = alloc.client_description
            new.task_states = dict(alloc.task_states)
            new.modify_index = index
            table[new.id] = new
            self._bump("allocs", index)
            self._log_alloc_change(index, (alloc.id,))
        self.watch.notify(("allocs",), ("alloc-node", alloc.node_id),
                          index=index)


class StateRestore:
    """Accumulates objects into a fresh generation, swapped in atomically.

    Parity role: state_store.go StateRestore / fsm.go Restore — snapshot
    restore rebuilds the whole store in one transaction.
    """

    def __init__(self, store: StateStore) -> None:
        self._store = store
        self._t = _Tables()

    def node_restore(self, node: Node) -> None:
        self._t.tables["nodes"][node.id] = node
        self._t.indexes["nodes"] = max(self._t.indexes["nodes"],
                                       node.modify_index)

    def job_restore(self, job: Job) -> None:
        self._t.tables["jobs"][job.id] = job
        self._t.indexes["jobs"] = max(self._t.indexes["jobs"],
                                      job.modify_index)

    def eval_restore(self, ev: Evaluation) -> None:
        self._t.tables["evals"][ev.id] = ev
        self._t.indexes["evals"] = max(self._t.indexes["evals"],
                                       ev.modify_index)
        StateStore._index_add(self._t.evals_by_job, ev.job_id, ev.id)

    def alloc_restore(self, alloc: Allocation) -> None:
        self._t.tables["allocs"][alloc.id] = alloc
        self._t.indexes["allocs"] = max(self._t.indexes["allocs"],
                                        alloc.modify_index)
        StateStore._index_add(self._t.allocs_by_node, alloc.node_id, alloc.id)
        StateStore._index_add(self._t.allocs_by_job, alloc.job_id, alloc.id)
        if alloc.eval_id:
            StateStore._index_add(self._t.allocs_by_eval, alloc.eval_id,
                                  alloc.id)

    def index_restore(self, table: str, index: int) -> None:
        self._t.indexes[table] = index

    def commit(self) -> None:
        # A restored generation carries a fresh lineage token (set in
        # _Tables.__init__), forcing every existing mirror to rebuild once
        # — even one whose raft-index fence matches the restored index.
        with self._store._lock:
            self._store._t = self._t
            self._store._gen_shared = False
            self._store._shared = set()
            self._store._idx_shared = set()
        self._store.watch.notify_all()
