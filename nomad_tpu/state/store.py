"""MVCC in-memory state store with O(1) snapshots.

Capability parity with /root/reference/nomad/state/state_store.go (go-memdb
immutable-radix MVCC): tables ``index, nodes, jobs, evals, allocs``; per-table
raft-index bookkeeping; secondary indexes (allocs by node/job/eval, evals by
job); snapshot in O(1); change notification for blocking queries.

Implementation is copy-on-write at table granularity instead of radix trees:
a snapshot freezes the current table dicts; the first write to a table after a
snapshot copies that table's dict (and the touched secondary-index buckets).
The store never mutates an object in place — every upsert stores a copy and
every reader must treat returned objects as immutable, exactly the contract
the reference documents (state_store.go:17-19).

The store is also the source feeding the device-resident fleet tensors: it
exposes a monotonically increasing per-table index that the state->HBM bridge
uses as its RefreshIndex-style fence (see nomad_tpu/models/fleet.py).
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, Optional

from nomad_tpu.structs import (
    Allocation,
    Evaluation,
    Job,
    Node,
    valid_node_status,
)

TABLES = ("nodes", "jobs", "evals", "allocs")


class StateWatch:
    """Notify-on-change groups keyed by arbitrary hashable keys.

    Parity role: nomad/state/notify.go NotifyGroup — blocking queries
    register an event on keys like ("allocs",) or ("alloc-node", node_id)
    and are woken when a write touches the key.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._groups: dict = {}

    def watch(self, key) -> threading.Event:
        ev = threading.Event()
        with self._lock:
            self._groups.setdefault(key, set()).add(ev)
        return ev

    def stop_watch(self, key, ev: threading.Event) -> None:
        with self._lock:
            group = self._groups.get(key)
            if group is not None:
                group.discard(ev)
                if not group:
                    self._groups.pop(key, None)

    def notify(self, *keys) -> None:
        with self._lock:
            for key in keys:
                group = self._groups.pop(key, None)
                if group:
                    for ev in group:
                        ev.set()

    def notify_all(self) -> None:
        """Wake every watcher — used when the whole world may have changed
        (snapshot restore)."""
        with self._lock:
            groups, self._groups = self._groups, {}
        for group in groups.values():
            for ev in group:
                ev.set()


class _LineageToken:
    """Weakref-able identity token (bare ``object()`` is not)."""

    __slots__ = ("__weakref__",)


class _Tables:
    """One immutable-once-shared generation of all table + index dicts."""

    __slots__ = ("tables", "indexes", "allocs_by_node", "allocs_by_job",
                 "allocs_by_eval", "evals_by_job", "alloc_log",
                 "alloc_log_base", "lineage")

    def __init__(self) -> None:
        self.tables = {name: {} for name in TABLES}
        self.indexes = {name: 0 for name in TABLES}
        self.allocs_by_node: dict = {}
        self.allocs_by_job: dict = {}
        self.allocs_by_eval: dict = {}
        self.evals_by_job: dict = {}
        # Alloc changelog: append-only [(index, (alloc_id, ...))], index
        # ascending — the feed for the incremental state->HBM usage
        # mirror (nomad_tpu/models/fleet.py UsageMirror).  Entries with
        # index <= alloc_log_base have been compacted away; a mirror
        # older than that must rebuild.  The list object is intentionally
        # shared across generations (readers filter by their snapshot's
        # allocs index; appends only ever add higher indexes).
        self.alloc_log: list = []
        self.alloc_log_base: int = 0
        # Lineage token: identity preserved across clones and changelog
        # compaction, REPLACED by snapshot restore — a mirror synced under
        # a different lineage must rebuild even if the raft index matches
        # (the world was swapped wholesale).  Weakref-able on purpose:
        # per-lineage caches (scheduler/util._READY_CACHE) key on it with
        # a WeakKeyDictionary so a dead world's entries free themselves.
        self.lineage: object = _LineageToken()

    def clone(self) -> "_Tables":
        new = _Tables.__new__(_Tables)
        new.tables = {k: v for k, v in self.tables.items()}
        new.indexes = dict(self.indexes)
        new.allocs_by_node = self.allocs_by_node
        new.allocs_by_job = self.allocs_by_job
        new.allocs_by_eval = self.allocs_by_eval
        new.evals_by_job = self.evals_by_job
        new.alloc_log = self.alloc_log
        new.alloc_log_base = self.alloc_log_base
        new.lineage = self.lineage
        return new


class _ReadMixin:
    """Shared read API between the live store and snapshots."""

    _t: _Tables

    # -- nodes ------------------------------------------------------------
    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._t.tables["nodes"].get(node_id)

    def nodes(self) -> Iterable[Node]:
        return list(self._t.tables["nodes"].values())

    # -- jobs -------------------------------------------------------------
    def job_by_id(self, job_id: str) -> Optional[Job]:
        return self._t.tables["jobs"].get(job_id)

    def jobs(self) -> Iterable[Job]:
        return list(self._t.tables["jobs"].values())

    def jobs_by_scheduler(self, sched_type: str) -> list:
        return [j for j in self._t.tables["jobs"].values()
                if j.type == sched_type]

    # -- evals ------------------------------------------------------------
    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._t.tables["evals"].get(eval_id)

    def evals(self) -> Iterable[Evaluation]:
        return list(self._t.tables["evals"].values())

    def evals_by_job(self, job_id: str) -> list:
        table = self._t.tables["evals"]
        return [table[i] for i in self._t.evals_by_job.get(job_id, ())]

    # -- allocs -----------------------------------------------------------
    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._t.tables["allocs"].get(alloc_id)

    def allocs(self) -> Iterable[Allocation]:
        return list(self._t.tables["allocs"].values())

    def allocs_by_node(self, node_id: str) -> list:
        table = self._t.tables["allocs"]
        return [table[i] for i in self._t.allocs_by_node.get(node_id, ())]

    def has_allocs_on_node(self, node_id: str) -> bool:
        """O(1) emptiness probe — the scheduler finish path calls this
        once per placed node to skip proposed-alloc scans on fresh
        nodes."""
        return bool(self._t.allocs_by_node.get(node_id))

    def allocs_node_index(self) -> dict:
        """The raw node_id -> alloc-id-collection index, READ-ONLY.

        Handed to the native bulk finish (native/port_alloc.cpp) so the
        per-node emptiness probe is a C dict lookup instead of a Python
        call per placement.  Safe to borrow for an eval: writers copy
        shared indexes before mutating (copy-on-write, _writable_index)."""
        return self._t.allocs_by_node

    def allocs_by_job(self, job_id: str) -> list:
        table = self._t.tables["allocs"]
        return [table[i] for i in self._t.allocs_by_job.get(job_id, ())]

    def allocs_by_eval(self, eval_id: str) -> list:
        table = self._t.tables["allocs"]
        return [table[i] for i in self._t.allocs_by_eval.get(eval_id, ())]

    # -- indexes ----------------------------------------------------------
    def get_index(self, table: str) -> int:
        return self._t.indexes.get(table, 0)

    def latest_index(self) -> int:
        return max(self._t.indexes.values(), default=0)


class StateSnapshot(_ReadMixin):
    """A frozen point-in-time view of the store (O(1) to create)."""

    def __init__(self, tables: _Tables) -> None:
        self._t = tables


class StateStore(_ReadMixin):
    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._t = _Tables()
        self._gen_shared = False    # generation container shared w/ snapshot
        self._shared: set = set()   # table names shared with a snapshot
        self._idx_shared = set()    # secondary index names shared
        self.watch = StateWatch()

    # -- snapshot / restore ----------------------------------------------
    def snapshot(self) -> StateSnapshot:
        with self._lock:
            self._gen_shared = True
            self._shared = set(TABLES)
            self._idx_shared = {"allocs_by_node", "allocs_by_job",
                                "allocs_by_eval", "evals_by_job"}
            return StateSnapshot(self._t)

    def restore(self) -> "StateRestore":
        """Bulk-load rig used by FSM snapshot restore: stage into a fresh
        generation, swap atomically on commit."""
        return StateRestore(self)

    # -- write plumbing ---------------------------------------------------
    def _writable_table(self, name: str) -> dict:
        if self._gen_shared:
            self._t = self._t.clone()
            self._gen_shared = False
        if name in self._shared:
            self._t.tables[name] = dict(self._t.tables[name])
            self._shared.discard(name)
        return self._t.tables[name]

    def _writable_index(self, name: str) -> dict:
        if self._gen_shared:
            self._t = self._t.clone()
            self._gen_shared = False
        if name in self._idx_shared:
            setattr(self._t, name, dict(getattr(self._t, name)))
            self._idx_shared.discard(name)
        return getattr(self._t, name)

    @staticmethod
    def _index_add(idx: dict, key: str, item_id: str) -> None:
        bucket = idx.get(key)
        bucket = set() if bucket is None else set(bucket)
        bucket.add(item_id)
        idx[key] = bucket

    @staticmethod
    def _index_remove(idx: dict, key: str, item_id: str) -> None:
        bucket = idx.get(key)
        if bucket is None:
            return
        bucket = set(bucket)
        bucket.discard(item_id)
        if bucket:
            idx[key] = bucket
        else:
            idx.pop(key, None)

    def _bump(self, table: str, index: int) -> None:
        self._t.indexes[table] = index

    _ALLOC_LOG_MAX = 16384

    def _log_alloc_change(self, index: int, alloc_ids) -> None:
        """Record changed alloc ids for incremental mirror sync.  Called
        under the store lock AFTER _writable_table (generation private)."""
        log = self._t.alloc_log
        log.append((index, tuple(alloc_ids)))
        if len(log) > self._ALLOC_LOG_MAX:
            keep = self._ALLOC_LOG_MAX // 2
            # New list: older generations keep the one they saw.
            self._t.alloc_log_base = log[-keep - 1][0]
            self._t.alloc_log = log[-keep:]

    # -- nodes ------------------------------------------------------------
    def upsert_node(self, index: int, node: Node) -> None:
        with self._lock:
            table = self._writable_table("nodes")
            existing = table.get(node.id)
            new = node.copy()
            if existing is not None:
                new.create_index = existing.create_index
            else:
                new.create_index = index
            new.modify_index = index
            table[new.id] = new
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node.id))

    def delete_node(self, index: int, node_id: str) -> None:
        with self._lock:
            table = self._writable_table("nodes")
            if node_id not in table:
                raise KeyError(f"node not found: {node_id}")
            del table[node_id]
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node_id))

    def update_node_status(self, index: int, node_id: str,
                           status: str) -> None:
        if not valid_node_status(status):
            raise ValueError(f"invalid node status {status!r}")
        with self._lock:
            table = self._writable_table("nodes")
            existing = table.get(node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            new = existing.copy()
            new.status = status
            new.modify_index = index
            table[node_id] = new
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node_id))

    def update_node_drain(self, index: int, node_id: str,
                          drain: bool) -> None:
        with self._lock:
            table = self._writable_table("nodes")
            existing = table.get(node_id)
            if existing is None:
                raise KeyError(f"node not found: {node_id}")
            new = existing.copy()
            new.drain = drain
            new.modify_index = index
            table[node_id] = new
            self._bump("nodes", index)
        self.watch.notify(("nodes",), ("node", node_id))

    # -- jobs -------------------------------------------------------------
    def upsert_job(self, index: int, job: Job) -> None:
        with self._lock:
            table = self._writable_table("jobs")
            existing = table.get(job.id)
            new = job.copy()
            if existing is not None:
                new.create_index = existing.create_index
            else:
                new.create_index = index
            new.modify_index = index
            table[new.id] = new
            self._bump("jobs", index)
        self.watch.notify(("jobs",), ("job", job.id))

    def delete_job(self, index: int, job_id: str) -> None:
        with self._lock:
            table = self._writable_table("jobs")
            if job_id not in table:
                raise KeyError(f"job not found: {job_id}")
            del table[job_id]
            self._bump("jobs", index)
        self.watch.notify(("jobs",), ("job", job_id))

    # -- evals ------------------------------------------------------------
    def upsert_evals(self, index: int, evals: list) -> None:
        with self._lock:
            table = self._writable_table("evals")
            by_job = self._writable_index("evals_by_job")
            for ev in evals:
                existing = table.get(ev.id)
                new = ev.copy()
                if existing is not None:
                    new.create_index = existing.create_index
                else:
                    new.create_index = index
                new.modify_index = index
                table[new.id] = new
                self._index_add(by_job, new.job_id, new.id)
            self._bump("evals", index)
        self.watch.notify(("evals",))

    def delete_eval(self, index: int, eval_ids: list,
                    alloc_ids: list) -> None:
        """Reap evals + allocs in one txn (reference: Eval.Reap)."""
        touched_nodes = []
        with self._lock:
            evals = self._writable_table("evals")
            by_job = self._writable_index("evals_by_job")
            for eid in eval_ids:
                ev = evals.pop(eid, None)
                if ev is not None:
                    self._index_remove(by_job, ev.job_id, eid)
            allocs = self._writable_table("allocs")
            a_node = self._writable_index("allocs_by_node")
            a_job = self._writable_index("allocs_by_job")
            a_eval = self._writable_index("allocs_by_eval")
            removed = []
            for aid in alloc_ids:
                alloc = allocs.pop(aid, None)
                if alloc is not None:
                    self._index_remove(a_node, alloc.node_id, aid)
                    self._index_remove(a_job, alloc.job_id, aid)
                    self._index_remove(a_eval, alloc.eval_id, aid)
                    touched_nodes.append(alloc.node_id)
                    removed.append(aid)
            self._bump("evals", index)
            self._bump("allocs", index)
            if removed:
                self._log_alloc_change(index, removed)
        keys = [("evals",), ("allocs",)]
        keys += [("alloc-node", n) for n in set(touched_nodes)]
        self.watch.notify(*keys)

    # -- allocs -----------------------------------------------------------
    def upsert_allocs(self, index: int, allocs: list) -> None:
        """Scheduler/plan-authoritative write: preserves client-owned fields
        of any existing alloc (reference: state_store.go:601-637).  One
        item of the batched path — the merge semantics live in exactly
        one place (upsert_allocs_batched)."""
        if not allocs:
            # The batched path skips empty items; a bare index write
            # must still move the table fence — on a PRIVATE generation
            # (_writable_table clones when shared), never in place under
            # a live snapshot.
            with self._lock:
                self._writable_table("allocs")
                self._bump("allocs", index)
            self.watch.notify(("allocs",))
            return
        self.upsert_allocs_batched([(index, allocs)])

    def upsert_allocs_batched(self, items: list) -> None:
        """Group-commit write: ``items`` is ``[(index, allocs), ...]`` in
        eval order, applied under ONE lock hold with one coalesced watch
        notification — byte-identical final state to calling
        ``upsert_allocs(index, allocs)`` per item in order (same
        create/modify indexes, same changelog entries, same last-writer-
        wins on duplicate alloc ids), minus the per-plan lock/notify
        churn.  The raft path passes one shared entry index per item;
        the harness path passes per-plan indexes so sequential replays
        stay index-exact."""
        touched_nodes = []
        # Buckets already copied within THIS call: _index_add/_remove
        # copy the shared bucket set on every touch (snapshot safety);
        # across a whole window that is O(bucket x allocs) churn for
        # buckets that are only shared once.  Copy each bucket the
        # first time the window touches it, then mutate the private
        # copy in place.
        fresh: dict = {}  # (id(index dict), key) -> private bucket

        def add(idx: dict, key: str, item_id: str) -> None:
            bucket = fresh.get((id(idx), key))
            if bucket is None:
                base = idx.get(key)
                bucket = set() if base is None else set(base)
                idx[key] = fresh[(id(idx), key)] = bucket
            bucket.add(item_id)

        def remove(idx: dict, key: str, item_id: str) -> None:
            bucket = fresh.get((id(idx), key))
            if bucket is None:
                base = idx.get(key)
                if base is None:
                    return
                bucket = idx[key] = fresh[(id(idx), key)] = set(base)
            bucket.discard(item_id)
            if not bucket:
                idx.pop(key, None)
                fresh.pop((id(idx), key), None)

        with self._lock:
            table = self._writable_table("allocs")
            a_node = self._writable_index("allocs_by_node")
            a_job = self._writable_index("allocs_by_job")
            a_eval = self._writable_index("allocs_by_eval")
            for index, allocs in items:
                if not allocs:
                    continue
                for alloc in allocs:
                    existing = table.get(alloc.id)
                    new = alloc.copy()
                    if existing is not None:
                        new.create_index = existing.create_index
                        new.client_status = existing.client_status
                        new.client_description = \
                            existing.client_description
                        new.task_states = existing.task_states
                        remove(a_node, existing.node_id, alloc.id)
                    else:
                        new.create_index = index
                    new.modify_index = index
                    table[new.id] = new
                    add(a_node, new.node_id, new.id)
                    add(a_job, new.job_id, new.id)
                    if new.eval_id:
                        add(a_eval, new.eval_id, new.id)
                    touched_nodes.append(new.node_id)
                self._bump("allocs", index)
                self._log_alloc_change(index, [a.id for a in allocs])
        keys = [("allocs",)] + [("alloc-node", n)
                                for n in set(touched_nodes)]
        self.watch.notify(*keys)

    def update_alloc_from_client(self, index: int,
                                 alloc: Allocation) -> None:
        """Client-authoritative merge: only client status fields move
        (reference: state_store.go:556-597)."""
        with self._lock:
            table = self._writable_table("allocs")
            existing = table.get(alloc.id)
            if existing is None:
                raise KeyError(f"alloc not found: {alloc.id}")
            new = existing.copy()
            new.client_status = alloc.client_status
            new.client_description = alloc.client_description
            new.task_states = dict(alloc.task_states)
            new.modify_index = index
            table[new.id] = new
            self._bump("allocs", index)
            self._log_alloc_change(index, (alloc.id,))
        self.watch.notify(("allocs",), ("alloc-node", alloc.node_id))


class StateRestore:
    """Accumulates objects into a fresh generation, swapped in atomically.

    Parity role: state_store.go StateRestore / fsm.go Restore — snapshot
    restore rebuilds the whole store in one transaction.
    """

    def __init__(self, store: StateStore) -> None:
        self._store = store
        self._t = _Tables()

    def node_restore(self, node: Node) -> None:
        self._t.tables["nodes"][node.id] = node
        self._t.indexes["nodes"] = max(self._t.indexes["nodes"],
                                       node.modify_index)

    def job_restore(self, job: Job) -> None:
        self._t.tables["jobs"][job.id] = job
        self._t.indexes["jobs"] = max(self._t.indexes["jobs"],
                                      job.modify_index)

    def eval_restore(self, ev: Evaluation) -> None:
        self._t.tables["evals"][ev.id] = ev
        self._t.indexes["evals"] = max(self._t.indexes["evals"],
                                       ev.modify_index)
        StateStore._index_add(self._t.evals_by_job, ev.job_id, ev.id)

    def alloc_restore(self, alloc: Allocation) -> None:
        self._t.tables["allocs"][alloc.id] = alloc
        self._t.indexes["allocs"] = max(self._t.indexes["allocs"],
                                        alloc.modify_index)
        StateStore._index_add(self._t.allocs_by_node, alloc.node_id, alloc.id)
        StateStore._index_add(self._t.allocs_by_job, alloc.job_id, alloc.id)
        if alloc.eval_id:
            StateStore._index_add(self._t.allocs_by_eval, alloc.eval_id,
                                  alloc.id)

    def index_restore(self, table: str, index: int) -> None:
        self._t.indexes[table] = index

    def commit(self) -> None:
        # A restored generation carries a fresh lineage token (set in
        # _Tables.__init__), forcing every existing mirror to rebuild once
        # — even one whose raft-index fence matches the restored index.
        with self._store._lock:
            self._store._t = self._t
            self._store._gen_shared = False
            self._store._shared = set()
            self._store._idx_shared = set()
        self._store.watch.notify_all()
