from .store import StateRestore, StateSnapshot, StateStore, StateWatch  # noqa: F401
