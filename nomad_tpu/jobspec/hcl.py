"""Minimal HCL reader: the subset jobspecs use.

Supports: ``key = value`` assignments (strings, numbers, booleans, lists),
nested blocks ``name { ... }`` and labeled blocks ``name "label" { ... }``,
and comments (#, //, /* */).  Repeated blocks accumulate into lists.  The
result is a plain dict tree: blocks become ``{"name": [ {..}, ... ]}`` and
labeled blocks carry their label under ``"__label__"``.
"""
from __future__ import annotations

import re
from typing import Any


class HCLError(ValueError):
    pass


_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+)
  | (?P<comment>\#[^\n]*|//[^\n]*|/\*.*?\*/)
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<punct>[{}\[\],=])
  | (?P<ident>[A-Za-z_][A-Za-z0-9_.\-]*)
""", re.VERBOSE | re.DOTALL)

_KEYWORDS = {"true": True, "false": False, "null": None}


def _tokenize(text: str) -> list:
    tokens = []
    pos = 0
    line = 1
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            raise HCLError(f"line {line}: unexpected character "
                           f"{text[pos]!r}")
        kind = m.lastgroup
        value = m.group()
        line += value.count("\n")
        pos = m.end()
        if kind in ("ws", "comment"):
            continue
        tokens.append((kind, value, line))
    tokens.append(("eof", "", line))
    return tokens


class _Parser:
    def __init__(self, tokens: list) -> None:
        self.tokens = tokens
        self.i = 0

    def peek(self):
        return self.tokens[self.i]

    def next(self):
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, kind: str, value: str = None):
        tok = self.next()
        if tok[0] != kind or (value is not None and tok[1] != value):
            raise HCLError(
                f"line {tok[2]}: expected {value or kind}, got {tok[1]!r}")
        return tok

    def parse_body(self, out: dict, until: str) -> dict:
        while True:
            kind, value, line = self.peek()
            if kind == "eof" or (kind == "punct" and value == until):
                self.next()
                return out
            if kind not in ("ident", "string"):
                raise HCLError(
                    f"line {line}: expected key or block, got {value!r}")
            self.next()
            key = _unquote(value) if kind == "string" else value

            kind2, value2, line2 = self.peek()
            if kind2 == "punct" and value2 == "=":
                self.next()
                out[key] = self.parse_value()
            elif kind2 == "string":
                # labeled block: name "label" { ... }
                self.next()
                label = _unquote(value2)
                self.expect("punct", "{")
                block = self.parse_body({"__label__": label}, "}")
                out.setdefault(key, []).append(block)
            elif kind2 == "punct" and value2 == "{":
                self.next()
                block = self.parse_body({}, "}")
                out.setdefault(key, []).append(block)
            else:
                raise HCLError(
                    f"line {line2}: expected '=', label or block after "
                    f"{key!r}")

    def parse_value(self) -> Any:
        kind, value, line = self.next()
        if kind == "string":
            return _unquote(value)
        if kind == "number":
            return float(value) if "." in value else int(value)
        if kind == "ident":
            if value in _KEYWORDS:
                return _KEYWORDS[value]
            return value
        if kind == "punct" and value == "[":
            items = []
            while True:
                k, v, ln = self.peek()
                if k == "punct" and v == "]":
                    self.next()
                    return items
                items.append(self.parse_value())
                k, v, ln = self.peek()
                if k == "punct" and v == ",":
                    self.next()
                elif not (k == "punct" and v == "]"):
                    raise HCLError(f"line {ln}: expected ',' or ']' in "
                                   "list")
        raise HCLError(f"line {line}: unexpected value {value!r}")


_ESCAPES = {'"': '"', "\\": "\\", "n": "\n", "t": "\t", "r": "\r"}


def _unquote(s: str) -> str:
    # Single pass: sequential .replace() would corrupt a literal
    # backslash followed by 'n'/'t' into a control character.
    return re.sub(r"\\(.)",
                  lambda m: _ESCAPES.get(m.group(1), m.group(0)),
                  s[1:-1])


def loads(text: str) -> dict:
    parser = _Parser(_tokenize(text))
    return parser.parse_body({}, "\x00")
