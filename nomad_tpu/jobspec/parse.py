"""Jobspec -> structs.Job.

Capability parity with /root/reference/jobspec/parse.go: job/group/task/
constraint/resources/network/update/env/meta stanzas with the reference's
defaults (region=global, type=service, priority=50, count=1); job-level
bare tasks wrap into a group named after the task (parse.go:128-141);
constraint sugar keys (version=, regexp=) set the operand; duration strings
("30s", "1m") for update.stagger.
"""
from __future__ import annotations

import re
from typing import Optional

from nomad_tpu.utils.duration import parse_duration
from nomad_tpu.structs import (
    Constraint,
    Job,
    NetworkResource,
    Resources,
    Task,
    TaskGroup,
    UpdateStrategy,
)

from .hcl import HCLError, loads


class ParseError(ValueError):
    pass


_DYNAMIC_PORT_RE = re.compile(r"^[a-zA-Z0-9_]+$")


def parse(text: str) -> Job:
    try:
        root = loads(text)
    except HCLError as e:
        raise ParseError(str(e)) from e

    jobs = root.get("job")
    if not jobs:
        raise ParseError("exactly one 'job' block is required")
    if len(jobs) > 1:
        raise ParseError("only one 'job' block per file")
    try:
        return _parse_job(jobs[0])
    except ParseError:
        raise
    except (ValueError, TypeError) as e:
        # Bad field types (priority = "high", count = "x", ...) must
        # surface as ParseError so callers' validation paths catch them.
        raise ParseError(str(e)) from e


def parse_file(path: str) -> Job:
    with open(path) as fh:
        return parse(fh.read())


def _parse_job(obj: dict) -> Job:
    job = Job(
        id=obj.get("__label__", ""),
        # The label is the ID; an explicit ``name`` field may differ
        # (reference test-fixtures/specify-job.hcl).
        name=str(obj.get("name", obj.get("__label__", ""))),
        region=obj.get("region", "global"),
        type=obj.get("type", "service"),
        priority=int(obj.get("priority", 50)),
        all_at_once=bool(obj.get("all_at_once", False)),
        datacenters=list(obj.get("datacenters", [])),
        meta=_parse_meta(obj),
    )
    job.constraints = _parse_constraints(obj)
    updates = obj.get("update", [])
    if len(updates) > 1:
        raise ParseError("only one 'update' block allowed per job")
    for upd in updates:
        job.update = UpdateStrategy(
            stagger=_parse_duration(upd.get("stagger", 0)),
            max_parallel=int(upd.get("max_parallel", 0)),
        )

    for group in obj.get("group", []):
        job.task_groups.append(_parse_group(group))
    # Job-level bare tasks become single-task groups (parse.go:128-141).
    for task_obj in obj.get("task", []):
        task = _parse_task(task_obj)
        job.task_groups.append(TaskGroup(
            name=task.name, count=1, tasks=[task]))

    errs = job.validate()
    if errs:
        raise ParseError("; ".join(errs))
    return job


def _parse_group(obj: dict) -> TaskGroup:
    tg = TaskGroup(
        name=obj.get("__label__", ""),
        count=int(obj.get("count", 1)),
        meta=_parse_meta(obj),
        constraints=_parse_constraints(obj),
    )
    for task_obj in obj.get("task", []):
        tg.tasks.append(_parse_task(task_obj))
    return tg


def _parse_task(obj: dict) -> Task:
    task = Task(
        name=obj.get("__label__", ""),
        driver=obj.get("driver", ""),
        meta=_parse_meta(obj),
        constraints=_parse_constraints(obj),
    )
    for config in obj.get("config", []):
        task.config = {k: v for k, v in config.items()
                       if k != "__label__"}
    for env in obj.get("env", []):
        task.env = {k: str(v) for k, v in env.items()
                    if k != "__label__"}
    resources = obj.get("resources", [])
    if len(resources) > 1:
        # Message verbatim from the reference (parse.go parseResources),
        # singular 'resource' and all, so error-matching stays portable.
        raise ParseError("only one 'resource' block allowed per task")
    for res in resources:
        task.resources = _parse_resources(res)
    return task


def _parse_resources(obj: dict) -> Resources:
    res = Resources(
        cpu=int(obj.get("cpu", 100)),
        memory_mb=int(obj.get("memory", 10)),
        disk_mb=int(obj.get("disk", 0)),
        iops=int(obj.get("iops", 0)),
    )
    nets = obj.get("network", [])
    if len(nets) > 1:
        raise ParseError("only one 'network' resource allowed")
    for net in nets:
        n = NetworkResource(
            mbits=int(net.get("mbits", 10)),
            reserved_ports=[int(p) for p in
                            net.get("reserved_ports", [])],
        )
        # Labels become environment variables, so they must not collide
        # case-insensitively (parse.go:411-426).
        seen: dict = {}
        for label in net.get("dynamic_ports", []):
            label = str(label)
            if not _DYNAMIC_PORT_RE.match(label):
                raise ParseError(
                    f"invalid dynamic port label {label!r}")
            first = seen.get(label.lower())
            if first is not None:
                raise ParseError(
                    f"Found a port label collision: `{label}` "
                    f"overlaps with previous `{first}`")
            seen[label.lower()] = label
            n.dynamic_ports.append(label)
        res.networks.append(n)
    return res


def _parse_constraints(obj: dict) -> list:
    out = []
    for c in obj.get("constraint", []):
        constraint = Constraint(
            hard=bool(c.get("hard", True)),
            l_target=str(c.get("attribute", "")),
            r_target=str(c.get("value", "")),
            operand=str(c.get("operator", "=")),
            weight=int(c.get("weight", 0)),
        )
        # Sugar: version = ">= 1.0" / regexp = "..." set the operand
        # (parse.go:245-258).
        if "version" in c:
            constraint.operand = "version"
            constraint.r_target = str(c["version"])
        elif "regexp" in c:
            constraint.operand = "regexp"
            constraint.r_target = str(c["regexp"])
        elif "distinct_hosts" in c:
            constraint.operand = "distinct_hosts"
            constraint.r_target = ""
        out.append(constraint)
    return out


def _parse_meta(obj: dict) -> dict:
    meta: dict = {}
    for m in obj.get("meta", []):
        meta.update({k: str(v) for k, v in m.items()
                     if k != "__label__"})
    return meta


def _parse_duration(value) -> float:
    """'30s'/'1m'/'500ms' or a bare number of seconds."""
    try:
        return parse_duration(value)
    except ValueError as e:
        raise ParseError(str(e)) from e
