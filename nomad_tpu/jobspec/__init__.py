"""Declarative jobspec parsing (HCL subset)."""
from .parse import parse, parse_file, ParseError  # noqa: F401
from .hcl import loads as hcl_loads  # noqa: F401
