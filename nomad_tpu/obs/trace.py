"""Distributed tracing: per-eval span trees across every plane.

The reference instruments with flat go-metrics timers; flat timers
cannot answer "where did eval X spend its 123 ms submit->respond"
(BENCH_r10 5f) — only a *causal* trace can.  This recorder threads one
context — ``{"trace_id", "span_id"}``, carried exactly like the
``_deadline`` envelope (server/overload.py) — from the client edge
(``ConnPool.call`` / the agent's ``InprocRPC``) through broker
enqueue->dequeue, the scheduler stages, ``Plan.Submit``, the group-
commit window verify, the raft batch apply, the FSM decode, and the
batched store upsert, so one eval's span tree covers agent edge ->
scheduler kernel -> leader commit -> state store.

Design constraints, in order:

- **Disabled = one module-bool check.**  Every instrumentation site in
  the runtime guards on ``trace.ENABLED`` (the same pattern as
  ``faultinject.ACTIVE``); with tracing off the hot path pays a single
  global read.  bench.py asserts the tracing-ON config-4 stream stays
  within 5% of off.
- **Lock-cheap recording.**  Finished spans append to a per-thread
  buffer (plain ``list.append`` — owner-thread only, no lock) and drain
  into one bounded global ring under a single leaf lock every
  ``FLUSH_AT`` spans.  The ring lock acquires nothing else, so it can
  never participate in a lock-order cycle.
- **Bounded.**  The ring holds at most ``ring`` spans; overflow drops
  the OLDEST and counts (``stats()["dropped"]``) — an always-on tracer
  must never be a leak.
- **Monotonic only.**  Span times are ``perf_counter`` deltas against
  the tracer's epoch; no wall clock enters span math, so seeded chaos
  runs replay bit-stable modulo durations.
- **Seedable ids.**  Ids are ``<base><counter>`` hex; ``seed`` pins the
  base so a seeded run's ids are deterministic.

Spans cross threads (an eval is enqueued on one thread, scheduled on a
second, committed on a third), so alongside the ambient
``span()``/``attach()`` stack there is a low-level :meth:`Tracer.record`
that synthesizes a finished span from explicit (t0, dur, ctx) — the
broker's queue-wait span, the applier's per-plan window spans, and the
pipelined runner's cross-thread stage spans all use it.

Applier span taxonomy (the partitioned window verify, ISSUE 13): each
member plan's tree carries ``plan.queued`` (enqueue -> window pop),
then ``applier.window`` (shared t0/dur across the window, tagged
``window`` size and ``components`` count), and under it one
``applier.verify`` span carrying the timing of the claim-graph
COMPONENT that plan verified in (tagged ``component`` scheduling
ordinal, ``size``, ``fallback``) — component walks run concurrently on
the applier's ComponentExecutor, so sibling verify spans under the same
window overlap in time, which is the concurrency made visible.
``raft.apply`` follows as before (shared per window, one per member).

Control-plane taxonomy (ISSUE 14): the feedback controller records one
``control.tick`` span per evaluation (tags ``tick``, ``adjusted``)
with a ``control.adjust`` child per moved knob (``knob``, ``old``,
``new``, ``gauge``, ``direction``, ``reversal``, ``rail``) — the
decision trail that makes a tuning loop auditable after the fact.

Export is Chrome-trace JSON (``chrome://tracing`` / Perfetto "X"
complete events), span tags riding in ``args``.
"""
from __future__ import annotations

import itertools
import json
import threading
import time
from contextlib import contextmanager
from typing import Optional

# Hot-path gate: every runtime instrumentation site checks this single
# module bool before touching the tracer (mirrors faultinject.ACTIVE).
ENABLED = False
_TRACER: Optional["Tracer"] = None

# Envelope key in RPC args, beside overload's ``_deadline``: the wire
# form is {"trace_id": str, "span_id": str}.
TRACE_KEY = "_trace"

# Per-thread buffer drains into the global ring at this many spans.
FLUSH_AT = 64

DEFAULT_RING = 65536


class _ThreadBuf:
    """One thread's span buffer: appended by the owner thread only
    (no lock — list.append is atomic under the GIL), drained into the
    ring by the owner at FLUSH_AT, or by snapshot() for threads that
    have died."""

    __slots__ = ("spans", "thread")

    def __init__(self) -> None:
        self.spans: list = []
        self.thread = threading.current_thread()


class _Ambient(threading.local):
    """Per-thread ambient context stack for the span()/attach() API."""

    def __init__(self) -> None:
        self.stack: list = []


class Tracer:
    def __init__(self, seed: Optional[int] = None,
                 ring: int = DEFAULT_RING) -> None:
        if ring < 1:
            raise ValueError("ring must hold at least one span")
        if seed is None:
            import os
            base = int.from_bytes(os.urandom(4), "big")
        else:
            base = seed & 0xFFFFFFFF
        self._base = f"{base:08x}"
        self._ids = itertools.count(1)
        self._epoch = time.perf_counter()
        self._lock = threading.Lock()   # leaf lock: ring + buffer registry
        self._ring: list = []           # finished spans (dicts), bounded
        self._ring_max = ring
        self._dropped = 0
        self._recorded = 0
        self._bufs: dict = {}           # id(buf) -> _ThreadBuf
        self._local = threading.local()
        self._ambient = _Ambient()

    # -- ids / context -----------------------------------------------------
    def new_id(self) -> str:
        """A fresh span/trace id: deterministic under a seed."""
        return f"{self._base}{next(self._ids):08x}"

    def now(self) -> float:
        """Monotonic seconds since the tracer's epoch."""
        return time.perf_counter() - self._epoch

    def ctx(self) -> Optional[dict]:
        """The ambient context ({"trace_id", "span_id"}) or None."""
        stack = self._ambient.stack
        return stack[-1] if stack else None

    @contextmanager
    def attach(self, ctx: Optional[dict]):
        """Make ``ctx`` ambient for the calling thread (a worker
        adopting a dequeued eval's context)."""
        if not ctx:
            yield
            return
        self._ambient.stack.append(ctx)
        try:
            yield
        finally:
            self._ambient.stack.pop()

    # -- recording ---------------------------------------------------------
    def record(self, name: str, t0: float, dur: float,
               ctx: Optional[dict] = None,
               parent_ctx: Optional[dict] = None,
               span_id: Optional[str] = None, **tags) -> dict:
        """Record one finished span and return its context.

        ``parent_ctx`` sets the parent explicitly (cross-thread spans);
        ``ctx`` continues an existing trace; absent both, the span
        roots a new trace.  ``t0`` is tracer-epoch seconds (see
        :meth:`now`)."""
        if parent_ctx:
            trace_id = parent_ctx.get("trace_id") or self.new_id()
            parent_id = parent_ctx.get("span_id")
        elif ctx:
            trace_id = ctx.get("trace_id") or self.new_id()
            parent_id = ctx.get("parent_id")
        else:
            trace_id = self.new_id()
            parent_id = None
        sid = span_id or (ctx.get("span_id") if ctx else None) \
            or self.new_id()
        span = {
            "name": name,
            "trace_id": trace_id,
            "span_id": sid,
            "parent_id": parent_id,
            "t0": t0,
            "dur": dur,
            "thread": threading.current_thread().name,
        }
        if tags:
            span["tags"] = tags
        self._append(span)
        return {"trace_id": trace_id, "span_id": sid}

    def anchor(self, name: str, parent_ctx: Optional[dict] = None,
               **tags) -> dict:
        """Record an instant anchor span and return its context — the
        single root every later span for one logical entity (an eval)
        descends from, however many threads and retries touch it."""
        now = self.now()
        return self.record(name, now, 0.0, parent_ctx=parent_ctx,
                           span_id=self.new_id(), **tags)

    @contextmanager
    def span(self, name: str, ctx: Optional[dict] = None, **tags):
        """Ambient nested span: parent is ``ctx`` (when given) or the
        current ambient context; the new span becomes ambient for the
        body.  Yields the span's context dict."""
        parent = ctx if ctx is not None else self.ctx()
        mine = {"trace_id": (parent or {}).get("trace_id")
                or self.new_id(),
                "span_id": self.new_id()}
        t0 = self.now()
        self._ambient.stack.append(mine)
        try:
            yield mine
        finally:
            self._ambient.stack.pop()
            # ctx (not parent_ctx): the recorded span must carry the
            # EXACT ids `mine` advertised while it was ambient — a
            # rootless span otherwise minted a second trace id.
            self.record(name, t0, self.now() - t0,
                        ctx={"trace_id": mine["trace_id"],
                             "parent_id": parent["span_id"]
                             if parent else None},
                        span_id=mine["span_id"], **tags)

    def _append(self, span: dict) -> None:
        buf = getattr(self._local, "buf", None)
        if buf is None or buf.thread is not threading.current_thread():
            buf = _ThreadBuf()
            self._local.buf = buf
            with self._lock:
                # Fold dead threads' buffers here, not just in
                # snapshot(): short-lived recording threads (the
                # applier's per-window respond thread) would otherwise
                # grow the registry without bound on an always-on
                # tracer that nobody snapshots.  Amortized: one sweep
                # per NEW thread, over a registry bounded by live
                # threads + the dead ones since the last sweep.
                for key, old in list(self._bufs.items()):
                    if not old.thread.is_alive():
                        if old.spans:
                            spans, old.spans = old.spans, []
                            self._push_locked(spans)
                        del self._bufs[key]
                self._bufs[id(buf)] = buf
        buf.spans.append(span)
        if len(buf.spans) >= FLUSH_AT:
            spans, buf.spans = buf.spans, []
            with self._lock:
                self._push_locked(spans)

    def _push_locked(self, spans: list) -> None:
        self._recorded += len(spans)
        self._ring.extend(spans)
        over = len(self._ring) - self._ring_max
        if over > 0:
            del self._ring[:over]
            self._dropped += over

    # -- export ------------------------------------------------------------
    def snapshot(self) -> list:
        """Every retained span (ring + still-buffered), oldest-first by
        arrival.  Non-destructive; buffers of dead threads are folded
        into the ring so they cannot linger unbounded."""
        with self._lock:
            for key, buf in list(self._bufs.items()):
                if not buf.thread.is_alive() and buf.spans:
                    spans, buf.spans = buf.spans, []
                    self._push_locked(spans)
                if not buf.thread.is_alive():
                    del self._bufs[key]
            out = list(self._ring)
            for buf in self._bufs.values():
                out.extend(list(buf.spans))
        return out

    def stats(self) -> dict:
        with self._lock:
            buffered = sum(len(b.spans) for b in self._bufs.values())
            return {"ring": len(self._ring), "buffered": buffered,
                    "recorded": self._recorded + buffered,
                    "dropped": self._dropped,
                    "ring_max": self._ring_max}

    def chrome_trace(self) -> dict:
        """Chrome-trace/Perfetto JSON: one complete ("X") event per
        span, timestamps in microseconds since the tracer epoch, tags
        under ``args`` beside the span/parent ids."""
        events = []
        tids: dict = {}
        for s in self.snapshot():
            tid = tids.setdefault(s["thread"], len(tids) + 1)
            args = {"trace_id": s["trace_id"], "span_id": s["span_id"],
                    "parent_id": s["parent_id"]}
            args.update(s.get("tags") or {})
            events.append({
                "name": s["name"], "cat": s["name"].split(".")[0],
                "ph": "X",
                "ts": round(s["t0"] * 1e6, 1),
                "dur": round(s["dur"] * 1e6, 1),
                "pid": 1, "tid": tid,
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"tracer": "nomad-tpu obs",
                              "threads": {str(v): k
                                          for k, v in tids.items()}}}

    def export_chrome(self, path: str) -> int:
        """Write the Chrome-trace JSON; returns the event count."""
        doc = self.chrome_trace()
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return len(doc["traceEvents"])


# ---------------------------------------------------------------------------
# module-level convenience API (no-ops unless enabled)
# ---------------------------------------------------------------------------

def enable(seed: Optional[int] = None,
           ring: int = DEFAULT_RING) -> Tracer:
    """Install a fresh process-global tracer and flip the hot-path
    gate.  Returns the tracer."""
    global _TRACER, ENABLED
    _TRACER = Tracer(seed=seed, ring=ring)
    ENABLED = True
    return _TRACER


def disable() -> None:
    global _TRACER, ENABLED
    ENABLED = False
    _TRACER = None


def tracer() -> Optional[Tracer]:
    return _TRACER


@contextmanager
def tracing(seed: Optional[int] = None, ring: int = DEFAULT_RING):
    """Scoped enable/disable for tests and benches; yields the tracer."""
    t = enable(seed=seed, ring=ring)
    try:
        yield t
    finally:
        disable()


def ctx() -> Optional[dict]:
    t = _TRACER
    return t.ctx() if t is not None else None


def inject(args: dict) -> dict:
    """Stamp the ambient context into an RPC args dict (the `_deadline`
    discipline: copy, never mutate the caller's dict — retry loops
    re-send the same args)."""
    t = _TRACER
    if t is None:
        return args
    current = t.ctx()
    if current is None or TRACE_KEY in args:
        return args
    return dict(args, **{TRACE_KEY: {"trace_id": current["trace_id"],
                                     "span_id": current["span_id"]}})


@contextmanager
def client_call(method: str, args: dict):
    """The client-edge instrumentation shared by ``ConnPool.call`` and
    the agent's ``InprocRPC``: stamp the trace envelope (copying args —
    retry loops re-send the same dict) and record one
    ``rpc.client.<method>`` span per attempt.  When no ambient context
    exists the client span roots the trace and the envelope carries its
    id, so the server-side tree hangs off the agent edge."""
    t = _TRACER
    if t is None:
        yield args
        return
    parent = t.ctx()
    sid = t.new_id()
    tid = parent["trace_id"] if parent else t.new_id()
    if TRACE_KEY not in args:
        args = dict(args, **{TRACE_KEY: {"trace_id": tid,
                                         "span_id": sid}})
    t0 = t.now()
    try:
        yield args
    finally:
        t.record("rpc.client." + method, t0, t.now() - t0,
                 ctx={"trace_id": tid,
                      "parent_id": parent["span_id"] if parent
                      else None},
                 span_id=sid, method=method)


def extract(args: dict) -> Optional[dict]:
    """The envelope context from arriving RPC args (left in place so
    leader/region forwards keep propagating it)."""
    got = args.get(TRACE_KEY)
    if isinstance(got, dict) and got.get("trace_id"):
        return {"trace_id": got.get("trace_id"),
                "span_id": got.get("span_id")}
    return None
