"""Unified metrics registry: every ``stats()`` dict, one namespace.

The runtime grew 14 per-component ``stats()`` providers (applier,
broker, overload, heartbeat, edge loop, dispatch pool, plan queue,
breaker, runner, store watch, swarm, ...) that were never exported —
each bench and test hand-collected the ones it knew about.  The
registry turns them into one tree:

- ``register(name, fn)`` parks a zero-argument provider returning a
  (possibly nested) dict; ``snapshot()`` calls every provider and
  flattens the result into dotted gauges — key grammar
  ``nomad.<provider>.<path...>`` with nested dicts joined by dots and
  non-numeric leaves stringified (they publish as labels, not gauges).
- ``publish(metrics)`` pushes the numeric leaves as gauges into a
  ``utils/metrics.Metrics`` fanout (in-memory sink + optional statsd),
  so the existing telemetry plumbing (SIGUSR1 dump, statsd) sees the
  same numbers with no second producer.
- A provider that raises is reported under ``nomad.<name>.error``
  instead of wedging the snapshot — a torn-down component must never
  take the metrics plane with it (same discipline as
  ``OverloadController.pressure``).

Instances are cheap and owned: each ``Server`` builds its own (its
providers close over live components and die with it); the module-
global :data:`REGISTRY` carries process-wide singletons only (the
device circuit breaker, a live agent swarm).  ``snapshot(extra=...)``
merges other registries so the agent's ``/v1/agent/metrics`` endpoint
serves server + process registries as one document.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

PREFIX = "nomad"


def flatten(tree: dict, prefix: str = "") -> dict:
    """Nested dict -> {"a.b.c": leaf}.  Lists/tuples are summarized by
    length (a gauge), everything non-numeric is stringified."""
    out: dict = {}
    for key, val in tree.items():
        path = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(val, dict):
            out.update(flatten(val, path))
        elif isinstance(val, (list, tuple)):
            out[f"{path}.len"] = len(val)
        elif isinstance(val, bool):
            out[path] = int(val)
        elif isinstance(val, (int, float)):
            out[path] = val
        else:
            out[path] = str(val)
    return out


class _Sampler:
    """Long-lived worker running providers under a deadline for
    :meth:`MetricsRegistry.collect` — the ``_CollectWorker`` pattern
    from scheduler/pipeline.py: one callable at a time via ``inq``,
    result on ``outq``; on a timeout the registry abandons this worker
    (its queues go with it, so a late result can never be mistaken for
    a later provider's) and tells it to exit via the ``None`` sentinel
    once the hung call finally returns."""

    def __init__(self) -> None:
        self.inq: queue.Queue = queue.Queue()
        self.outq: queue.Queue = queue.Queue()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="metrics-sampler")
        self.thread.start()

    def _run(self) -> None:
        while True:
            fn = self.inq.get()
            if fn is None:
                return
            try:
                self.outq.put((True, fn()))
            except BaseException as e:
                self.outq.put((False, e))

    def join(self, timeout: "float | None" = None) -> None:
        """Reap after the exit sentinel (clean-shutdown path only; an
        abandoned sampler dies on its own when the hung call returns)."""
        self.thread.join(timeout)


class MetricsRegistry:
    def __init__(self, clock: Callable[[], float] = time.monotonic
                 ) -> None:
        self._lock = threading.Lock()
        self._providers: dict = {}   # token -> (name, fn)
        self._seq = 0
        self._clock = clock
        # Staleness tracking for collect(): provider name ->
        # (value fingerprint, clock() when it last changed).
        self._ages: dict = {}
        # Lazy deadline-bounded sampler (collect(timeout=...) only).
        # ``_sampler_gen`` bumps on clear(): a collect() mid-flight
        # when the registry is torn down must not park its claimed
        # sampler back into the cleared registry (nobody would ever
        # send that thread its exit sentinel again).
        self._sampler: Optional[_Sampler] = None
        self._sampler_gen = 0

    # -- wiring ------------------------------------------------------------
    def register(self, name: str, fn: Callable[[], dict]) -> str:
        """Park a provider; returns a deregistration token.  Names are
        unique — re-registering a live name replaces it (a restarted
        component supersedes its predecessor)."""
        with self._lock:
            self._seq += 1
            token = f"p{self._seq}"
            for tok, (got, _fn) in list(self._providers.items()):
                if got == name:
                    del self._providers[tok]
            self._providers[token] = (name, fn)
            # A replaced name is a NEW provider: its staleness clock
            # restarts (collect() must not blame the successor for the
            # predecessor's frozen values).
            self._ages.pop(name, None)
            return token

    def deregister(self, token: str) -> bool:
        with self._lock:
            got = self._providers.pop(token, None)
            if got is not None:
                self._ages.pop(got[0], None)
            return got is not None

    def clear(self) -> None:
        with self._lock:
            self._providers.clear()
            self._ages.clear()
            sampler, self._sampler = self._sampler, None
            self._sampler_gen += 1
        if sampler is not None:
            sampler.inq.put(None)
            sampler.join(2.0)

    def providers(self) -> list:
        with self._lock:
            return sorted(name for name, _fn in self._providers.values())

    # -- reading -----------------------------------------------------------
    def snapshot(self, extra: Optional[list] = None) -> dict:
        """One flattened ``{dotted_key: value}`` document over every
        provider (plus the providers of any ``extra`` registries).
        Providers run OUTSIDE the registry lock — they read other
        components' locks and must not nest under ours."""
        with self._lock:
            providers = list(self._providers.values())
        if extra:
            for reg in extra:
                with reg._lock:
                    providers.extend(reg._providers.values())
        out: dict = {}
        for name, fn in providers:
            base = f"{PREFIX}.{name}"
            try:
                stats = fn()
            except Exception as e:
                out[f"{base}.error"] = f"{type(e).__name__}: {e}"
                continue
            if not isinstance(stats, dict):
                out[f"{base}.error"] = "provider returned non-dict"
                continue
            out.update(flatten(stats, base))
        return out

    def collect(self, timeout: Optional[float] = None,
                extra: Optional[list] = None) -> dict:
        """:meth:`snapshot` hardened for a serving surface: stamps a
        per-provider ``nomad.<name>.age_s`` gauge (seconds since the
        provider's flattened value last CHANGED — a component that
        keeps returning the same frozen numbers is stale even though
        its call succeeds), and with a ``timeout`` runs each provider
        under a deadline on a long-lived sampler worker so one hung
        provider (wedged on a dead component's lock) isolates as
        ``.error = "sample timeout"`` instead of blocking the whole
        collection.  Staleness is tracked on THIS registry for its own
        providers and for ``extra`` registries' providers alike (keyed
        by provider name)."""
        with self._lock:
            providers = list(self._providers.values())
        if extra:
            for reg in extra:
                with reg._lock:
                    providers.extend(reg._providers.values())
        out: dict = {}
        now = self._clock()
        for name, fn in providers:
            base = f"{PREFIX}.{name}"
            ok, got = self._sample(fn, timeout)
            if ok and not isinstance(got, dict):
                ok, got = False, TypeError("provider returned non-dict")
            if not ok:
                out[f"{base}.error"] = got if isinstance(got, str) \
                    else f"{type(got).__name__}: {got}"
                with self._lock:
                    aged = self._ages.get(name)
                if aged is not None:
                    out[f"{base}.age_s"] = round(now - aged[1], 3)
                continue
            flat = flatten(got, base)
            out.update(flat)
            fp = hash(tuple(sorted(
                (k, str(v)) for k, v in flat.items())))
            with self._lock:
                aged = self._ages.get(name)
                if aged is None or aged[0] != fp:
                    self._ages[name] = (fp, now)
                    aged = self._ages[name]
            out[f"{base}.age_s"] = round(now - aged[1], 3)
        return out

    def _sample(self, fn, timeout: Optional[float]) -> tuple:
        """(ok, value-or-error) for one provider, under the optional
        deadline.  The sampler worker is reused across samples; a
        timed-out worker is abandoned mid-call and replaced (see
        :class:`_Sampler`)."""
        if timeout is None:
            try:
                return True, fn()
            except Exception as e:
                return False, e
        # CLAIM the parked sampler (slot set to None) so two concurrent
        # collect() calls can never interleave one worker's queues;
        # a healthy sampler parks back afterwards — unless clear()
        # bumped the generation meanwhile (teardown), in which case it
        # is reaped here instead of outliving its registry.  A second
        # sampler born from a claim race is reaped the same way.
        with self._lock:
            sampler, self._sampler = self._sampler, None
            gen = self._sampler_gen
        if sampler is None:
            sampler = _Sampler()
        sampler.inq.put(fn)
        try:
            ok, val = sampler.outq.get(timeout=timeout)
        except queue.Empty:
            sampler.inq.put(None)  # abandoned: exits after the hung call
            return False, f"sample timeout after {timeout}s"
        with self._lock:
            if self._sampler is None and self._sampler_gen == gen:
                self._sampler = sampler
                sampler = None
        if sampler is not None:
            sampler.inq.put(None)
            sampler.join(1.0)
        if not ok and isinstance(val, BaseException) \
                and not isinstance(val, Exception):
            raise val  # KeyboardInterrupt and friends propagate
        return ok, val

    def publish(self, metrics, extra: Optional[list] = None) -> int:
        """Push every numeric leaf as a gauge into a utils/metrics
        fanout; returns the number of gauges set."""
        snap = self.snapshot(extra=extra)
        n = 0
        for key, val in snap.items():
            if isinstance(val, (int, float)):
                metrics.set_gauge(key, float(val))
                n += 1
        return n


# Process-wide singletons only (device breaker, live swarms); component
# registries are per-owner and die with their owner.
REGISTRY = MetricsRegistry()
