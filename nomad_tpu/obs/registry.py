"""Unified metrics registry: every ``stats()`` dict, one namespace.

The runtime grew 14 per-component ``stats()`` providers (applier,
broker, overload, heartbeat, edge loop, dispatch pool, plan queue,
breaker, runner, store watch, swarm, ...) that were never exported —
each bench and test hand-collected the ones it knew about.  The
registry turns them into one tree:

- ``register(name, fn)`` parks a zero-argument provider returning a
  (possibly nested) dict; ``snapshot()`` calls every provider and
  flattens the result into dotted gauges — key grammar
  ``nomad.<provider>.<path...>`` with nested dicts joined by dots and
  non-numeric leaves stringified (they publish as labels, not gauges).
- ``publish(metrics)`` pushes the numeric leaves as gauges into a
  ``utils/metrics.Metrics`` fanout (in-memory sink + optional statsd),
  so the existing telemetry plumbing (SIGUSR1 dump, statsd) sees the
  same numbers with no second producer.
- A provider that raises is reported under ``nomad.<name>.error``
  instead of wedging the snapshot — a torn-down component must never
  take the metrics plane with it (same discipline as
  ``OverloadController.pressure``).

Instances are cheap and owned: each ``Server`` builds its own (its
providers close over live components and die with it); the module-
global :data:`REGISTRY` carries process-wide singletons only (the
device circuit breaker, a live agent swarm).  ``snapshot(extra=...)``
merges other registries so the agent's ``/v1/agent/metrics`` endpoint
serves server + process registries as one document.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional

PREFIX = "nomad"


def flatten(tree: dict, prefix: str = "") -> dict:
    """Nested dict -> {"a.b.c": leaf}.  Lists/tuples are summarized by
    length (a gauge), everything non-numeric is stringified."""
    out: dict = {}
    for key, val in tree.items():
        path = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(val, dict):
            out.update(flatten(val, path))
        elif isinstance(val, (list, tuple)):
            out[f"{path}.len"] = len(val)
        elif isinstance(val, bool):
            out[path] = int(val)
        elif isinstance(val, (int, float)):
            out[path] = val
        else:
            out[path] = str(val)
    return out


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._providers: dict = {}   # token -> (name, fn)
        self._seq = 0

    # -- wiring ------------------------------------------------------------
    def register(self, name: str, fn: Callable[[], dict]) -> str:
        """Park a provider; returns a deregistration token.  Names are
        unique — re-registering a live name replaces it (a restarted
        component supersedes its predecessor)."""
        with self._lock:
            self._seq += 1
            token = f"p{self._seq}"
            for tok, (got, _fn) in list(self._providers.items()):
                if got == name:
                    del self._providers[tok]
            self._providers[token] = (name, fn)
            return token

    def deregister(self, token: str) -> bool:
        with self._lock:
            return self._providers.pop(token, None) is not None

    def clear(self) -> None:
        with self._lock:
            self._providers.clear()

    def providers(self) -> list:
        with self._lock:
            return sorted(name for name, _fn in self._providers.values())

    # -- reading -----------------------------------------------------------
    def snapshot(self, extra: Optional[list] = None) -> dict:
        """One flattened ``{dotted_key: value}`` document over every
        provider (plus the providers of any ``extra`` registries).
        Providers run OUTSIDE the registry lock — they read other
        components' locks and must not nest under ours."""
        with self._lock:
            providers = list(self._providers.values())
        if extra:
            for reg in extra:
                with reg._lock:
                    providers.extend(reg._providers.values())
        out: dict = {}
        for name, fn in providers:
            base = f"{PREFIX}.{name}"
            try:
                stats = fn()
            except Exception as e:
                out[f"{base}.error"] = f"{type(e).__name__}: {e}"
                continue
            if not isinstance(stats, dict):
                out[f"{base}.error"] = "provider returned non-dict"
                continue
            out.update(flatten(stats, base))
        return out

    def publish(self, metrics, extra: Optional[list] = None) -> int:
        """Push every numeric leaf as a gauge into a utils/metrics
        fanout; returns the number of gauges set."""
        snap = self.snapshot(extra=extra)
        n = 0
        for key, val in snap.items():
            if isinstance(val, (int, float)):
                metrics.set_gauge(key, float(val))
                n += 1
        return n


# Process-wide singletons only (device breaker, live swarms); component
# registries are per-owner and die with their owner.
REGISTRY = MetricsRegistry()
