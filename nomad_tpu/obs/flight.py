"""Flight recorder: turn a bad moment into a post-hoc debuggable file.

Chaos and soak failures die with their evidence: by the time a human
looks, the span ring has wrapped, the stacks have moved on, and the
queue depths are back to normal.  The flight recorder dumps the black
box AT the moment something trips:

- **breaker-open** — the device executor just went unhealthy
  (scheduler/breaker.py calls :func:`trip` on CLOSED->OPEN);
- **overload entry** — the admission plane entered the shedding state
  (server/overload.py calls :func:`trip` on *->OVERLOAD);
- **stall watchdog** — a guarded section (a plan-apply window, a drain
  window) overstayed its deadline (:class:`StallWatchdog` /
  :func:`guard`).

Each trip writes ONE bounded JSON incident file —
``incident-<seq>-<reason>.json`` under the installed directory —
carrying the last-N spans from the trace ring, every live thread's
stack (utils/profiling.thread_stacks — the pprof-goroutine analogue),
and a metrics snapshot (the caller-supplied registries plus the in-mem
telemetry sink).  Bounds, so the recorder can never become the
incident: at most ``max_files`` newest incidents on disk (oldest
pruned), at most ``max_spans`` spans per file, and a per-reason
``min_interval`` rate limit (a flapping breaker must not write a
thousand files).

Everything is a no-op until :func:`install` runs — the trip sites in
breaker/overload pay one module-bool read when no recorder is
installed (the same gate discipline as ``trace.ENABLED`` and
``faultinject.ACTIVE``).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Optional

logger = logging.getLogger("nomad_tpu.obs.flight")

# Hot-path gate, mirrored from trace.ENABLED / faultinject.ACTIVE.
INSTALLED = False
_RECORDER: Optional["FlightRecorder"] = None


class FlightRecorder:
    def __init__(self, directory: str, max_files: int = 8,
                 max_spans: int = 2048,
                 min_interval: float = 5.0,
                 registries: Optional[list] = None,
                 extra_fn=None,
                 clock=time.monotonic) -> None:
        self.directory = directory
        self.max_files = max(1, max_files)
        self.max_spans = max(1, max_spans)
        self.min_interval = min_interval
        self.registries = list(registries or [])
        # Process-context hook, the recorder-level twin of the stall
        # guard's per-section extra_fn: a zero-arg -> dict called at
        # dump time and merged into EVERY incident's extra under
        # "context" (the feedback controller passes its per-knob
        # positions, so any incident names where every knob sat).
        # Best-effort: a failing hook must not eat the incident.
        self.extra_fn = extra_fn
        self._clock = clock
        self._lock = threading.Lock()
        self._seq = 0
        self._last_by_reason: dict = {}   # reason -> last trip time
        self.trips = 0          # incidents written; guarded
        self.suppressed = 0     # rate-limited trips; guarded
        os.makedirs(directory, exist_ok=True)

    def add_registry(self, registry) -> None:
        with self._lock:
            self.registries.append(registry)

    # -- the trip path -----------------------------------------------------
    def record(self, reason: str, extra: Optional[dict] = None
               ) -> Optional[str]:
        """Dump one incident; returns the file path (None when rate-
        limited).  Never raises — a failing dump logs and returns None;
        the triggering subsystem must not inherit recorder errors."""
        now = self._clock()
        with self._lock:
            last = self._last_by_reason.get(reason)
            if last is not None and now - last < self.min_interval:
                self.suppressed += 1
                return None
            self._last_by_reason[reason] = now
            self._seq += 1
            seq = self._seq
            self.trips += 1
        try:
            return self._write(seq, reason, extra)
        except Exception:
            logger.exception("flight recorder: dump for %r failed",
                             reason)
            return None

    def _write(self, seq: int, reason: str,
               extra: Optional[dict]) -> str:
        from nomad_tpu.utils import profiling
        from nomad_tpu.utils.metrics import metrics

        from . import trace as trace_mod

        spans: list = []
        tracer = trace_mod.tracer()
        if tracer is not None:
            spans = tracer.snapshot()[-self.max_spans:]
        providers: dict = {}
        with self._lock:
            registries = list(self.registries)
        for reg in registries:
            try:
                providers.update(reg.snapshot())
            except Exception as e:
                providers["nomad.flight.registry_error"] = str(e)
        extra = dict(extra or {})
        if self.extra_fn is not None:
            try:
                extra["context"] = self.extra_fn()
            except Exception:
                logger.exception("flight recorder: extra_fn failed")
        doc = {
            "reason": reason,
            "seq": seq,
            "monotonic": self._clock(),
            "extra": extra,
            "spans": spans,
            "thread_stacks": profiling.thread_stacks(),
            "metrics": {
                "providers": providers,
                "inmem": metrics.inmem.snapshot(),
            },
        }
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in reason)
        path = os.path.join(self.directory,
                            f"incident-{seq:04d}-{safe}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, default=str)
        # faultlint-ok(uninjectable-io): observability plane — incident
        # snapshots never feed replicated or serving state.
        os.replace(tmp, path)
        self._prune()
        return path

    def _prune(self) -> None:
        """Keep only the newest ``max_files`` incidents on disk."""
        try:
            names = sorted(n for n in os.listdir(self.directory)
                           if n.startswith("incident-")
                           and n.endswith(".json"))
        except OSError:
            return
        for name in names[:-self.max_files]:
            try:
                os.remove(os.path.join(self.directory, name))
            except OSError:
                pass

    def incidents(self) -> list:
        """Incident file names on disk, oldest first."""
        try:
            return sorted(n for n in os.listdir(self.directory)
                          if n.startswith("incident-")
                          and n.endswith(".json"))
        except OSError:
            return []

    def stats(self) -> dict:
        with self._lock:
            return {"trips": self.trips, "suppressed": self.suppressed,
                    "on_disk": len(self.incidents())}


class StallWatchdog:
    """One checker thread watching armed sections for overstays.

    ``guard(name, timeout)`` arms a deadline around a section that
    should complete promptly (a plan-apply window, a drain window); a
    section still armed past its deadline trips the flight recorder
    ONCE (per arm) with the stalled section's name.  The thread wakes
    on arm/disarm/stop and otherwise sleeps to the earliest untripped
    deadline (indefinitely when nothing is armed), so an idle — or
    merely guarded — watchdog costs nothing.  ``stop()`` joins the
    thread — the lifecycle lint requires every thread reaped."""

    def __init__(self, on_stall) -> None:
        self.on_stall = on_stall     # fn(name, age_seconds, extra)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._armed: dict = {}   # token -> (name, armed_at, deadline,
        #                                    extra_fn)
        self._tripped: set = set()   # tokens already reported
        self._seq = 0
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="flight-stall-watchdog")
        self._thread.start()

    def arm(self, name: str, timeout: float, extra_fn=None) -> str:
        """``extra_fn`` (optional, zero-arg -> dict) is called AT trip
        time on the watchdog thread and merged into the incident's
        extra — the guarded section's own attribution of what it is
        stuck on (the plan applier passes its component executor's
        ``active()``, so a wedged window names the slow component)."""
        with self._cond:
            self._seq += 1
            token = f"g{self._seq}"
            now = time.monotonic()
            self._armed[token] = (name, now, now + timeout, extra_fn)
            self._cond.notify_all()
            return token

    def disarm(self, token: str) -> None:
        with self._cond:
            self._armed.pop(token, None)
            self._tripped.discard(token)

    @contextmanager
    def guard(self, name: str, timeout: float, extra_fn=None):
        token = self.arm(name, timeout, extra_fn)
        try:
            yield
        finally:
            self.disarm(token)

    def _run(self) -> None:
        while True:
            fire: list = []
            with self._cond:
                if self._stopped:
                    return
                now = time.monotonic()
                next_deadline = None
                for token, (name, armed_at, deadline, extra_fn) in \
                        self._armed.items():
                    if token in self._tripped:
                        continue
                    if now >= deadline:
                        self._tripped.add(token)
                        fire.append((name, now - armed_at, extra_fn))
                    elif next_deadline is None or \
                            deadline < next_deadline:
                        next_deadline = deadline
                if not fire:
                    # Earliest untripped deadline, or indefinitely
                    # (arm/disarm/stop all notify the condition).
                    self._cond.wait(None if next_deadline is None
                                    else next_deadline - now)
                    continue
            for name, age, extra_fn in fire:
                extra = None
                if extra_fn is not None:
                    # The section's own attribution, best-effort: a
                    # failing extra_fn must not eat the incident.
                    try:
                        extra = extra_fn()
                    except Exception:
                        logger.exception(
                            "stall attribution for %r failed", name)
                try:
                    self.on_stall(name, age, extra)
                except Exception:
                    logger.exception("stall watchdog callback failed")

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not threading.current_thread():
            self._thread.join(2.0)


# ---------------------------------------------------------------------------
# module-level gate (trip sites in breaker/overload use these)
# ---------------------------------------------------------------------------

_WATCHDOG: Optional[StallWatchdog] = None


def install(directory: str, registries: Optional[list] = None,
            **kw) -> FlightRecorder:
    """Install the process flight recorder (and its stall watchdog)."""
    global _RECORDER, _WATCHDOG, INSTALLED
    uninstall()
    rec = FlightRecorder(directory, registries=registries, **kw)
    _RECORDER = rec
    _WATCHDOG = StallWatchdog(
        lambda name, age, extra: trip(
            "stall." + name,
            dict(extra or {}, stalled_for_s=round(age, 3))))
    INSTALLED = True
    return rec


def uninstall() -> None:
    global _RECORDER, _WATCHDOG, INSTALLED
    INSTALLED = False
    watchdog, _WATCHDOG = _WATCHDOG, None
    _RECORDER = None
    if watchdog is not None:
        watchdog.stop()


def recorder() -> Optional[FlightRecorder]:
    return _RECORDER


@contextmanager
def installed(directory: str, **kw):
    """Scoped install/uninstall for tests and benches."""
    rec = install(directory, **kw)
    try:
        yield rec
    finally:
        uninstall()


def trip(reason: str, extra: Optional[dict] = None) -> Optional[str]:
    """Dump an incident if a recorder is installed; no-op otherwise.
    Callers gate on ``flight.INSTALLED`` first so the common path is
    one module-bool read."""
    rec = _RECORDER
    if rec is None:
        return None
    return rec.record(reason, extra)


@contextmanager
def guard(name: str, timeout: float, extra_fn=None):
    """Stall-guard a section: if it overstays ``timeout`` the watchdog
    trips ``stall.<name>``, merging ``extra_fn()`` (the section's own
    attribution — e.g. which window component is still verifying) into
    the incident extra.  No-op when no recorder is installed."""
    watchdog = _WATCHDOG
    if watchdog is None:
        yield
        return
    with watchdog.guard(name, timeout, extra_fn=extra_fn):
        yield
