"""Trace & telemetry plane.

Three cooperating pieces (README "Observability"):

- :mod:`nomad_tpu.obs.trace` — per-eval distributed tracing: a span
  recorder (lock-cheap per-thread buffers, bounded global ring,
  seedable ids, monotonic-only timestamps) plus the ``_trace`` RPC
  envelope, exportable as Chrome-trace/Perfetto JSON.
- :mod:`nomad_tpu.obs.registry` — the unified metrics registry turning
  every component ``stats()`` into ``nomad.<provider>.<path>`` gauges,
  served at ``/v1/agent/metrics`` and via ``nomad-tpu metrics``.
- :mod:`nomad_tpu.obs.flight` — the flight recorder: on breaker-open,
  overload entry, or a stall-watchdog trip, dump span ring + thread
  stacks + metrics snapshot to a bounded on-disk incident file.

Layering: obs imports nothing from nomad_tpu outside ``utils`` — every
other subsystem may import obs without cycles.
"""
from . import flight, registry, trace  # noqa: F401
from .registry import REGISTRY, MetricsRegistry, flatten  # noqa: F401
from .trace import TRACE_KEY, Tracer, tracing  # noqa: F401
from .flight import FlightRecorder, StallWatchdog  # noqa: F401
