"""tpu-nomad: a TPU-native cluster scheduling framework.

Capability parity with HashiCorp Nomad v0.1.2, re-designed TPU-first: the
host plane (RPC, raft, broker, agents) is Python/asyncio; the scheduler core
(feasibility filtering + bin-pack ranking) is vectorized JAX over
device-resident fleet tensors, sharded over a jax.sharding.Mesh.
"""

__version__ = "0.1.0"
