"""Shared pure helpers with no package-level dependencies.

Lives below both the scheduler layer and the device-model layer so constraint
predicates are importable from either side without cycles.
"""
from .predicates import (  # noqa: F401
    check_constraint_values,
    resolve_constraint_target,
)
from .versions import check_constraint as check_version_constraint  # noqa: F401
