"""Constraint predicate primitives shared by the sequential scheduler and the
TPU constraint-mask compiler.

Capability parity with /root/reference/scheduler/feasible.go:226-376
(resolveConstraintTarget + checkConstraint).  Both execution paths — the lazy
ConstraintIterator and the vectorized mask compiler — call these exact
functions, so parity between them holds by construction.

The ``ctx`` argument only needs ``regexp_cache`` / ``constraint_cache`` dict
attributes (EvalContext provides them; the mask compiler passes its own).
"""
from __future__ import annotations

import re

from .versions import check_constraint as check_version_constraint


def resolve_constraint_target(target: str, node):
    """Interpolate $node.*, $attr.*, $meta.*; literals pass through.

    Returns (value, ok) (reference: feasible.go:226-256).
    """
    if not target.startswith("$"):
        return target, True
    if target == "$node.id":
        return node.id, True
    if target == "$node.datacenter":
        return node.datacenter, True
    if target == "$node.name":
        return node.name, True
    if target.startswith("$attr."):
        key = target[len("$attr."):]
        if key in node.attributes:
            return node.attributes[key], True
        return None, False
    if target.startswith("$meta."):
        key = target[len("$meta."):]
        if key in node.meta:
            return node.meta[key], True
        return None, False
    return None, False


def check_constraint_values(ctx, operand: str, l_val, r_val) -> bool:
    """Evaluate one operand against resolved values (feasible.go:259-376)."""
    if operand in ("=", "==", "is"):
        return l_val == r_val
    if operand in ("!=", "not"):
        return l_val != r_val
    if operand in ("<", "<=", ">", ">="):
        return _check_lexical_order(operand, l_val, r_val)
    if operand == "version":
        return _check_version(ctx, l_val, r_val)
    if operand == "regexp":
        return _check_regexp(ctx, l_val, r_val)
    return False


def _check_lexical_order(op: str, l_val, r_val) -> bool:
    if not isinstance(l_val, str) or not isinstance(r_val, str):
        return False
    return {
        "<": l_val < r_val,
        "<=": l_val <= r_val,
        ">": l_val > r_val,
        ">=": l_val >= r_val,
    }[op]


def _check_version(ctx, l_val, r_val) -> bool:
    if isinstance(l_val, int):
        l_val = str(l_val)
    if not isinstance(l_val, str) or not isinstance(r_val, str):
        return False
    cache = ctx.constraint_cache
    result = cache.get((l_val, r_val))
    if result is None:
        result = check_version_constraint(l_val, r_val)
        cache[(l_val, r_val)] = result
    return result


def _check_regexp(ctx, l_val, r_val) -> bool:
    if not isinstance(l_val, str) or not isinstance(r_val, str):
        return False
    cache = ctx.regexp_cache
    pattern = cache.get(r_val)
    if pattern is None:
        try:
            pattern = re.compile(r_val)
        except re.error:
            return False
        cache[r_val] = pattern
    return pattern.search(l_val) is not None
