"""Typed concurrency annotations the lock lint enforces.

PR 1's allowlist accumulated a pile of ``bare-read`` waivers whose
justifications all said one of two things: "immutable after __init__" or
"rebound atomically by copy-swap".  Those are *contracts*, and a waiver
ledger is the wrong place for a contract — nothing ever checks that the
attribute actually stays immutable, so the justification rots silently.

These markers move the contract into the type surface where
``nomad_tpu/analysis/lockcheck.py`` can verify it:

``Immutable``
    The attribute is bound once before the object is published (in
    ``__init__`` or a constructor-only helper) and never rebound.
    Bare reads are exempt from the discipline pass; ANY later write —
    even a lock-guarded one — is reported as ``immutable-write``.

``CopySwap``
    The attribute is atomically rebound to a fresh immutable value by
    writers holding the lock (the read-copy-update idiom: readers see
    the old or the new object, never a torn one).  Bare reads are
    exempt; writes outside the lock are still ``bare-write``.

Usage (annotation only — zero runtime behavior)::

    self.addr: Immutable = sock.getsockname()
    self.alloc: CopySwap = alloc      # rebound under _publish_lock

Subscripted forms (``Immutable[str]``) work too.  The classes are
deliberately inert: they exist so the annotation names something
importable and greppable.
"""
from __future__ import annotations

__all__ = ["Immutable", "CopySwap"]


class _Marker:
    """Annotation-only: subscriptable, never instantiated."""

    def __init__(self) -> None:
        raise TypeError(f"{type(self).__name__} is an annotation marker, "
                        "not a runtime type")

    def __class_getitem__(cls, item):
        return cls


class Immutable(_Marker):
    """Bound once pre-publication; reads need no lock, writes forbidden."""


class CopySwap(_Marker):
    """Atomically rebound under the lock; reads need no lock."""
