"""Boot-log gating + recent-log ring for the agent.

Capability parity with the reference's log plumbing:
- GatedHandler = helper/gated-writer/writer.go — writes are BUFFERED
  until the gate opens (the agent knows its final log level/sinks only
  after config parsing), then replayed once and passed through;
- LogWriter = command/agent/log_writer.go — a ring of recent formatted
  lines with attachable live sinks, serving "show me the agent log"
  monitors without re-reading files.

Installed by the CLI agent command (nomad_tpu/cli/main.py cmd_agent);
library embedders keep plain propagation.
"""
from __future__ import annotations

import logging
import sys
import threading
from collections import deque
from typing import Callable, Optional

from nomad_tpu.utils.sync import CopySwap

FORMAT = "%(asctime)s [%(levelname)s] %(name)s: %(message)s"


class LogWriter(logging.Handler):
    """Ring buffer of recent formatted lines + attachable live sinks."""

    def __init__(self, maxlen: int = 512) -> None:
        super().__init__()
        self.setFormatter(logging.Formatter(FORMAT))
        self._ring: deque = deque(maxlen=maxlen)
        self._total = 0  # monotonic count of lines ever appended
        self._sinks: list = []
        # Reentrant: a sink that logs through the same logger (error
        # paths) must not deadlock the pipeline.
        self._slock = threading.RLock()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:  # pragma: no cover - defensive
            return
        with self._slock:
            self._ring.append(line)
            self._total += 1
            sinks = list(self._sinks)
        for sink in sinks:
            try:
                sink(line)
            except Exception:  # pragma: no cover - bad sink
                pass

    def lines(self, n: int = 0) -> list:
        with self._slock:
            out = list(self._ring)
        return out[-n:] if n else out

    def lines_since(self, since: int) -> tuple[list, int]:
        """(lines appended after monotonic offset ``since``, current
        offset) — the follow-mode contract: clients resume from the
        returned offset and never re-see or miss a line (lines evicted
        past the ring's maxlen before being read are simply gone).

        ``since > total`` means the offset came from a PREVIOUS process
        (agent restarted, counter reset): the whole ring is returned —
        the restart backlog is exactly what a watching operator wants."""
        with self._slock:
            total = self._total
            ring = list(self._ring)
        if since > total:
            return ring, total
        avail = min(len(ring), total - since)
        return (ring[-avail:] if avail else []), total

    def monitor(self, sink: Callable[[str], None]) -> Callable[[], None]:
        """Attach a live sink; returns an unsubscribe callable.  The
        recent ring is replayed into the sink first, so a monitor sees
        context before the live tail (reference log_writer.go logs +
        handlers).

        Replay-then-register happens under the (reentrant) lock so a
        concurrent emit cannot interleave a live line among backlog
        lines; the cost is that logging threads wait out the (bounded,
        <= maxlen lines) replay at attach time — sinks must be prompt
        and never block on remote I/O (buffer and drain elsewhere)."""
        with self._slock:
            # Snapshot: a reentrant sink that logs (the RLock admits it)
            # would otherwise mutate the deque mid-iteration.
            for line in list(self._ring):
                sink(line)
            self._sinks.append(sink)

        def unsubscribe() -> None:
            with self._slock:
                if sink in self._sinks:
                    self._sinks.remove(sink)
        return unsubscribe


class GatedHandler(logging.Handler):
    """Buffers records until ``open_gate``; then replays them through
    the final targets exactly once and passes live records through."""

    def __init__(self) -> None:
        super().__init__(level=logging.NOTSET)
        self._buffer: list = []
        # Rebound (a fresh list) under _glock by open_gate; bare reads
        # serve whichever complete target list was last published —
        # the copy-on-write-swap contract the annotation enforces.
        self._targets: CopySwap = []
        self._open = False
        self._glock = threading.Lock()

    @staticmethod
    def _dispatch(targets: list, record: logging.LogRecord) -> None:
        for t in targets:
            # Handler.handle() skips the per-handler level check (that
            # normally lives in Logger.callHandlers) — apply it here so
            # the configured level filters buffered AND live records.
            if record.levelno >= t.level:
                t.handle(record)

    def emit(self, record: logging.LogRecord) -> None:
        with self._glock:
            if not self._open:
                self._buffer.append(record)
                return
            targets = list(self._targets)
        self._dispatch(targets, record)

    def open_gate(self, targets: list) -> None:
        """Drain-then-open: buffered records are dispatched BEFORE the
        gate flips, iterating until the buffer is empty under the lock,
        so live records emitted by already-running threads during the
        replay still queue behind the backlog — output stays in
        chronological order."""
        with self._glock:
            self._targets = list(targets)
        while True:
            with self._glock:
                buffered, self._buffer = self._buffer, []
                if not buffered:
                    self._open = True
                    return
            for record in buffered:
                self._dispatch(self._targets, record)


class BootLogGate:
    """The CLI agent's logging pipeline: install before config parsing,
    open after the agent knows its level/sinks."""

    def __init__(self, logger_name: str = "nomad_tpu",
                 stream=None) -> None:
        self.logger = logging.getLogger(logger_name)
        self.gate = GatedHandler()
        self.log_writer = LogWriter()
        self._stream = stream
        # Capture everything during boot; the final level filters at
        # gate-open (we don't know the configured level yet).
        self._prior_level = self.logger.level
        self._prior_propagate = self.logger.propagate
        self.logger.setLevel(logging.DEBUG)
        self.logger.propagate = False
        self.logger.addHandler(self.gate)

    def open(self, level: str = "INFO") -> None:
        """Attach the real stderr handler + the recent-log ring at the
        configured level and replay buffered boot records once."""
        numeric = getattr(logging, str(level).upper(), None)
        if not isinstance(numeric, int):
            numeric = logging.INFO
        stderr_handler = logging.StreamHandler(self._stream or sys.stderr)
        stderr_handler.setFormatter(logging.Formatter(FORMAT))
        stderr_handler.setLevel(numeric)
        # The ring stays UNLEVELED: /v1/agent/monitor can serve DEBUG
        # backlog even when stderr filters at INFO (the logger is held
        # at DEBUG for exactly this; the extra record construction on
        # debug sites is the price of always-available monitor detail).
        self.gate.open_gate([stderr_handler, self.log_writer])

    def set_level(self, level: str) -> None:
        """Re-filter the open pipeline (SIGHUP log_level reload).  Only
        the stderr handler moves; the ring keeps capturing everything."""
        numeric = getattr(logging, str(level).upper(), None)
        if not isinstance(numeric, int):
            return
        for target in self.gate._targets:
            if target is not self.log_writer:
                target.setLevel(numeric)

    def remove(self) -> None:
        """Detach (tests / embedder cleanup)."""
        self.logger.removeHandler(self.gate)
        self.logger.setLevel(self._prior_level)
        self.logger.propagate = self._prior_propagate
