"""Telemetry: counters, gauges and timers with pluggable sinks.

Capability parity with armon/go-metrics as the reference uses it
(MeasureSince on every RPC/FSM/worker/plan step, SetGauge for queue depths,
in-memory sink dumpable on demand, optional statsd/statsite UDP fanout —
reference command/agent/command.go:487-533).
"""
from __future__ import annotations

import socket
import threading
import time
from collections import defaultdict
from typing import Optional


class InmemSink:
    """Aggregating in-memory sink with interval-windowed samples.

    Counters and gauges are cumulative/last-write (go-metrics
    semantics).  Samples are bucketed into ``interval``-second windows
    and only the newest ``retain`` windows feed the percentile summary:
    a latency spike from an hour ago must age OUT of the reported p99
    (the forever-cumulative version served stale percentiles for the
    life of the process).  Per-window sample count is bounded
    (``max_per_interval``, newest kept) so a storm cannot grow the sink
    without bound.  ``clock`` is injectable for deterministic tests."""

    def __init__(self, interval: float = 10.0, retain: int = 6,
                 max_per_interval: int = 4096,
                 clock=time.monotonic) -> None:
        if interval <= 0 or retain < 1:
            raise ValueError("want interval > 0 and retain >= 1")
        self.interval = interval
        self.retain = retain
        self.max_per_interval = max_per_interval
        self._clock = clock
        self._lock = threading.Lock()
        self.counters: dict = defaultdict(float)
        self.gauges: dict = {}
        # key -> [[interval_index, [values...]], ...] newest last.
        self.samples: dict = defaultdict(list)

    def incr_counter(self, key: str, value: float) -> None:
        with self._lock:
            self.counters[key] += value

    def set_gauge(self, key: str, value: float) -> None:
        with self._lock:
            self.gauges[key] = value

    def _interval_index(self) -> int:
        return int(self._clock() / self.interval)

    def add_sample(self, key: str, value: float) -> None:
        now_idx = self._interval_index()
        with self._lock:
            windows = self.samples[key]
            if not windows or windows[-1][0] != now_idx:
                windows.append([now_idx, []])
                # Age out everything beyond the retained window count.
                if len(windows) > self.retain:
                    del windows[: len(windows) - self.retain]
            bucket = windows[-1][1]
            bucket.append(value)
            if len(bucket) > self.max_per_interval:
                del bucket[: len(bucket) - self.max_per_interval]

    def snapshot(self) -> dict:
        now_idx = self._interval_index()
        with self._lock:
            oldest_live = now_idx - self.retain + 1
            out = {"counters": dict(self.counters),
                   "gauges": dict(self.gauges), "samples": {}}
            for key, windows in self.samples.items():
                values: list = []
                for idx, bucket in windows:
                    # Windows are pruned on WRITE; a key nobody has
                    # sampled recently still ages out on read.
                    if idx >= oldest_live:
                        values.extend(bucket)
                if not values:
                    continue
                ordered = sorted(values)
                out["samples"][key] = {
                    "count": len(values),
                    "mean": sum(values) / len(values),
                    "max": ordered[-1],
                    "p99": ordered[min(len(ordered) - 1,
                                       int(len(ordered) * 0.99))],
                }
            return out


class StatsdSink:
    """Fire-and-forget statsd UDP fanout."""

    def __init__(self, address: tuple) -> None:
        self.address = tuple(address)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def _send(self, payload: str) -> None:
        try:
            self.sock.sendto(payload.encode(), self.address)
        except OSError:
            pass

    def incr_counter(self, key: str, value: float) -> None:
        self._send(f"{key}:{value}|c")

    def set_gauge(self, key: str, value: float) -> None:
        self._send(f"{key}:{value}|g")

    def add_sample(self, key: str, value: float) -> None:
        self._send(f"{key}:{value * 1000:.3f}|ms")


class Metrics:
    def __init__(self) -> None:
        self.sinks: list = [InmemSink()]

    @property
    def inmem(self) -> InmemSink:
        return self.sinks[0]

    def add_statsd(self, host: str, port: int) -> None:
        self.sinks.append(StatsdSink((host, port)))

    def incr_counter(self, key: str, value: float = 1.0) -> None:
        for sink in self.sinks:
            sink.incr_counter(key, value)

    def set_gauge(self, key: str, value: float) -> None:
        for sink in self.sinks:
            sink.set_gauge(key, value)

    def measure_since(self, key: str, start: float) -> None:
        elapsed = time.perf_counter() - start
        for sink in self.sinks:
            sink.add_sample(key, elapsed)

    def timer(self, key: str) -> "_Timer":
        return _Timer(self, key)


class _Timer:
    def __init__(self, metrics: Metrics, key: str) -> None:
        self.metrics = metrics
        self.key = key

    def __enter__(self):
        self.start = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self.metrics.measure_since(self.key, self.start)


# Global registry, mirroring go-metrics' package-level default.
metrics = Metrics()
