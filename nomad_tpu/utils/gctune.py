"""Server-process GC tuning.

The scheduler hot path churns short-lived acyclic objects (Allocations,
AllocMetrics, Resources offers) at ~100k/sec under load, while the state
store keeps hundreds of thousands of long-lived objects alive.  Python's
default generational thresholds (700, 10, 10) then trigger frequent full
collections that scan the entire live store — measured 100-200 ms pauses
on a 10k-node fleet, halving eval throughput.

The standard server fix (as popularized by Instagram's gc.freeze work):
move boot-time state to the permanent generation so collections never
scan it, and raise the gen-0 threshold so collection frequency matches
the actual cycle rate (the domain objects are reference-acyclic; cycles
come only from incidental plumbing).  GC stays ENABLED — true cycles are
still reclaimed, just far less often.

Called from Server startup and from bench.py (applied to both the device
and sequential paths, so benchmarks stay honest).
"""
from __future__ import annotations

import contextlib
import gc
import threading

_pause_lock = threading.Lock()
_pause_depth = 0
_pause_was_enabled = False


@contextlib.contextmanager
def gc_pause():
    """Defer collections across a bounded scheduling burst.

    A fused batch creates ~5k tracked objects per eval; young-gen
    collections mid-burst promote every survivor (the plans stay
    referenced) and cost ~20% of storm throughput.  The burst is
    bounded, the domain objects are reference-acyclic, and collection
    resumes on exit — deferral, not leakage.

    Nest-safe AND thread-safe via a refcount: bursts overlap across
    batch-worker threads, and the old save/restore-per-caller scheme let
    one thread's exit re-enable gc in the middle of another thread's
    burst (and an interleaved save could restore the wrong state).  The
    outermost enter saves, the last exit restores."""
    global _pause_depth, _pause_was_enabled
    with _pause_lock:
        if _pause_depth == 0:
            _pause_was_enabled = gc.isenabled()
            gc.disable()
        _pause_depth += 1
    try:
        yield
    finally:
        with _pause_lock:
            _pause_depth -= 1
            if _pause_depth == 0 and _pause_was_enabled:
                gc.enable()


def tune_gc(gen0: int = 50_000, gen1: int = 50, gen2: int = 50,
            freeze: bool = True) -> None:
    """Raise collection thresholds and freeze current live objects into
    the permanent generation.  Idempotent; call again after building
    large long-lived structures to freeze them too."""
    if freeze:
        gc.collect()
        gc.freeze()
    gc.set_threshold(gen0, gen1, gen2)
