"""Unified retry/backoff policy.

Every retry loop in the system routes through here instead of hand-rolled
``time.sleep`` loops (which the seed had in four flavors: fixed-interval
forever, linear no-cap, fixed base with no jitter, and
swallow-the-final-failure).  One policy object gives each call path:

  - jittered exponential backoff (full jitter by default — N clients
    retrying the same dead leader must not stampede in lockstep);
  - a per-attempt timeout hint for the transport call;
  - an overall deadline, checked BEFORE sleeping (never burn the last
    second of budget asleep);
  - retryable-exception classification (transport errors retry;
    application errors surface immediately);
  - a shutdown event so retry sleeps never outlive their owner;
  - a metrics hook (`nomad.retry.<name>.retries` / `.gaveup`).

Two shapes:

``Backoff``
    the bare delay sequence, for open-ended supervision loops that
    never "give up" (worker dequeue, peer replication, retry-join) —
    ``next()`` grows the delay, ``reset()`` snaps back after success.

``RetryPolicy``
    a bounded call wrapper for request/response paths —
    ``policy.call(fn)`` retries ``fn`` until success, a non-retryable
    error, ``max_attempts``, the ``deadline``, or ``stop``.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from .metrics import metrics

# Transport-shaped failures: the request may not have been processed and
# trying again is meaningful.  TimeoutError covers both socket timeouts
# and blocking-wait expiries; OSError covers refused/reset/unreachable.
# (ConnectionError is an OSError subclass — listed for readability.)
DEFAULT_RETRYABLE = (ConnectionError, TimeoutError, OSError)

# Admission-control NACKs (server/overload.ErrOverloaded) carry this
# marker.  In-proc the exception is an OSError subclass (already
# retryable above); over the wire it arrives as an RPCError whose
# STRING carries the marker — ``is_overloaded`` classifies both, and
# ``transport_or_overload`` is the retryable predicate for clients that
# should ride a shedding server with jittered backoff.
OVERLOADED_MARKER = "overloaded:"


def is_overloaded(exc: BaseException) -> bool:
    """True when ``exc`` is (or wraps, via the RPC error string) an
    admission-control shed — retry later, with backoff."""
    return OVERLOADED_MARKER in str(exc)[:128]


def transport_or_overload(exc: BaseException) -> bool:
    """Retryable predicate: transport-shaped failures OR an explicit
    server shed (the ``ErrOverloaded`` NACK, in-proc or over the
    wire)."""
    return isinstance(exc, DEFAULT_RETRYABLE) or is_overloaded(exc)


class RetryAborted(RuntimeError):
    """The stop event fired while waiting to retry (owner shutdown)."""


class Backoff:
    """Jittered exponential delay sequence.

    Full jitter by default (``jitter=1.0`` draws uniformly from
    (0, delay]); ``jitter=0`` is deterministic.  Not thread-safe — one
    Backoff per supervising loop.
    """

    def __init__(self, base: float = 0.25, max_delay: float = 30.0,
                 multiplier: float = 2.0, jitter: float = 1.0,
                 rng: Optional[random.Random] = None) -> None:
        if base <= 0:
            raise ValueError(f"backoff base must be > 0, got {base!r}")
        self.base = base
        self.max_delay = max(base, max_delay)
        self.multiplier = multiplier
        self.jitter = min(max(jitter, 0.0), 1.0)
        self._rng = rng or random
        self._failures = 0

    @property
    def failures(self) -> int:
        return self._failures

    def next(self) -> float:
        """The delay to wait after one more failure (grows the state)."""
        exp = min(self._failures, 63)  # cap the exponent, not just the delay
        self._failures += 1
        delay = min(self.max_delay, self.base * (self.multiplier ** exp))
        if self.jitter:
            # Full-jitter family: uniform over the top `jitter` fraction,
            # never below (1-jitter)*delay so jitter=1 keeps a (0, d] draw
            # and jitter=0.25 keeps delays within 25% of nominal.
            delay = delay * (1.0 - self.jitter * self._rng.random())
        return max(delay, 1e-6)

    def reset(self) -> None:
        self._failures = 0

    def sleep(self, stop: Optional[threading.Event] = None) -> bool:
        """Wait ``next()`` seconds; returns True if ``stop`` fired
        first (callers exit their loop on True)."""
        delay = self.next()
        if stop is not None:
            return stop.wait(delay)
        time.sleep(delay)
        return False


class RetryPolicy:
    """Bounded retry wrapper for request/response calls.

    Stateless across calls (each ``call`` builds its own Backoff), so
    one module-level policy instance safely serves many threads.
    ``retryable`` is an exception tuple or a predicate; ``name`` keys
    the metrics counters.
    """

    def __init__(self, base: float = 0.25, max_delay: float = 30.0,
                 multiplier: float = 2.0, jitter: float = 1.0,
                 max_attempts: Optional[int] = None,
                 deadline: Optional[float] = None,
                 attempt_timeout: Optional[float] = None,
                 retryable=DEFAULT_RETRYABLE,
                 name: str = "") -> None:
        self.base = base
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.max_attempts = max_attempts
        self.deadline = deadline
        self.attempt_timeout = attempt_timeout
        self.retryable = retryable
        self.name = name or "anon"

    def is_retryable(self, exc: BaseException) -> bool:
        if callable(self.retryable) and not isinstance(self.retryable,
                                                       type):
            return bool(self.retryable(exc))
        return isinstance(exc, self.retryable)

    def per_attempt_timeout(self,
                            start: Optional[float] = None) -> Optional[float]:
        """The timeout one attempt should pass to its transport call:
        ``attempt_timeout`` clipped to the deadline's remaining budget
        (pass the ``time.monotonic()`` taken at loop entry)."""
        timeout = self.attempt_timeout
        if self.deadline is not None and start is not None:
            remaining = max(self.deadline - (time.monotonic() - start),
                            0.001)
            timeout = remaining if timeout is None \
                else min(timeout, remaining)
        return timeout

    def call(self, fn: Callable, *,
             stop: Optional[threading.Event] = None,
             on_retry: Optional[Callable] = None,
             rng: Optional[random.Random] = None):
        """Invoke ``fn()`` with retries.  On exhaustion (attempts or
        deadline) the LAST underlying exception is re-raised — callers
        keep their exception types; nothing is swallowed.  ``on_retry``
        (attempt#, exc, upcoming delay) fires before each sleep.

        When the policy carries an ``attempt_timeout`` or ``deadline``,
        ``fn`` is invoked as ``fn(timeout)`` with this attempt's budget
        (attempt_timeout clipped to the deadline's remainder) for the
        caller to hand to its transport call — the policy cannot
        interrupt an arbitrary callable itself, so a caller that
        ignores the argument gets between-attempt enforcement only."""
        backoff = Backoff(self.base, self.max_delay, self.multiplier,
                          self.jitter, rng=rng)
        start = time.monotonic()
        bounded = self.attempt_timeout is not None or \
            self.deadline is not None
        attempt = 0
        while True:
            attempt += 1
            try:
                if bounded:
                    return fn(self.per_attempt_timeout(start))
                return fn()
            except BaseException as e:
                if not self.is_retryable(e):
                    raise
                if self.max_attempts is not None and \
                        attempt >= self.max_attempts:
                    metrics.incr_counter(
                        f"nomad.retry.{self.name}.gaveup")
                    raise
                delay = backoff.next()
                if self.deadline is not None and \
                        time.monotonic() - start + delay > self.deadline:
                    metrics.incr_counter(
                        f"nomad.retry.{self.name}.gaveup")
                    raise
                metrics.incr_counter(f"nomad.retry.{self.name}.retries")
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                if stop is not None:
                    if stop.wait(delay):
                        raise RetryAborted(
                            f"retry of {self.name} aborted: owner "
                            "shutting down") from e
                else:
                    time.sleep(delay)
