"""Loader for the optional C++ extension (_nomad_native).

The extension accelerates the host scheduling plane's hot loops (dynamic
port assignment — see native/port_alloc.cpp).  The .so is never committed
(it is platform/ABI-specific): on first import we try to build it from
source with ``native/build.py``; pure-Python fallbacks keep everything
working when the toolchain is unavailable.
"""
from __future__ import annotations

import logging
import os
import subprocess
import sys

logger = logging.getLogger("nomad_tpu.utils.native")

_repo = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _try_build() -> None:
    repo = _repo
    script = os.path.join(repo, "native", "build.py")
    src = os.path.join(repo, "native", "port_alloc.cpp")
    marker = os.path.join(repo, "native", ".build_failed")
    if not os.path.exists(script):
        raise ImportError("no native source tree")
    # A failed build leaves a marker so every later interpreter start
    # doesn't re-pay the compile attempt; editing the source retries.
    if os.path.exists(marker) and \
            os.path.getmtime(marker) >= os.path.getmtime(src):
        raise ImportError("previous native build failed")
    try:
        # faultlint-ok(uninjectable-io): import-time toolchain probe;
        # any failure routes to the pure-Python fallback below.
        subprocess.run([sys.executable, script], check=True,
                       capture_output=True, timeout=120)
    except Exception as e:
        logger.warning("native extension build failed, using pure-Python "
                       "fallback: %s", e)
        try:
            with open(marker, "w") as fh:
                fh.write(str(e))
        except OSError:
            pass
        raise


# The ABI version this checkout's Python code expects; must match
# native/port_alloc.cpp's exported ABI_VERSION.  A same-name signature
# change is invisible to hasattr() probes, so a stale prebuilt .so would
# otherwise crash mid-eval.
EXPECTED_ABI = 6


def _stale(repo: str) -> bool:
    """Is the built .so older than its source?  Rebuild-before-import
    keeps an already-built checkout working across signature changes
    (the in-process module object cannot be reloaded once imported)."""
    import sysconfig
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    so = os.path.join(repo, f"_nomad_native{suffix}")
    src = os.path.join(repo, "native", "port_alloc.cpp")
    try:
        return os.path.getmtime(so) < os.path.getmtime(src)
    except OSError:
        return False  # missing .so: normal import-failure path rebuilds


try:
    if _stale(_repo):
        try:  # pragma: no cover - toolchainless host
            _try_build()
        except Exception:
            # Import whatever exists anyway: a comment-only source touch
            # leaves the on-disk .so ABI-compatible and the gate below
            # accepts it; a genuinely old ABI is rejected there.
            pass
    import _nomad_native as native  # type: ignore

    HAS_NATIVE = True
except ImportError:
    try:  # pragma: no cover - exercised on unbuilt checkouts
        _try_build()
        import _nomad_native as native  # type: ignore

        HAS_NATIVE = True
    except Exception:
        native = None
        HAS_NATIVE = False

if HAS_NATIVE and getattr(native, "ABI_VERSION", 0) != EXPECTED_ABI:
    # An already-imported C extension cannot be reloaded in-process:
    # rebuild now so the NEXT process start imports a matching build,
    # and run this process on the pure-Python fallbacks.
    try:  # pragma: no cover - stale prebuilt .so
        _try_build()
        _refreshed = "rebuilt for next start"
    except Exception as _e:
        _refreshed = f"rebuild failed ({_e}); next start will retry"
    logger.warning(
        "native extension ABI %s != expected %s (stale build); %s, "
        "using pure-Python fallbacks now",
        getattr(native, "ABI_VERSION", 0), EXPECTED_ABI, _refreshed)
    native = None
    HAS_NATIVE = False
