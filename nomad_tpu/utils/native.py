"""Loader for the optional C++ extension (_nomad_native).

The extension accelerates the host scheduling plane's hot loops (dynamic
port assignment — see native/port_alloc.cpp).  Pure-Python fallbacks keep
everything working when it hasn't been built; ``python native/build.py``
produces it.
"""
from __future__ import annotations

try:
    import _nomad_native as native  # type: ignore

    HAS_NATIVE = True
except ImportError:  # pragma: no cover - exercised on unbuilt checkouts
    native = None
    HAS_NATIVE = False
