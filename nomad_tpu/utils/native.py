"""Loader for the optional C++ extension (_nomad_native).

The extension accelerates the host scheduling plane's hot loops (dynamic
port assignment — see native/port_alloc.cpp).  The .so is never committed
(it is platform/ABI-specific): on first import we try to build it from
source with ``native/build.py``; pure-Python fallbacks keep everything
working when the toolchain is unavailable.
"""
from __future__ import annotations

import logging
import os
import subprocess
import sys

logger = logging.getLogger("nomad_tpu.utils.native")


def _try_build() -> None:
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    script = os.path.join(repo, "native", "build.py")
    src = os.path.join(repo, "native", "port_alloc.cpp")
    marker = os.path.join(repo, "native", ".build_failed")
    if not os.path.exists(script):
        raise ImportError("no native source tree")
    # A failed build leaves a marker so every later interpreter start
    # doesn't re-pay the compile attempt; editing the source retries.
    if os.path.exists(marker) and \
            os.path.getmtime(marker) >= os.path.getmtime(src):
        raise ImportError("previous native build failed")
    try:
        subprocess.run([sys.executable, script], check=True,
                       capture_output=True, timeout=120)
    except Exception as e:
        logger.warning("native extension build failed, using pure-Python "
                       "fallback: %s", e)
        try:
            with open(marker, "w") as fh:
                fh.write(str(e))
        except OSError:
            pass
        raise


try:
    import _nomad_native as native  # type: ignore

    HAS_NATIVE = True
except ImportError:
    try:  # pragma: no cover - exercised on unbuilt checkouts
        _try_build()
        import _nomad_native as native  # type: ignore

        HAS_NATIVE = True
    except Exception:
        native = None
        HAS_NATIVE = False
