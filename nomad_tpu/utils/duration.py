"""Duration strings: '30s' / '1m' / '500ms' / '2h' -> seconds.

Shared by the jobspec parser and the HTTP blocking-query layer (one
implementation so the accepted units cannot drift).
"""
from __future__ import annotations

import re

_RE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h)?")
_UNITS = {"ms": 0.001, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(value) -> float:
    if isinstance(value, (int, float)):
        return float(value)
    m = _RE.fullmatch(str(value).strip())
    if not m:
        raise ValueError(f"invalid duration {value!r}")
    return float(m.group(1)) * _UNITS[m.group(2) or "s"]
