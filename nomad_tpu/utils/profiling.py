"""Introspection: host thread stacks + device (XLA) profiler control.

Capability parity with the reference's pprof mount
(/root/reference/command/agent/http.go:115-120 — net/http/pprof under
``enableDebug``) re-thought for this runtime: the host side dumps live
Python thread stacks (the pprof-goroutine equivalent) and the device side
toggles ``jax.profiler`` traces around the scheduler's XLA dispatches
(SURVEY §5 "add JAX profiler/XLA dump hooks around the device dispatch").
"""
from __future__ import annotations

import sys
import threading
import traceback
from typing import Optional

_lock = threading.Lock()
_trace_dir: Optional[str] = None


def thread_stacks() -> dict:
    """Stacks of every live thread, keyed by thread name — the
    goroutine-dump analogue served at /v1/agent/pprof."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        name = names.get(ident, f"thread-{ident}")
        out[f"{name} ({ident})"] = [
            {"file": fs.filename, "line": fs.lineno, "func": fs.name,
             "code": (fs.line or "").strip()}
            for fs in traceback.extract_stack(frame)
        ]
    return out


def start_device_trace(log_dir: str) -> None:
    """Begin a jax.profiler trace capturing every XLA dispatch until
    stopped; the directory is TensorBoard/xprof-loadable."""
    global _trace_dir
    import jax

    with _lock:
        if _trace_dir is not None:
            raise RuntimeError(f"device trace already active in "
                               f"{_trace_dir!r}")
        jax.profiler.start_trace(log_dir)
        _trace_dir = log_dir


def stop_device_trace() -> str:
    global _trace_dir
    import jax

    with _lock:
        if _trace_dir is None:
            raise RuntimeError("no device trace active")
        jax.profiler.stop_trace()
        done, _trace_dir = _trace_dir, None
        return done


def active_trace_dir() -> Optional[str]:
    # Under _lock like every other _trace_dir access: a bare read could
    # observe a torn start/stop transition from another thread (and the
    # lockcheck gate rightly flags guarded attrs read unlocked).
    with _lock:
        return _trace_dir


class device_trace:
    """Context manager for one-shot traces (bench.py --xla-trace)."""

    def __init__(self, log_dir: Optional[str]) -> None:
        self.log_dir = log_dir

    def __enter__(self):
        if self.log_dir:
            start_device_trace(self.log_dir)
        return self

    def __exit__(self, *exc) -> bool:
        if self.log_dir:
            stop_device_trace()
        return False
