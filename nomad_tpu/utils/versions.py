"""Semantic-version parsing + constraint checking.

Capability parity with hashicorp/go-version as used by
/root/reference/scheduler/feasible.go:303-347 ("version" constraint operand).
Also provides the int64 encoding the TPU constraint compiler uses to make
version comparisons device-executable (nomad_tpu/models/constraints.py).
"""
from __future__ import annotations

import re
from typing import Optional

_VERSION_RE = re.compile(
    r"^v?(\d+(?:\.\d+)*)([-.]?(?:[a-zA-Z][0-9A-Za-z.-]*))?$")

# Segment width for the int64 packing: supports versions up to .99999 per
# segment, 3 segments.  Pre-release versions subtract 1 so "1.0.0-beta" <
# "1.0.0", matching semver ordering.
_SEG = 100000


def parse_version(s: str) -> Optional[tuple]:
    """Parse to ((major, minor, patch), prerelease) or None if invalid."""
    m = _VERSION_RE.match(s.strip())
    if not m:
        return None
    nums = [int(x) for x in m.group(1).split(".")][:3]
    while len(nums) < 3:
        nums.append(0)
    pre = (m.group(2) or "").lstrip("-.")
    return tuple(nums), pre


def encode_version(s: str) -> Optional[int]:
    """Pack a version into a comparable int64 (device-side representation)."""
    parsed = parse_version(s)
    if parsed is None:
        return None
    (major, minor, patch), pre = parsed
    if major >= _SEG or minor >= _SEG or patch >= _SEG:
        return None
    packed = (major * _SEG + minor) * _SEG + patch
    packed *= 2
    if pre:
        packed -= 1  # prerelease sorts just below the release
    return packed


def _sort_key(parsed: tuple) -> tuple:
    """Total order matching semver closely enough for constraints: release
    sorts above any prerelease of the same base; prereleases compare
    lexically."""
    nums, pre = parsed
    return (nums, 1, "") if not pre else (nums, 0, pre)


def _cmp(a: str, b: str) -> Optional[int]:
    pa, pb = parse_version(a), parse_version(b)
    if pa is None or pb is None:
        return None
    ka, kb = _sort_key(pa), _sort_key(pb)
    return (ka > kb) - (ka < kb)


_CONSTRAINT_RE = re.compile(r"^\s*(>=|<=|!=|~>|=|>|<)?\s*([\w.+-]+)\s*$")


def parse_constraint(spec: str) -> Optional[list]:
    """Parse "">= 1.0, < 1.4"" into [(op, version), ...].  Each rhs must
    itself parse as a version (go-version's NewConstraint rejects
    unparseable versions at parse time — without this, ">= banana"
    would validate clean and then silently never match any node)."""
    out = []
    for clause in spec.split(","):
        m = _CONSTRAINT_RE.match(clause)
        if not m:
            return None
        if parse_version(m.group(2)) is None:
            return None
        out.append((m.group(1) or "=", m.group(2)))
    return out


def check_constraint(version_str: str, spec: str) -> bool:
    """Does version_str satisfy the constraint set?  Invalid input -> False."""
    clauses = parse_constraint(spec)
    if clauses is None:
        return False
    for op, rhs in clauses:
        if op == "~>":
            # Pessimistic: >= rhs and < next increment of rhs's second-to-
            # last specified segment ("~> 1.2.3" -> >=1.2.3 <1.3.0).
            parsed = parse_version(rhs)
            if parsed is None:
                return False
            segs = rhs.split("-")[0].lstrip("v").split(".")
            try:
                nums = [int(x) for x in segs]
            except ValueError:
                return False  # e.g. "~> 1.2beta": not a valid pessimistic spec
            if len(nums) == 1:
                upper_nums = [nums[0] + 1]
            else:
                upper_nums = nums[:-2] + [nums[-2] + 1, 0]
            upper = ".".join(str(x) for x in upper_nums)
            c1, c2 = _cmp(version_str, rhs), _cmp(version_str, upper)
            if c1 is None or c2 is None or c1 < 0 or c2 >= 0:
                return False
            continue
        c = _cmp(version_str, rhs)
        if c is None:
            return False
        ok = {
            "=": c == 0,
            "!=": c != 0,
            ">": c > 0,
            ">=": c >= 0,
            "<": c < 0,
            "<=": c <= 0,
        }[op]
        if not ok:
            return False
    return True
