"""Canonical mock fixtures for tests and benchmarks.

Capability parity with /root/reference/nomad/mock/mock.go — same shapes and
resource magnitudes so scheduler behavior (fit, scores, anti-affinity) is
comparable against the reference's test expectations.
"""
from __future__ import annotations

from nomad_tpu.structs import (
    ALLOC_CLIENT_STATUS_PENDING,
    ALLOC_DESIRED_STATUS_RUN,
    EVAL_STATUS_PENDING,
    JOB_STATUS_PENDING,
    JOB_TYPE_SERVICE,
    JOB_TYPE_SYSTEM,
    NODE_STATUS_READY,
    Allocation,
    Constraint,
    Evaluation,
    Job,
    NetworkResource,
    Node,
    Plan,
    PlanResult,
    Resources,
    Task,
    TaskGroup,
    generate_uuid,
)


def node(idx: int | None = None) -> Node:
    """A ready linux node: 4000 MHz cpu, 8 GiB mem, 100 GiB disk, 1 Gbit."""
    octet = 100 if idx is None else (idx % 250) + 1
    return Node(
        id=generate_uuid(),
        datacenter="dc1",
        name="foobar" if idx is None else f"node-{idx}",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "version": "0.1.0",
            "driver.exec": "1",
        },
        resources=Resources(
            cpu=4000,
            memory_mb=8192,
            disk_mb=100 * 1024,
            iops=150,
            networks=[NetworkResource(
                device="eth0", cidr=f"192.168.0.{octet}/32", mbits=1000)],
        ),
        reserved=Resources(
            cpu=100,
            memory_mb=256,
            disk_mb=4 * 1024,
            networks=[NetworkResource(
                device="eth0", ip=f"192.168.0.{octet}",
                reserved_ports=[22], mbits=1)],
        ),
        links={"consul": "foobar.dc1"},
        meta={"pci-dss": "true"},
        node_class="linux-medium-pci",
        status=NODE_STATUS_READY,
    )


def node_slab(n: int) -> "NodeSlab":
    """A columnar n-row fleet of exactly the mock ``node`` shape
    (structs/node_slab.py): one template node + dense id/name/endpoint
    columns, no per-row Node/Resources/NetworkResource construction.
    Row r materializes bit-identical to ``node(r)`` (modulo the random
    uuid), which tests/test_node_slab.py pins."""
    from nomad_tpu.structs import NodeSlab, generate_uuids

    template = node(0)
    octets = [(i % 250) + 1 for i in range(n)]
    return NodeSlab(
        ids=generate_uuids(n),
        names=[f"node-{i}" for i in range(n)],
        datacenters="dc1",
        template=template,
        cidrs=[f"192.168.0.{o}/32" for o in octets],
        ips=[f"192.168.0.{o}" for o in octets],
    )


def job() -> Job:
    return Job(
        region="global",
        id=generate_uuid(),
        name="my-job",
        type=JOB_TYPE_SERVICE,
        priority=50,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[Constraint(
            hard=True, l_target="$attr.kernel.name",
            r_target="linux", operand="=")],
        task_groups=[TaskGroup(
            name="web",
            count=10,
            tasks=[Task(
                name="web",
                driver="exec",
                config={"command": "/bin/date", "args": "+%s"},
                resources=Resources(
                    cpu=500,
                    memory_mb=256,
                    networks=[NetworkResource(
                        mbits=50, dynamic_ports=["http"])],
                ),
            )],
            meta={"elb_check_type": "http"},
        )],
        meta={"owner": "armon"},
        status=JOB_STATUS_PENDING,
        create_index=42,
        modify_index=99,
    )


def system_job() -> Job:
    j = job()
    j.type = JOB_TYPE_SYSTEM
    j.priority = 100
    j.task_groups[0].count = 1
    j.task_groups[0].meta = {}
    return j


def eval() -> Evaluation:  # noqa: A001 - mirrors reference fixture name
    return Evaluation(
        id=generate_uuid(),
        priority=50,
        type=JOB_TYPE_SERVICE,
        job_id=generate_uuid(),
        status=EVAL_STATUS_PENDING,
    )


def alloc() -> Allocation:
    j = job()
    a = Allocation(
        id=generate_uuid(),
        eval_id=generate_uuid(),
        node_id="foo",
        task_group="web",
        resources=Resources(
            cpu=500,
            memory_mb=256,
            networks=[NetworkResource(
                device="eth0", ip="192.168.0.100",
                reserved_ports=[12345], mbits=100,
                dynamic_ports=["http"])],
        ),
        task_resources={
            "web": Resources(
                cpu=500,
                memory_mb=256,
                networks=[NetworkResource(
                    device="eth0", ip="192.168.0.100",
                    reserved_ports=[5000], mbits=50,
                    dynamic_ports=["http"])],
            ),
        },
        job=j,
        job_id=j.id,
        desired_status=ALLOC_DESIRED_STATUS_RUN,
        client_status=ALLOC_CLIENT_STATUS_PENDING,
    )
    return a


def plan() -> Plan:
    return Plan(priority=50)


def plan_result() -> PlanResult:
    return PlanResult()
