"""Runtime sanitizers: cross-check the static analysis with real runs.

Static analysis sees possible orders; these see ACTUAL ones.

**LockOrderWitness** — `threading.Lock/RLock/Condition` constructors are
wrapped so every lock created from package code carries its creation
*site* (file:line).  Each thread keeps a held-site stack; acquiring B
while holding A records the edge A→B.  A cycle in the observed graph
means two real code paths took the same pair of lock classes in
opposite orders — the textbook deadlock precondition, caught even when
the test run never actually deadlocks (exactly what `-race`-style
sanitizers are for).  Edges are keyed by site, not instance; same-site
nesting of distinct instances is collected separately (``self_edges``,
advisory — hierarchical same-class locking is often legitimate).

**RecompileSentinel** — snapshots the jit caches of the package's
registered kernels and fails any test session that retraces a kernel
past its budget.  Unbounded retracing is the silent performance failure
mode of the device path: every new (shape, static-arg) combination
costs a full XLA compile, and a kernel whose shapes aren't properly
bucketed erodes the bench headline without failing a single behavioral
test.

**TransferGuardSanitizer** — wraps the scheduler's device-dispatch
seams in ``jax.transfer_guard_host_to_device("disallow")`` scopes: any
IMPLICIT host->device transfer on a dispatch path (a host array or
scalar silently committed by jit) raises inside the test that caused
it.  This is the runtime twin of devlint's transfer-discipline pass:
the discipline says every intended transfer is explicit (`device_put`
through the counted seams — devices.put_counted / mesh._put /
ShardedResidency), so the guard can reject everything implicit without
false positives.  Direct kernel calls outside the scheduler seams
(parity tests feeding host arrays on purpose) are unaffected.

**BudgetWitnessSanitizer** — the runtime twin of faultlint's deadline
pass.  While a thread is inside an admitted RPC body
(``Endpoints._admitted_body``, heartbeat/liveness lane excluded), the
blocking primitives (``Event.wait`` / ``Condition.wait`` /
``Queue.get``) are wrapped to record any wait entered with NO timeout:
a ``timeout=None`` that the static pass can't see (a variable that
evaluates to None at runtime, a default leaking through a helper)
is caught on the actual serving thread, with the wait's stack, and
fails the test that caused it at its teardown.  Observe-only: the
wait still runs; cross-thread handoffs (a serving thread parking work
for an applier thread) are out of scope — faultlint's loop-surface
entries cover those statically.

All are opt-in via install()/uninstall() and wired into the test suite
by tests/test_static_analysis.py (and conftest, env-gated) — see
README "Static analysis & sanitizers".
"""
from __future__ import annotations

import os
import sys
import threading
from typing import Optional

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_condition = threading.Condition


class _WrappedLock:
    """Order-tracking proxy around a real Lock/RLock."""

    __slots__ = ("_inner", "_site", "_witness")

    def __init__(self, inner, site: str, witness: "LockOrderWitness"):
        self._inner = inner
        self._site = site
        self._witness = witness

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._witness._on_acquire(self._site, id(self))
        return got

    def release(self) -> None:
        self._inner.release()
        self._witness._on_release(self._site, id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # Condition(wrapped_lock) support: Condition feature-detects
    # _release_save/_acquire_restore/_is_owned via attribute existence,
    # so these must exist exactly when the INNER lock has them (RLock
    # yes, Lock no) — hence __getattr__, not plain methods.  The
    # save/restore round-trip stays order-tracked.
    def __getattr__(self, name: str):
        if name == "_release_save":
            inner_fn = self._inner._release_save
            witness, site, me = self._witness, self._site, id(self)

            def _release_save():
                state = inner_fn()
                witness._on_release(site, me)
                return state
            return _release_save
        if name == "_acquire_restore":
            inner_fn = self._inner._acquire_restore
            witness, site, me = self._witness, self._site, id(self)

            def _acquire_restore(state):
                inner_fn(state)
                witness._on_acquire(site, me)
            return _acquire_restore
        if name in ("_is_owned", "_at_fork_reinit"):
            return getattr(self._inner, name)
        raise AttributeError(name)


class LockOrderWitness:
    """Records real lock-acquisition chains; reports order cycles."""

    def __init__(self, package_prefix: Optional[str] = None) -> None:
        if package_prefix is None:
            package_prefix = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
        self.package_prefix = os.path.abspath(package_prefix)
        self._tls = threading.local()
        self._graph_lock = _real_lock()
        self.edges: dict = {}     # (site_a, site_b) -> count
        self.self_edges: set = set()  # same-site, distinct-instance nests
        self.sites: set = set()
        self._installed = False
        self._saved: Optional[tuple] = None

    # -- recording ---------------------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _on_acquire(self, site: str, lock_id: int) -> None:
        stack = self._stack()
        if stack:
            top_site, top_id = stack[-1]
            if top_site != site:
                edge = (top_site, site)
                with self._graph_lock:
                    self.edges[edge] = self.edges.get(edge, 0) + 1
            elif top_id != lock_id:
                # Two INSTANCES of one lock class nested: advisory only
                # (hierarchical same-class locks are legitimate), kept
                # for inspection alongside the static
                # nested-self-acquire rule.
                with self._graph_lock:
                    self.self_edges.add(site)
        stack.append((site, lock_id))

    def _on_release(self, site: str, lock_id: int) -> None:
        stack = self._stack()
        # Locks are not always released LIFO: drop the innermost match.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == (site, lock_id):
                del stack[i]
                return

    def _site_of_caller(self) -> Optional[str]:
        frame = sys._getframe(2)
        fname = frame.f_code.co_filename
        if not os.path.abspath(fname).startswith(self.package_prefix):
            return None
        rel = os.path.relpath(os.path.abspath(fname),
                              os.path.dirname(self.package_prefix))
        return f"{rel}:{frame.f_lineno}"

    # -- install / uninstall ----------------------------------------------
    def install(self) -> "LockOrderWitness":
        """Patch the threading lock constructors; only locks created
        from files under ``package_prefix`` are wrapped."""
        if self._installed:
            return self
        # Save whatever is installed NOW (possibly another witness's
        # factories) so nested install/uninstall pairs restore correctly.
        self._saved = (threading.Lock, threading.RLock,
                       threading.Condition)
        witness = self

        def _wrap(inner, site):
            if site is None:
                return inner
            witness.sites.add(site)
            return _WrappedLock(inner, site, witness)

        def make_lock():
            return _wrap(_real_lock(), witness._site_of_caller())

        def make_rlock():
            return _wrap(_real_rlock(), witness._site_of_caller())

        def make_condition(lock=None):
            # A Condition over an (already wrapped) lock tracks through
            # the wrapper; a bare Condition() gets its own wrapped RLock
            # when created from package code (site = the Condition()
            # call, resolved HERE — one frame up would blame this file).
            if lock is None:
                lock = _wrap(_real_rlock(), witness._site_of_caller())
            return _real_condition(lock)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        threading.Condition = make_condition
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock, threading.RLock, threading.Condition = self._saved
        self._installed = False
        self._saved = None

    def __enter__(self) -> "LockOrderWitness":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- reporting ---------------------------------------------------------
    def find_cycles(self) -> list:
        """Elementary cycles in the observed order graph (site level)."""
        from .lockcheck import find_cycles

        graph: dict = {}
        with self._graph_lock:
            for (a, b) in self.edges:
                graph.setdefault(a, set()).add(b)
        return find_cycles(graph)

    def check(self) -> None:
        """Raise AssertionError when an order cycle was observed."""
        cycles = self.find_cycles()
        if cycles:
            lines = [" -> ".join(c + (c[0],)) for c in cycles]
            raise AssertionError(
                "lock-order cycles observed at runtime:\n  " +
                "\n  ".join(lines))


# ---------------------------------------------------------------------------
# Recompile sentinel
# ---------------------------------------------------------------------------

# Kernels the sentinel watches: (import path, attribute).  Each entry is
# the *wrapped* jit object whose cache growth is budgeted.
KERNEL_REGISTRY = (
    ("nomad_tpu.ops.binpack", "place_sequence"),
    ("nomad_tpu.ops.binpack", "place_rounds"),
    ("nomad_tpu.ops.binpack", "place_rounds_batch"),
    ("nomad_tpu.ops.binpack", "place_sequence_batch"),
    ("nomad_tpu.parallel.mesh", "_window_verify_jit"),
)

# One kernel serves many (fleet size, placement bucket, static-arg)
# shapes per suite; buckets are powers of two so a healthy run stays far
# under this.  A kernel whose inputs stop hitting the buckets shows up
# as hundreds of entries, not tens.
DEFAULT_BUDGET = 24


def _cache_size(jitted) -> Optional[int]:
    for attr in ("_cache_size",):
        fn = getattr(jitted, attr, None)
        if callable(fn):
            try:
                return int(fn())
            except Exception:
                return None
    return None


# ---------------------------------------------------------------------------
# Transfer-guard sanitizer
# ---------------------------------------------------------------------------

# The dispatch seams the guard wraps: every scheduler-driven device
# dispatch flows through one of these.  (import path, class-or-None,
# attribute.)  Direct kernel calls — the parity suites deliberately
# feeding host arrays to ops.binpack — are NOT wrapped: the discipline
# is a property of the scheduler seams, not of the kernels.
TRANSFER_SEAMS = (
    ("nomad_tpu.scheduler.jax_binpack", "JaxBinPackScheduler",
     "dispatch_device"),
    ("nomad_tpu.scheduler.batch", "BatchEvalRunner", "_process"),
    ("nomad_tpu.models.fleet", "UsageMirror", "_update_device"),
    ("nomad_tpu.parallel.mesh", None, "place_sequence_sharded"),
    ("nomad_tpu.parallel.mesh", None, "place_rounds_sharded"),
    ("nomad_tpu.parallel.mesh", None, "place_rounds_batch_sharded"),
    ("nomad_tpu.parallel.mesh", None, "place_sequence_batch_sharded"),
    ("nomad_tpu.parallel.mesh", None, "window_verify_sharded"),
    ("nomad_tpu.ops.plan_conflict", None, "_dispatch_window_fit"),
)


class TransferGuardSanitizer:
    """Rejects IMPLICIT host->device transfers on the dispatch seams.

    Explicit transfers (jax.device_put through the counted seams) pass;
    a host value reaching jit commitment inside a wrapped seam raises
    XlaRuntimeError in the offending test.  The d2h direction is not
    guarded (the CPU test backend's zero-copy fetches never trip it);
    devlint's static concretize pass owns that side.
    """

    def __init__(self, seams=TRANSFER_SEAMS) -> None:
        self.seams = seams
        self._saved: list = []
        self._installed = False

    def install(self) -> "TransferGuardSanitizer":
        if self._installed:
            return self
        import importlib

        import jax

        def wrap(fn):
            def guarded(*args, **kwargs):
                with jax.transfer_guard_host_to_device("disallow"):
                    return fn(*args, **kwargs)
            guarded.__name__ = fn.__name__
            guarded.__qualname__ = getattr(fn, "__qualname__",
                                           fn.__name__)
            guarded.__wrapped__ = fn
            return guarded

        for mod_path, cls_name, attr in self.seams:
            try:
                mod = importlib.import_module(mod_path)
            except Exception:
                continue
            holder = getattr(mod, cls_name) if cls_name else mod
            fn = getattr(holder, attr, None)
            if fn is None:
                continue
            self._saved.append((holder, attr, fn))
            setattr(holder, attr, wrap(fn))
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for holder, attr, fn in self._saved:
            setattr(holder, attr, fn)
        self._saved = []
        self._installed = False

    def __enter__(self) -> "TransferGuardSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class RecompileSentinel:
    """Budgets jit-cache growth for the registered kernels."""

    def __init__(self, budget: int = DEFAULT_BUDGET,
                 extra: Optional[dict] = None) -> None:
        self.budget = budget
        self.extra = dict(extra or {})   # name -> jitted object
        self._baseline: dict = {}
        self.supported = True

    def _kernels(self) -> dict:
        import importlib

        out: dict = {}
        for mod_path, attr in KERNEL_REGISTRY:
            try:
                mod = importlib.import_module(mod_path)
            except Exception:
                continue
            fn = getattr(mod, attr, None)
            if fn is not None:
                out[f"{mod_path}.{attr}"] = fn
        out.update(self.extra)
        return out

    def install(self) -> "RecompileSentinel":
        sizes = {}
        for name, fn in self._kernels().items():
            size = _cache_size(fn)
            if size is None:
                self.supported = False
                continue
            sizes[name] = size
        self._baseline = sizes
        return self

    def report(self) -> dict:
        """name -> traces since install (only kernels with a baseline)."""
        out = {}
        for name, fn in self._kernels().items():
            if name not in self._baseline:
                continue
            size = _cache_size(fn)
            if size is not None:
                out[name] = size - self._baseline[name]
        return out

    def check(self) -> None:
        """Raise AssertionError when any kernel exceeded its budget."""
        over = {name: n for name, n in self.report().items()
                if n > self.budget}
        if over:
            detail = ", ".join(f"{k}: {v} traces (budget {self.budget})"
                               for k, v in sorted(over.items()))
            raise AssertionError(
                f"jit recompile budget exceeded — {detail}; either a "
                "shape stopped hitting its power-of-two bucket or a new "
                "call site passes unbucketed shapes (see "
                "nomad_tpu/ops/binpack.py docstring)")


class ReplicaDivergenceSanitizer:
    """Shadow-replica twin: the runtime proof of apply determinism.

    While installed, every ``NomadFSM`` constructed carries a hidden
    in-proc twin (no broker, no hooks, no trace spans).  Each raft
    entry the primary applies is re-applied to the twin, and
    ``store.fingerprint()`` is byte-compared at commit quiescence
    points — the first few applies (including the first applies after a
    ``restore``, which resets the count; restore itself compares lazily
    so fingerprinting doesn't materialize freshly restored columnar
    slabs), every ``interval`` thereafter, and at each test's teardown
    (``compare_all`` via conftest).  Any nondeterminism the static
    consensuslint pass can't
    see (a hash-order walk that escaped the AST patterns, a
    time-dependent value smuggled through a helper) diverges the twin
    and fails the test that caused it.

    Tests that seed state by writing the primary's store DIRECTLY
    (bypassing the raft log) would falsely diverge the twin, so each
    store counts its write-method commits (``_bump``) while the
    sanitizer is installed: a primary/twin commit-count mismatch means
    out-of-band writes, and that FSM's pair is dropped from comparison
    (counted in ``desynced``, not silent) instead of reported.

    Divergence raises inside the offending apply AND is recorded for
    ``check()`` at session teardown — a raise swallowed by a raft
    apply loop still fails the session.
    """

    def __init__(self, interval: int = 64) -> None:
        self.interval = interval
        self.mismatches: list = []
        self.desynced = 0
        self.compared = 0
        self._installed = False
        self._saved: list = []
        self._fsms: list = []     # weakrefs of primaries
        self._reg_lock = threading.Lock()
        self._tls = threading.local()

    # -- install/uninstall --------------------------------------------------
    def install(self) -> "ReplicaDivergenceSanitizer":
        if self._installed:
            return self
        import weakref

        from nomad_tpu.server.fsm import NomadFSM
        from nomad_tpu.state.store import StateStore

        san = self
        orig_init = NomadFSM.__init__
        orig_apply = NomadFSM.apply
        orig_restore = NomadFSM.restore
        orig_bump = StateStore._bump
        self._saved = [(NomadFSM, "__init__", orig_init),
                       (NomadFSM, "apply", orig_apply),
                       (NomadFSM, "restore", orig_restore),
                       (StateStore, "_bump", orig_bump)]

        def counted_bump(store, table, index):
            store._sanitizer_bumps = \
                getattr(store, "_sanitizer_bumps", 0) + 1
            return orig_bump(store, table, index)

        def init(fsm, *args, **kwargs):
            orig_init(fsm, *args, **kwargs)
            if getattr(san._tls, "constructing", False):
                return          # this IS a twin being built
            san._tls.constructing = True
            try:
                twin = NomadFSM()
            finally:
                san._tls.constructing = False
            # Shadow the span recorder on the twin: the obs plane's
            # exactly-once apply-span accounting must see each entry
            # once, not once per replica.
            twin._record_apply_spans = _noop_spans
            fsm._divergence_twin = twin
            fsm._divergence_lock = _real_lock()
            fsm._divergence_applied = 0
            with san._reg_lock:
                san._fsms.append(weakref.ref(fsm))

        def apply(fsm, index, entry):
            twin = getattr(fsm, "_divergence_twin", None)
            if twin is None:
                return orig_apply(fsm, index, entry)
            with fsm._divergence_lock:
                try:
                    result = orig_apply(fsm, index, entry)
                except BaseException:
                    # A deterministic rejection must hit the twin too,
                    # or the next compare reports a skew that isn't
                    # nondeterminism.
                    try:
                        orig_apply(twin, index, entry)
                    except BaseException:
                        pass
                    raise
                try:
                    orig_apply(twin, index, entry)
                except BaseException as e:
                    san._report(
                        fsm, index,
                        f"shadow twin raised {e!r} on an entry the "
                        f"primary applied cleanly")
                fsm._divergence_applied += 1
                n = fsm._divergence_applied
                if n <= 4 or n % san.interval == 0:
                    san._compare(fsm, twin, index)
                return result

        def restore(fsm, blob):
            twin = getattr(fsm, "_divergence_twin", None)
            if twin is None:
                return orig_restore(fsm, blob)
            with fsm._divergence_lock:
                result = orig_restore(fsm, blob)
                orig_restore(twin, blob)
                fsm._divergence_applied = 0
                # No eager compare here: fingerprint() would materialize
                # the freshly restored columnar slabs, destroying the
                # lazy-restore property tests assert on.  The first
                # post-restore applies and the per-test teardown sweep
                # compare the restored pair instead.
                return result

        NomadFSM.__init__ = init
        NomadFSM.apply = apply
        NomadFSM.restore = restore
        StateStore._bump = counted_bump
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for holder, attr, fn in self._saved:
            setattr(holder, attr, fn)
        self._saved = []
        self._installed = False

    def __enter__(self) -> "ReplicaDivergenceSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- comparison ---------------------------------------------------------
    def _compare(self, fsm, twin, index: int) -> None:
        p_bumps = getattr(fsm.state, "_sanitizer_bumps", 0)
        t_bumps = getattr(twin.state, "_sanitizer_bumps", 0)
        if p_bumps != t_bumps:
            # Out-of-band direct store writes (test seeding): this
            # pair can never agree again; drop it, visibly.
            fsm._divergence_twin = None
            self.desynced += 1
            return
        self.compared += 1
        a = fsm.state.fingerprint()
        b = twin.state.fingerprint()
        if a != b:
            self._report(
                fsm, index,
                f"primary fingerprint {a[:16]}… != shadow twin "
                f"{b[:16]}… after identical entries")

    def _report(self, fsm, index: int, detail: str) -> None:
        # One report per pair: a diverged twin stays diverged, so drop
        # it rather than re-reporting at every later quiescence point.
        fsm._divergence_twin = None
        where = "restore" if index < 0 else f"index {index}"
        msg = (f"replica divergence at {where}: {detail} — the apply "
               f"path consumed a nondeterministic input (wall clock, "
               f"RNG, host env, or hash-order); see "
               f"analysis/consensuslint.py rules")
        self.mismatches.append(msg)
        raise AssertionError(msg)

    def compare_all(self) -> None:
        """Quiescence-point sweep (per-test teardown): fingerprint every
        live pair; raises on the first divergence found."""
        if not self._installed:
            return
        with self._reg_lock:
            refs = list(self._fsms)
            self._fsms = [r for r in refs if r() is not None]
        for ref in refs:
            fsm = ref()
            if fsm is None:
                continue
            twin = getattr(fsm, "_divergence_twin", None)
            if twin is None:
                continue
            with fsm._divergence_lock:
                self._compare(fsm, twin, index=fsm._divergence_applied)

    def check(self) -> None:
        """Session-teardown catch-all: any recorded divergence — even
        one whose in-apply raise was swallowed by a raft loop — fails
        the session."""
        if self.mismatches:
            raise AssertionError(
                "replica divergence observed during the session:\n" +
                "\n".join(f"  - {m}" for m in self.mismatches))


def _noop_spans(*args, **kwargs) -> None:
    return None


# ---------------------------------------------------------------------------
# Budget witness
# ---------------------------------------------------------------------------

class BudgetWitnessSanitizer:
    """Records unbounded waits taken on a thread serving an admitted RPC.

    The deadline discipline (server/overload.py) says every wait on a
    request path consumes the admitted envelope's budget.  faultlint
    proves the *syntactic* form; this witness proves the runtime one: a
    ``timeout=None`` hiding behind a variable or a default argument is
    invisible to the AST but lands here, on the actual serving thread,
    with the wait's call stack.

    Waits are recorded, never blocked — the per-test ``check_test()``
    (conftest ``budget_quiescence``) fails the offending test and
    resets; session ``check()`` is the catch-all for hits recorded
    outside any test body.  The heartbeat/liveness lane is exempt, same
    as the static pass.
    """

    def __init__(self, package_prefix: Optional[str] = None) -> None:
        if package_prefix is None:
            package_prefix = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))
        self.package_prefix = os.path.abspath(package_prefix)
        self.hits: list = []        # (method, primitive, test, stack)
        self._tls = threading.local()
        self._hits_lock = _real_lock()
        self._installed = False
        self._saved: list = []

    # -- install/uninstall --------------------------------------------------
    def install(self) -> "BudgetWitnessSanitizer":
        if self._installed:
            return self
        import queue

        from nomad_tpu.server.endpoints import Endpoints
        from nomad_tpu.server.overload import HEARTBEAT_LANE

        san = self
        orig_body = Endpoints._admitted_body
        # Patch the REAL primitive classes saved at import time:
        # LockOrderWitness rebinds the threading.Condition *name* to a
        # factory, but its instances are still _real_condition objects,
        # so the method patch covers both installation orders.
        orig_event_wait = threading.Event.wait
        orig_cond_wait = _real_condition.wait
        orig_get = queue.Queue.get
        self._saved = [(Endpoints, "_admitted_body", orig_body),
                       (threading.Event, "wait", orig_event_wait),
                       (_real_condition, "wait", orig_cond_wait),
                       (queue.Queue, "get", orig_get)]

        def admitted_body(ep, method, handler, args):
            if method in HEARTBEAT_LANE or "heartbeat" in method.lower():
                return orig_body(ep, method, handler, args)
            prev = getattr(san._tls, "serving", None)
            san._tls.serving = method
            try:
                return orig_body(ep, method, handler, args)
            finally:
                san._tls.serving = prev

        def record(primitive: str) -> None:
            method = getattr(san._tls, "serving", None)
            if method is None:
                return
            # Only PACKAGE wait sites count — stdlib-internal waits
            # (Thread.start's _started handshake, Queue.get's internal
            # Condition) are not budget holders; this is the same
            # domain restriction the static pass has.
            caller = sys._getframe(2).f_code.co_filename
            if not os.path.abspath(caller).startswith(
                    san.package_prefix):
                return
            import traceback

            # Drop the two witness frames; keep the caller's chain.
            stack = "".join(traceback.format_stack(limit=14)[:-2])
            test = os.environ.get("PYTEST_CURRENT_TEST", "<no test>")
            with san._hits_lock:
                san.hits.append((method, primitive, test, stack))

        def event_wait(ev, timeout=None):
            if timeout is None:
                record("Event.wait")
            return orig_event_wait(ev, timeout)

        def cond_wait(cond, timeout=None):
            if timeout is None:
                record("Condition.wait")
            return orig_cond_wait(cond, timeout)

        def queue_get(q, block=True, timeout=None):
            if block and timeout is None:
                record("Queue.get")
            return orig_get(q, block, timeout)

        Endpoints._admitted_body = admitted_body
        threading.Event.wait = event_wait
        _real_condition.wait = cond_wait
        queue.Queue.get = queue_get
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for holder, attr, fn in self._saved:
            setattr(holder, attr, fn)
        self._saved = []
        self._installed = False

    def __enter__(self) -> "BudgetWitnessSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- reporting ----------------------------------------------------------
    def _render(self, hits: list) -> str:
        lines = []
        for method, primitive, test, stack in hits:
            lines.append(
                f"unbounded {primitive} while serving {method} "
                f"(test: {test}):\n{stack}")
        return (
            "budget-witness: wait with no timeout on an RPC-serving "
            "thread — the admitted envelope's budget was dropped (see "
            "analysis/faultlint.py deadline pass):\n" +
            "\n".join(lines))

    def check_test(self) -> None:
        """Per-test teardown: fail THIS test on any hit it recorded,
        then reset so later tests report only their own."""
        with self._hits_lock:
            hits, self.hits = self.hits, []
        if hits:
            raise AssertionError(self._render(hits))

    def check(self) -> None:
        """Session catch-all for hits recorded outside any test body
        (module fixtures, background threads between tests)."""
        with self._hits_lock:
            hits = list(self.hits)
        if hits:
            raise AssertionError(self._render(hits))
