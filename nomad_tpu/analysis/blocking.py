"""Interprocedural concurrency passes over the call graph.

Three passes, all riding ``callgraph.CallGraph`` + lockcheck's lock-site
naming (``Class.attr`` / ``module.NAME``), so one allowlist grammar
covers the whole analyzer family:

**blocking-under-lock** — a *blocking root* classification (``time.sleep``,
``Event.wait``/``Condition.wait``, ``Future.result``, blocking
``queue.get/put``, socket ops, subprocess waits, device
dispatch/collect, and the ``utils/retry.py`` sleep paths) is propagated
up the call graph to a ``may_block`` set, then intersected with each
function's held-lock regions: ``fn_a`` holding ``C._lock`` while calling
``fn_b → fn_c → sock.sendall`` is flagged with the full call chain.
``Condition.wait`` on the condition guarding the *innermost held* lock
is exempt (wait releases that lock); any other lock held across it still
flags.  Key grammar: ``blocking-under-lock:path:Qual[Site]``.

**cross-function lock-order** — interprocedurally-reachable acquisitions
(a transitive ``may_acquire`` fixpoint) feed the lock-order graph, so
cycles spanning modules (pipeline↔breaker, alloc_runner↔rpc) and
nested self-acquires three frames deep are detected statically, not just
by the runtime witness.  Cycles lockcheck's syntactic pass already
reports are suppressed here.  Key grammar: ``lock-cycle:path:a->b->a``
and ``nested-self-acquire:path:Qual->Site``.

**thread/future lifecycle** — every ``threading.Thread(...)`` creation
site must retain a joinable handle (``.join()`` reachable in the binding
scope), escape to a registry (returned / passed / appended), or carry a
justified allowlist line; ``Future``-shaped objects must reach a
``respond``/``set_result``/``set_exception`` in their binding scope; an
``Event`` someone waits on *untimed* must have a ``.set()`` reachable.
Key grammar: ``thread-leak:path:Qual.binding`` (same for
``future-leak`` / ``event-leak``).

A separate test-tree helper (``scan_test_sleeps``) flags fixed
``time.sleep(<const>)`` calls in test files that do not carry a
``# sleep-ok:`` justification comment — the wait-until conversion
ratchet.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Optional

from . import Finding
from .callgraph import CallGraph, _self_attr, _child_defs
from . import lockcheck

# -- blocking-root classification -------------------------------------------

# Attribute-call method names that block regardless of receiver type.
_ALWAYS_BLOCKING_METHODS = {
    "sendall": "socket send", "recv": "socket recv",
    "recvfrom": "socket recv", "accept": "socket accept",
    "connect": "socket connect", "wrap_socket": "TLS handshake",
    "communicate": "subprocess wait", "result": "Future.result",
    "wait": "blocking wait", "wait_for": "blocking wait",
}
# Device round-trips (the pipeline's dispatch/collect seam + jax sync).
_DEVICE_METHODS = {
    "dispatch_device": "device dispatch",
    "collect_device": "device collect",
    "block_until_ready": "device sync",
}
# External (non-package) callables that block.
_BLOCKING_EXTERNALS = {
    "time.sleep": "time.sleep",
    "socket.create_connection": "socket dial",
    "select.select": "select",
    "subprocess.run": "subprocess", "subprocess.call": "subprocess",
    "subprocess.check_call": "subprocess",
    "subprocess.check_output": "subprocess",
}
_QUEUE_RECEIVER_RE = re.compile(r"(^|_)(q|queue|inq|outq|inbox|outbox)$")
_THREAD_RECEIVER_RE = re.compile(
    r"(thread|ticker|notifier|reader|drain|worker|repl)s?$")


def _kwarg(node: ast.Call, name: str):
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_false(expr) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is False


class _Region:
    """Per-function record of held-lock context at every call site."""

    __slots__ = ("key", "qual", "rel", "roots", "calls", "acquires")

    def __init__(self, key: str, qual: str, rel: str) -> None:
        self.key = key
        self.qual = qual
        self.rel = rel
        # (held_sites_tuple, label, line, receiver_attr) — direct roots
        self.roots: list = []
        # (held_sites_tuple, callee_key, line, text) — resolved calls
        self.calls: list = []
        self.acquires: set = set()   # lock sites acquired directly


class _RegionVisitor(ast.NodeVisitor):
    """Walk ONE function body tracking the held-lock stack; record lock
    acquisitions, resolved intra-package calls, and direct blocking
    roots.  ``.acquire()/.release()`` calls on resolvable sites extend
    the held region to the end of the enclosing statement list (the
    try/finally and guarded-acquire patterns)."""

    def __init__(self, graph: CallGraph, pkg, info, cls_info,
                 region: _Region, fn_node) -> None:
        self.graph = graph
        self.pkg = pkg           # lockcheck._Package
        self.info = info         # callgraph.ModuleInfo
        self.cls_info = cls_info  # lockcheck._ClassInfo or None
        self.region = region
        self.fn_node = fn_node
        self.cls_key = None
        if cls_info is not None:
            self.cls_key = f"{cls_info.module}.{cls_info.name}"
        self.module = info.module
        self.stack: list = []
        self.local_types: dict = {}
        self.local_queues: set = set()   # locals holding queue objects
        self.local_bounded: set = set()  # ...with maxsize > 0

    # -- lock-site naming (same rules as lockcheck._OrderVisitor) ----------
    def _site_of(self, expr: ast.expr) -> Optional[str]:
        if self.cls_info is not None:
            name = lockcheck._lock_name_of(self.cls_info, expr)
            if name:
                return f"{self.cls_info.name}.{name}"
        if isinstance(expr, ast.Name) and \
                expr.id in self.pkg.module_locks.get(self.module, ()):
            return f"{self.module}.{expr.id}"
        if isinstance(expr, ast.Attribute):
            owner_attr = _self_attr(expr.value)
            if owner_attr is not None and self.cls_info is not None:
                cls_name = self.cls_info.attr_types.get(owner_attr)
                if cls_name:
                    target = self.pkg.class_by_name(cls_name)
                    if target is not None:
                        alias = target.lock_aliases.get(expr.attr,
                                                        expr.attr)
                        if alias in target.locks:
                            return f"{target.name}.{alias}"
            if expr.attr == "lock" or expr.attr.endswith("_lock"):
                return f"?.{expr.attr}"
        return None

    def run(self) -> None:
        node = self.fn_node
        args = node.args
        for a in list(args.posonlyargs) + list(args.args) + \
                list(args.kwonlyargs):
            if a.annotation is not None:
                from .callgraph import _unquote
                hit = self.graph._class_key_of_expr(
                    self.info, _unquote(a.annotation))
                if hit is not None:
                    self.local_types[a.arg] = hit
        self._walk_body(node.body)

    # -- body walking with acquire()-extended regions ----------------------
    def _walk_body(self, body: list) -> None:
        pushed = 0
        for stmt in body:
            site = self._acquire_stmt_site(stmt)
            if site is not None:
                self._note_acquire(site, stmt.lineno)
                self.stack.append(site)
                pushed += 1
                continue
            if self._release_stmt_site(stmt) is not None and pushed:
                self.stack.pop()
                pushed -= 1
                continue
            self.visit(stmt)
        for _ in range(pushed):
            self.stack.pop()

    def _acquire_stmt_site(self, stmt) -> Optional[str]:
        """`x.acquire(...)` as a statement, `y = x.acquire(...)`, or the
        `if not x.acquire(blocking=False): return` guard — the held
        region runs to the end of the enclosing block."""
        call = None
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
        elif isinstance(stmt, ast.Assign) and \
                isinstance(stmt.value, ast.Call):
            call = stmt.value
        elif isinstance(stmt, ast.If) and \
                isinstance(stmt.test, ast.UnaryOp) and \
                isinstance(stmt.test.op, ast.Not) and \
                isinstance(stmt.test.operand, ast.Call):
            inner = stmt.test.operand
            if self._is_acquire(inner) and self._body_exits(stmt.body):
                # Failure arm runs WITHOUT the lock; an else arm (and
                # everything after the If, handled by the caller) runs
                # WITH it.
                self._walk_body(stmt.body)
                site = self._site_of(inner.func.value) or "?.acquire"
                if stmt.orelse:
                    self.stack.append(site)
                    self._walk_body(stmt.orelse)
                    self.stack.pop()
                return site
            return None
        if call is not None and self._is_acquire(call):
            return self._site_of(call.func.value) or "?.acquire"
        return None

    @staticmethod
    def _is_acquire(call: ast.Call) -> bool:
        return isinstance(call.func, ast.Attribute) and \
            call.func.attr == "acquire"

    def _release_stmt_site(self, stmt) -> Optional[str]:
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Call) and \
                isinstance(stmt.value.func, ast.Attribute) and \
                stmt.value.func.attr == "release":
            return self._site_of(stmt.value.func.value) or "?.release"
        return None

    @staticmethod
    def _body_exits(body: list) -> bool:
        return bool(body) and isinstance(
            body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

    def _note_acquire(self, site: str, line: int) -> None:
        self.region.acquires.add(site)
        if self.stack:
            self.region.calls.append(
                (tuple(self.stack), None, line, f"acquire {site}"))

    def visit_With(self, node: ast.With) -> None:
        sites = []
        for item in node.items:
            site = self._site_of(item.context_expr)
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            if site is not None:
                self.region.acquires.add(site)
                self.stack.append(site)
                sites.append(site)
        self._walk_body(node.body)
        for _ in sites:
            self.stack.pop()

    # Nested defs / lambdas run elsewhere: not this function's context.
    def visit_FunctionDef(self, node) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:
        pass

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        self._walk_body(node.body)
        self._walk_body(node.orelse)

    def visit_Try(self, node: ast.Try) -> None:
        self._walk_body(node.body)
        for handler in node.handlers:
            self._walk_body(handler.body)
        self._walk_body(node.orelse)
        self._walk_body(node.finalbody)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        self._walk_body(node.body)
        self._walk_body(node.orelse)

    def visit_While(self, node: ast.While) -> None:
        self.visit(node.test)
        self._walk_body(node.body)
        self._walk_body(node.orelse)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            hit = self.graph._class_key_of_expr(self.info,
                                                node.value.func)
            text = ""
            try:
                text = ast.unparse(node.value.func)
            except Exception:
                pass
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if hit is not None:
                    self.local_types[tgt.id] = hit
                if text.endswith("Queue") or text == "queue.Queue":
                    self.local_queues.add(tgt.id)
                    call = node.value
                    arg = call.args[0] if call.args else _kwarg(
                        call, "maxsize")
                    if arg is not None and not \
                            lockcheck.queue_maxsize_unbounded(arg):
                        self.local_bounded.add(tgt.id)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        label = self._root_label(node)
        held = tuple(self.stack)
        if label is not None:
            recv = None
            fn = node.func
            if isinstance(fn, ast.Attribute):
                recv = _self_attr(fn.value)
            self.region.roots.append((held, label, node.lineno, recv))
        else:
            callee, kind = self.graph.resolve_call(
                self.info, self.cls_key, self.local_types, node.func)
            if kind == "intra":
                text = ""
                try:
                    text = ast.unparse(node.func)
                except Exception:
                    pass
                self.region.calls.append((held, callee, node.lineno,
                                          text))
        self.generic_visit(node)

    # -- root classification ------------------------------------------------
    def _root_label(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Attribute):
            meth = fn.attr
            if meth in _DEVICE_METHODS:
                return _DEVICE_METHODS[meth]
            if meth in _ALWAYS_BLOCKING_METHODS:
                return _ALWAYS_BLOCKING_METHODS[meth]
            if meth == "sleep":
                owner = fn.value
                if isinstance(owner, ast.Name) and \
                        owner.id in ("time", "_time"):
                    return "time.sleep"
                # Backoff.sleep et al resolve through the call graph.
                return None
            if meth == "join":
                if self._receiver_is_thread(fn.value):
                    return "Thread.join"
                return None
            if meth in ("get", "put"):
                if _is_false(_kwarg(node, "block")):
                    return None
                if not self._receiver_is_queue(fn.value):
                    return None
                if meth == "put" and not self._receiver_is_bounded(
                        fn.value):
                    # put() on an unbounded queue never blocks; only
                    # known-bounded queues are roots (documented
                    # under-approximation).
                    return None
                return f"queue.{meth}"
            if meth == "acquire":
                if _is_false(_kwarg(node, "blocking")):
                    return None
                if self._site_of(fn.value) is None:
                    return "blocking acquire"
                return None  # resolvable site: order pass owns it
            return None
        # plain-name / external calls
        callee, kind = self.graph.resolve_call(
            self.info, self.cls_key, self.local_types, fn)
        if kind == "external" and callee in _BLOCKING_EXTERNALS:
            return _BLOCKING_EXTERNALS[callee]
        return None

    def _receiver_is_queue(self, owner: ast.expr) -> bool:
        attr = _self_attr(owner)
        if attr is not None:
            if self.cls_info is not None and attr in \
                    self.cls_info.sync_safe:
                return True
            return bool(_QUEUE_RECEIVER_RE.search(attr))
        if isinstance(owner, ast.Name):
            if owner.id in self.local_queues:
                return True
            return bool(_QUEUE_RECEIVER_RE.search(owner.id))
        if isinstance(owner, ast.Attribute):
            return bool(_QUEUE_RECEIVER_RE.search(owner.attr))
        return False

    def _receiver_is_bounded(self, owner: ast.expr) -> bool:
        attr = _self_attr(owner)
        if attr is not None and self.cls_info is not None:
            return attr in self.cls_info.bounded_queues
        if isinstance(owner, ast.Name):
            return owner.id in self.local_bounded
        return False

    def _receiver_is_thread(self, owner: ast.expr) -> bool:
        attr = _self_attr(owner)
        name = attr if attr is not None else (
            owner.id if isinstance(owner, ast.Name) else (
                owner.attr if isinstance(owner, ast.Attribute) else None))
        if name is None:
            return False
        if name in ("t", "tr", "thread"):
            return True
        return bool(_THREAD_RECEIVER_RE.search(name))


# ---------------------------------------------------------------------------
# pass drivers
# ---------------------------------------------------------------------------

def _build_regions(graph: CallGraph, pkg) -> dict:
    cls_infos = {}
    for info in pkg.classes:
        cls_infos[(info.module, info.name)] = info
    regions: dict = {}
    for key, fn in graph.functions.items():
        info = graph.modules.get(fn.module)
        if info is None:
            continue
        cls_info = cls_infos.get((fn.module, fn.cls)) if fn.cls else None
        region = _Region(key, fn.qual, fn.rel)
        _RegionVisitor(graph, pkg, info, cls_info, region,
                       fn.node).run()
        regions[key] = region
    return regions


def _may_block(regions: dict) -> dict:
    """key -> chain: [(description, rel, line), ...] ending at a root."""
    chains: dict = {}
    for key, region in regions.items():
        if region.roots:
            held, label, line, _recv = region.roots[0]
            chains[key] = [(label, region.rel, line)]
    changed = True
    while changed:
        changed = False
        for key, region in regions.items():
            for _held, callee, line, text in region.calls:
                if callee is None or callee not in chains:
                    continue
                cand = [(text or callee, region.rel, line)] + \
                    chains[callee]
                if key not in chains or len(cand) < len(chains[key]):
                    chains[key] = cand
                    changed = True
    return chains


def _may_acquire(regions: dict) -> dict:
    acq = {key: set(r.acquires) for key, r in regions.items()}
    changed = True
    while changed:
        changed = False
        for key, region in regions.items():
            mine = acq[key]
            for _held, callee, _line, _text in region.calls:
                if callee is None:
                    continue
                extra = acq.get(callee)
                if extra and not extra <= mine:
                    mine |= extra
                    changed = True
    return acq


def _cond_alias_exempt(pkg, region: _Region, graph: CallGraph,
                       recv: Optional[str], innermost: str) -> bool:
    """A ``.wait()`` on the Condition guarding the innermost held lock
    releases that lock while waiting — not a blocking-under-lock."""
    if recv is None or "." not in innermost:
        return False
    cls_name, lock_attr = innermost.split(".", 1)
    for info in pkg.classes:
        if info.name != cls_name:
            continue
        resolved = info.lock_aliases.get(recv, recv)
        if resolved == lock_attr:
            return True
    return False


def _chain_text(chain: list) -> str:
    return " -> ".join(step[0] for step in chain)


def blocking_under_lock(graph: CallGraph, pkg, regions: dict,
                        chains: dict) -> list:
    findings: list = []
    seen: set = set()
    for key, region in regions.items():
        for held, label, line, recv in region.roots:
            if not held:
                continue
            innermost = held[-1]
            if label == "blocking wait" and _cond_alias_exempt(
                    pkg, region, graph, recv, innermost):
                continue
            fkey = (region.qual, innermost, label)
            if fkey in seen:
                continue
            seen.add(fkey)
            findings.append(Finding(
                "blocking-under-lock", region.rel,
                f"{region.qual}[{innermost}]",
                f"holds {innermost} across {label}", line))
        for held, callee, line, text in region.calls:
            if not held or callee is None:
                continue
            chain = chains.get(callee)
            if chain is None:
                continue
            innermost = held[-1]
            fkey = (region.qual, innermost, callee)
            if fkey in seen:
                continue
            seen.add(fkey)
            findings.append(Finding(
                "blocking-under-lock", region.rel,
                f"{region.qual}[{innermost}]",
                f"holds {innermost} across a call chain that blocks: "
                f"{text or callee} -> {_chain_text(chain)}", line))
    return findings


def cross_function_lock_order(graph: CallGraph, pkg, regions: dict,
                              acq: dict) -> list:
    findings: list = []
    kind_of: dict = {}
    for info in pkg.classes:
        for attr, kind in info.locks.items():
            kind_of[f"{info.name}.{attr}"] = kind
    for module, locks in pkg.module_locks.items():
        for name, kind in locks.items():
            kind_of[f"{module}.{name}"] = kind

    edges: dict = {}
    self_edges: dict = {}
    for key, region in regions.items():
        for held, callee, line, text in region.calls:
            if not held or callee is None:
                continue
            outer = held[-1]
            for inner in acq.get(callee, ()):
                if inner in held:
                    if inner == outer:
                        self_edges.setdefault(
                            inner, (region, callee, line, text))
                    continue
                edges.setdefault((outer, inner),
                                 (region, callee, line, text))

    for site, (region, callee, line, text) in sorted(self_edges.items()):
        if kind_of.get(site) != "Lock":
            continue
        if site in pkg.self_sites:
            continue  # lockcheck's syntactic pass already reported it
        callee_fn = graph.functions.get(callee)
        callee_q = callee_fn.qual if callee_fn else callee
        findings.append(Finding(
            "nested-self-acquire", region.rel,
            f"{region.qual}->{callee_q}",
            f"non-reentrant {site} held while calling {text or callee_q},"
            f" which may re-acquire it (deadlock if the instances "
            "coincide)", line))

    order_graph: dict = {}
    for (a, b), meta in edges.items():
        order_graph.setdefault(a, {})[b] = meta
    for cycle in lockcheck.find_cycles(order_graph):
        if frozenset(cycle) in pkg.cycle_sets:
            continue  # already reported by the syntactic pass
        region, callee, line, text = order_graph[cycle[0]][cycle[1]]
        findings.append(Finding(
            "lock-cycle", region.rel,
            "->".join(cycle + (cycle[0],)),
            f"interprocedural lock-order cycle (witness: {region.qual} "
            f"-> {text or callee})", line))
    return findings


# ---------------------------------------------------------------------------
# thread/future/event lifecycle
# ---------------------------------------------------------------------------

_RESOLVING_METHODS = {"respond", "set_result", "set_exception", "cancel"}


def _calls_method_on(tree, binding: str, methods: set) -> bool:
    """Does any ``<binding>.m(...)`` / ``self.<binding>.m(...)`` with m in
    ``methods`` appear under ``tree``?"""
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in methods:
            continue
        owner = node.func.value
        name = _self_attr(owner)
        if name is None and isinstance(owner, ast.Name):
            name = owner.id
        if name is None and isinstance(owner, ast.Attribute):
            name = owner.attr
        if name == binding:
            return True
    return False


def _escapes(fn_node, binding: str, creation: ast.Call) -> bool:
    """The local handle leaves the function: returned, yielded, passed as
    a call argument, stored on an attribute/container, or put in a
    collection literal — somebody else owns its lifecycle then."""
    for node in ast.walk(fn_node):
        if isinstance(node, (ast.Return, ast.Yield)) and \
                node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id == binding:
                    return True
        elif isinstance(node, ast.Call):
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id == binding:
                        # a `binding.start()` receiver doesn't count,
                        # but `x.append(binding)` / `f(binding)` does
                        return True
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    if isinstance(node.value, ast.Name) and \
                            node.value.id == binding:
                        return True
            for sub in ast.walk(node.value):
                if sub is not node.value and isinstance(sub, ast.Name) \
                        and sub.id == binding:
                    if isinstance(node.value, (ast.Dict, ast.List,
                                               ast.Tuple, ast.Set)):
                        return True
    return False


def _creation_kind(graph: CallGraph, info, cls_key, local_types,
                   call: ast.Call) -> Optional[str]:
    """'thread' | 'event' | 'future' for a creation call, else None."""
    callee, kind = graph.resolve_call(info, cls_key, local_types,
                                     call.func)
    if kind == "external":
        if callee in ("threading.Thread",):
            return "thread"
        if callee == "threading.Event":
            return "event"
        if callee in ("concurrent.futures.Future", "futures.Future"):
            return "future"
    if kind == "intra" and isinstance(callee, str) and \
            callee.endswith(".__init__"):
        cls_name = callee.rsplit(":", 1)[-1].split(".")[0]
        if cls_name.endswith("Future"):
            return "future"
    # Unresolved bare names still count when unambiguous.
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id == "Thread":
            return "thread"
        if fn.id == "Event":
            return "event"
    if isinstance(fn, ast.Attribute) and fn.attr in ("Thread", "Event") \
            and isinstance(fn.value, ast.Name) and \
            fn.value.id == "threading":
        return {"Thread": "thread", "Event": "event"}[fn.attr]
    return None


def lifecycle(graph: CallGraph, pkg) -> list:
    findings: list = []
    cls_nodes = {}  # class key -> ClassDef node (search scope for attrs)
    for ckey, cnode in graph.classes.items():
        cls_nodes[ckey] = cnode.node

    for key, fn in graph.functions.items():
        info = graph.modules.get(fn.module)
        if info is None:
            continue
        cls_key = f"{fn.module}.{fn.cls}" if fn.cls else None
        scope_node = cls_nodes.get(cls_key, info.tree)
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            kind = _creation_kind(graph, info, cls_key, {}, node)
            if kind is None:
                continue
            binding, attr_bound, anonymous = _binding_of(fn.node, node)
            if kind == "thread":
                f = _check_thread(fn, scope_node, node, binding,
                                  attr_bound, anonymous)
            elif kind == "future":
                f = _check_future(fn, scope_node, node, binding,
                                  attr_bound, anonymous)
            else:
                f = _check_event(fn, scope_node, node, binding,
                                 attr_bound, anonymous)
            if f is not None:
                findings.append(f)
    return findings


def _binding_of(fn_node, creation: ast.Call):
    """(name, bound_to_self_attr, anonymous) for a creation call."""
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Assign) and node.value is creation:
            tgt = node.targets[0]
            attr = _self_attr(tgt)
            if attr is not None:
                return attr, True, False
            if isinstance(tgt, ast.Name):
                return tgt.id, False, False
            return None, False, False
        if isinstance(node, ast.AnnAssign) and node.value is creation:
            attr = _self_attr(node.target)
            if attr is not None:
                return attr, True, False
            if isinstance(node.target, ast.Name):
                return node.target.id, False, False
    # `threading.Thread(...).start()` or passed straight to a call
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Attribute) and node.value is creation:
            return None, False, True        # immediate method call
        if isinstance(node, ast.Call) and creation in node.args:
            return None, False, False       # passed: escapes
        if isinstance(node, (ast.Dict, ast.List, ast.Tuple)) and \
                any(el is creation for el in ast.walk(node)
                    if el is not node):
            return None, False, False
        if isinstance(node, ast.Return) and node.value is creation:
            return None, False, False
    return None, False, False


def _check_thread(fn, scope_node, creation, binding, attr_bound,
                  anonymous) -> Optional[Finding]:
    if anonymous:
        return Finding(
            "thread-leak", fn.rel, f"{fn.qual}.<anonymous>",
            "Thread started without retaining a handle: nothing can "
            "ever join it or observe its death", creation.lineno)
    if binding is None:
        return None  # escapes (passed/returned/collected)
    if attr_bound:
        if _calls_method_on(scope_node, binding, {"join"}):
            return None
        return Finding(
            "thread-leak", fn.rel, f"{fn.qual}.{binding}",
            f"Thread handle self.{binding} is never joined anywhere in "
            "its class: shutdown cannot wait it out", creation.lineno)
    if _calls_method_on(fn.node, binding, {"join"}) or \
            _escapes(fn.node, binding, creation):
        return None
    return Finding(
        "thread-leak", fn.rel, f"{fn.qual}.{binding}",
        f"Thread handle {binding!r} neither joined nor handed off "
        "before going out of scope", creation.lineno)


def _check_future(fn, scope_node, creation, binding, attr_bound,
                  anonymous) -> Optional[Finding]:
    if binding is None:
        return None  # escapes: consumer owns resolution
    scope = scope_node if attr_bound else fn.node
    if _calls_method_on(scope, binding, _RESOLVING_METHODS):
        return None
    if not attr_bound and _escapes(fn.node, binding, creation):
        return None
    where = f"self.{binding}" if attr_bound else repr(binding)
    return Finding(
        "future-leak", fn.rel, f"{fn.qual}.{binding}",
        f"future {where} is created but no "
        "respond/set_result/set_exception is reachable in its scope: "
        "a waiter would pend forever", creation.lineno)


def _check_event(fn, scope_node, creation, binding, attr_bound,
                 anonymous) -> Optional[Finding]:
    if binding is None:
        return None
    scope = scope_node if attr_bound else fn.node
    # Only events someone waits on UNTIMED can pend forever.
    untimed = False
    for node in ast.walk(scope):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "wait" and not node.args and \
                not node.keywords:
            owner = node.func.value
            name = _self_attr(owner) or (
                owner.id if isinstance(owner, ast.Name) else None)
            if name == binding:
                untimed = True
                break
    if not untimed:
        return None
    if _calls_method_on(scope, binding, {"set"}):
        return None
    if not attr_bound and _escapes(fn.node, binding, creation):
        return None
    return Finding(
        "event-leak", fn.rel, f"{fn.qual}.{binding}",
        f"event {binding!r} is waited on without a timeout but no "
        ".set() is reachable in its scope", creation.lineno)


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def analyze_package(package_dir: str, graph: Optional[CallGraph] = None,
                    scan=None) -> list:
    """Run the three interprocedural passes.  ``scan`` is lockcheck's
    ``scan_package`` result (run lockcheck.analyze_package on it FIRST so
    its syntactic cycles are known and not double-reported)."""
    if graph is None:
        graph = CallGraph.build(package_dir)
    pkg, _trees, err = scan or lockcheck.scan_package(package_dir)
    if err is not None:
        return []  # lockcheck already reports the parse error
    regions = _build_regions(graph, pkg)
    chains = _may_block(regions)
    acq = _may_acquire(regions)
    findings: list = []
    findings.extend(blocking_under_lock(graph, pkg, regions, chains))
    findings.extend(cross_function_lock_order(graph, pkg, regions, acq))
    findings.extend(lifecycle(graph, pkg))
    return findings


# ---------------------------------------------------------------------------
# test-tree mode: the fixed-sleep ratchet
# ---------------------------------------------------------------------------

def scan_test_sleeps(tests_dir: str) -> list:
    """Flag ``time.sleep(<constant>)`` in test files.  A fixed sleep is
    either a disguised wait (convert to ``wait_until``) or an intentional
    race-window/pacing sleep — the latter carries a ``# sleep-ok: why``
    comment on the same line and is skipped.  Advisory severity; the
    tier-1 gate bounds the count so it ratchets down, not up."""
    findings: list = []
    for root, dirs, files in os.walk(tests_dir):
        dirs[:] = sorted(d for d in dirs if not d.startswith("__pycache"))
        for fname in sorted(files):
            if not (fname.startswith("test_") or fname == "conftest.py") \
                    or not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path) as fh:
                source = fh.read()
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError:
                continue
            lines = source.splitlines()
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call) and
                        isinstance(node.func, ast.Attribute) and
                        node.func.attr == "sleep" and
                        isinstance(node.func.value, ast.Name) and
                        node.func.value.id in ("time", "_time")):
                    continue
                if not (node.args and isinstance(node.args[0],
                                                 ast.Constant)):
                    continue
                line_text = lines[node.lineno - 1] if \
                    node.lineno <= len(lines) else ""
                if "sleep-ok:" in line_text:
                    continue
                findings.append(Finding(
                    "fixed-sleep", os.path.join(
                        os.path.basename(tests_dir.rstrip(os.sep)),
                        os.path.relpath(path, tests_dir)),
                    f"{fname}:{node.lineno}",
                    f"fixed time.sleep({ast.unparse(node.args[0])}) in a "
                    "test: convert to wait_until or justify with "
                    "'# sleep-ok: <why>'", node.lineno,
                    severity="info"))
    return findings
