"""Device-plane lint: sharding propagation, transfer discipline,
recompile provenance.

The jax-binpack kernel is the repo's whole thesis, and since the fleet
went sharded (PR 12) its failure modes are *placement* failures no
behavioral test sees: a dispatch that bypasses the one mesh authority,
a host operand silently committed into a sharded kernel (a per-eval
implicit transfer), a device value concretized while a lock is held, or
a jit call whose static args drift per call and retrace the kernel.
Each degrades the 131k-node rows into transfer-bound or
recompile-per-eval regimes quietly — the metastable-failure shape
(PAPERS.md, Bronson et al.) — on hardware the test machine doesn't
have.  Three passes ride the PR-4 interprocedural call graph:

**Sharding propagation** — abstract-interprets placement through the
device core.  Every jit kernel in the package is discovered (decorator
and ``name = jax.jit(...)`` wrapper forms, ``*_sharded`` names classify
the sharded family); at each resolved kernel call site, every operand
is judged *placed* (derived from an explicit placement seam —
``device_put`` / ``mesh._put`` / ``devices.put_counted`` /
``ensure_on_default`` / ``ShardedResidency`` / the ``_dev_const``
holders / ``shard_fleet_arrays`` — or another kernel's output) or
*host*.  Rules:

  - ``mesh-bypass``: an UNSHARDED kernel dispatched from a function
    that never consulted ``dispatch_mesh`` — the dispatch silently
    pins the whole fleet to one device no matter what mesh the
    platform resolves.  (Kernel bodies and the kernel's own defining
    module are exempt: jit-to-jit composition is traced code, not a
    dispatch.)
  - ``sharding-mix``: a host operand flowing into a SHARDED kernel —
    GSPMD commits it with default placement, mixing shardings and
    paying an implicit transfer on every call.  Wrapper functions'
    parameters count as host (the wrapper IS the placement boundary).
  - ``resident-bypass``: a raw ``jax.device_put`` outside the
    sanctioned residency seams — an upload the transfer odometer and
    the residency policy never see.

**Transfer discipline** — classifies transfer sites: explicit
placements (the device_put family), device->host concretizations
(``np.asarray`` / ``float()`` / ``.item()`` / ``.tolist()`` /
``device_get`` on device-tainted values), and implicit
host-flows-into-kernel operands.  Two rules intersect them with
context:

  - ``transfer-under-lock``: a transfer site (or a call chain reaching
    one) inside a held-lock region — every other thread queues behind
    a PCIe/ICI round trip (the lock machinery is shared with
    blocking.py, same ``Qual[Lock.site]`` key grammar).
  - ``transfer-in-hot-loop``: an IMPLICIT transfer (host kernel
    operand, or an unsanctioned tainted concretize) reachable from the
    pipeline/applier hot paths — the per-eval cost that turns the
    stream transfer-bound.  The sanctioned collect seams
    (``fetch_results`` / ``collect_device``) stay open; explicit
    counted placements are the *fix*, not a finding.

**Recompile provenance** — makes the runtime recompile sentinel static:

  - ``recompile-churn``: a kernel call site whose static args derive
    from per-call-varying values (``len()`` arithmetic with no
    bucketing through ``_pad_to``/``pad_lanes``/``bit_length``
    rounding), an array constructor with an unbucketed dynamic shape
    feeding a kernel, or a dtype-less constructor feeding a kernel
    (dtype drift = a new trace signature per ambient default).

Deliberate exceptions carry an inline justification marker on (or one
line above) the site — ``# devlint-ok(<rule>): <why>`` — the same
reviewed-waiver pattern as the test tree's ``# sleep-ok:``; markers
with no justification text do not waive.  Waived sites are counted in
the coverage block (``nomad-tpu lint -json`` → ``coverage.devlint``)
so the ledger stays visible.
"""
from __future__ import annotations

import ast
import os
import re

from typing import Optional

from . import Finding
from .callgraph import CallGraph, _self_attr
from . import blocking, lockcheck
from .jaxlint import _dotted, _is_jax_jit, _static_names_from_call

# -- placement seams --------------------------------------------------------

# Call names (function or method, last segment) whose RESULT is a
# device-resident value: the explicit placement seams plus the resident
# cache getters.  The abstract interpretation of "placed" starts here.
PRODUCERS = frozenset({
    "device_put", "_put", "ensure_on_default", "put_counted",
    "shard_fleet_arrays",
    "device_capacity_reserved", "device_capacity_reserved_sharded",
    "device_feasible_sharded", "device_usage", "device_usage_sharded",
    "dispatch_usage", "_dev_const", "_dev_const_repl",
})

# Receiver-qualified producers: `<something sharded>.prepare/install/
# lookup` (ShardedResidency) — "prepare"/"install" alone are too
# generic to trust on arbitrary receivers.
_SHARDED_RES_METHODS = frozenset({"prepare", "install", "lookup"})

# Functions allowed to call jax.device_put directly (the seams
# themselves).  Quals starting with "ShardedResidency." are also
# sanctioned.
RESIDENT_SEAMS = frozenset({
    "_put", "ensure_on_default", "put_counted", "_scatter_rows",
})

# Sanctioned device->host collect seams: the deliberate fetch points
# whose concretizations are the design, not a finding.
D2H_SEAMS = frozenset({"fetch_results", "fetch_host", "collect_device"})

# Shape-bucketing helpers: a value routed through one of these is
# stable across calls (power-of-two buckets).
BUCKETING = frozenset({"_pad_to", "pad_lanes"})

# Hot-path roots (qualname last segment): the pipeline/batch dispatch
# and drain stages plus the applier's window verify — the per-eval
# loops where an implicit transfer is paid per eval.
HOT_SUFFIXES = frozenset({
    "dispatch_device", "_dispatch_device_sharded", "_drain_window",
    "_collect_item", "_process_staged", "_drain_loop", "_finish_lanes",
    "_run_single", "_process", "_apply_window", "evaluate_window",
    "_prepare_device", "finish_deferred", "_submit_window",
})

_ARRAY_CTORS = frozenset({"zeros", "ones", "empty", "full", "asarray",
                          "array", "arange"})
_CONCRETIZE_FUNCS = frozenset({"float", "int", "bool"})
_CONCRETIZE_METHODS = frozenset({"item", "tolist"})

_MARKER_RE = re.compile(r"#\s*devlint-ok\((?P<rule>[a-z-]+)\)\s*:\s*\S")


class Kernel:
    """One jit-wrapped callable discovered in the package."""

    __slots__ = ("fn_key", "names", "static", "sharded", "module",
                 "params", "line")

    def __init__(self, fn_key: str, module: str, params: list,
                 line: int) -> None:
        self.fn_key = fn_key          # FuncNode key of the traced body
        self.names: set = set()       # binding/def names callers use
        self.static: set = set()      # static_argnames (param names)
        self.sharded = False
        self.module = module
        self.params = params          # positional param names, in order
        self.line = line


def _find_kernels(graph: CallGraph) -> dict:
    """fn_key -> Kernel for every jit root in the package (decorator
    AND wrapper form, vmap/partial unwrapped)."""
    kernels: dict = {}

    def ensure(module: str, fn_name: str, fn_node, line: int) -> Kernel:
        key = f"{module}:{fn_name}"
        k = kernels.get(key)
        if k is None:
            params = [a.arg for a in fn_node.args.args]
            k = kernels[key] = Kernel(key, module, params, line)
            k.names.add(fn_name)
        return k

    for module, info in graph.modules.items():
        fns = {}
        for node in ast.walk(info.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fns.setdefault(node.name, node)
        for node in ast.walk(info.tree):
            if isinstance(node, ast.FunctionDef):
                for deco in node.decorator_list:
                    call = deco if isinstance(deco, ast.Call) else None
                    target = call.func if call else deco
                    inner = None
                    if _is_jax_jit(target):
                        inner = node
                    elif call is not None and _dotted(call.func) in (
                            ("partial",), ("functools", "partial")) and \
                            call.args and _is_jax_jit(call.args[0]):
                        inner = node
                    if inner is None:
                        continue
                    k = ensure(module, node.name, node, node.lineno)
                    if call is not None:
                        k.static |= _static_names_from_call(call, node)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    _is_jax_jit(node.value.func):
                jit_call = node.value
                fn_node = _unwrap(fns, jit_call.args[0]) \
                    if jit_call.args else None
                if fn_node is None:
                    continue
                k = ensure(module, fn_node.name, fn_node, node.lineno)
                k.static |= _static_names_from_call(jit_call, fn_node)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        k.names.add(tgt.id)
    for k in kernels.values():
        k.sharded = any("sharded" in n for n in k.names)
    return kernels


def _unwrap(fns: dict, expr: ast.expr) -> Optional[ast.FunctionDef]:
    for _ in range(6):
        if isinstance(expr, ast.Name):
            return fns.get(expr.id)
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d and d[-1] in ("vmap", "partial", "pmap", "shard_map",
                               "checkpoint", "remat", "grad") and \
                    expr.args:
                expr = expr.args[0]
                continue
            return None
        return None
    return None


# -- markers ----------------------------------------------------------------

def _load_markers(package_dir: str, rels) -> dict:
    """(rel, line) -> {rule, ...} for every justified devlint-ok marker."""
    base = os.path.dirname(os.path.abspath(package_dir))
    out: dict = {}
    for rel in rels:
        path = os.path.join(base, rel)
        try:
            with open(path) as fh:
                lines = fh.read().splitlines()
        except OSError:
            continue
        for i, text in enumerate(lines, 1):
            for m in _MARKER_RE.finditer(text):
                rule = m.group("rule")
                out.setdefault((rel, i), set()).add(rule)
                if not text.lstrip().startswith("#"):
                    # Inline marker (trailing comment on a code line):
                    # it waives THAT line only — never the statement
                    # below it.
                    continue
                # Comment-line marker: waive the continuation comment
                # lines directly below it and the first code line the
                # block lands on (a wrapped justification still covers
                # its site); a blank line ends the block unattached.
                j = i + 1
                while j <= len(lines) and \
                        lines[j - 1].lstrip().startswith("#"):
                    out.setdefault((rel, j), set()).add(rule)
                    j += 1
                if j <= len(lines) and lines[j - 1].strip():
                    out.setdefault((rel, j), set()).add(rule)
    return out


def _waived(markers: dict, rel: str, line: int, rule: str) -> bool:
    # Exact-line only: _load_markers already propagated each marker
    # down its comment block onto the first code line, so checking
    # line-1 here would ALSO waive the statement after the waived one
    # (a real defect hiding directly beneath any marker).
    return rule in markers.get((rel, line), ())


# -- per-function local classification --------------------------------------

class _Locals:
    """Best-effort forward classification of a function's locals:
    which names hold device-placed values, device-tainted values, and
    per-call-varying ("unstable") sizes; plus array-constructor sites.
    Branch-insensitive by design (any producer assignment marks the
    name placed) — the misses are counted, not silent."""

    __slots__ = ("placed", "tainted", "unstable", "ctors")

    def __init__(self) -> None:
        self.placed: set = set()
        self.tainted: set = set()
        self.unstable: set = set()
        # name -> (line, has_dtype, unstable_shape)
        self.ctors: dict = {}


def _producer_call(node: ast.Call, kernels_by_name: dict) -> bool:
    fn = node.func
    name = None
    if isinstance(fn, ast.Name):
        name = fn.id
    elif isinstance(fn, ast.Attribute):
        name = fn.attr
        if name in _SHARDED_RES_METHODS:
            try:
                owner = ast.unparse(fn.value)
            except Exception:
                owner = ""
            return "sharded" in owner
    if name is None:
        return False
    if name in PRODUCERS:
        return True
    return name in kernels_by_name


def _is_bucketed(expr: ast.expr) -> bool:
    """``_pad_to(x)`` / ``pad_lanes(x)`` / ``1 << (...).bit_length()``
    / min/max compositions of those."""
    if isinstance(expr, ast.Call):
        d = _dotted(expr.func)
        if d and d[-1] in BUCKETING:
            return True
        if d and d[-1] in ("min", "max"):
            return True  # min/max over stable inputs stays bounded
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.LShift):
        return True
    return False


def _scan_locals(fn_node, kernels_by_name: dict) -> _Locals:
    st = _Locals()

    def unstable_expr(expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in st.unstable
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d == ("len",) or (d and d[-1] == "sum"):
                return True
            return False
        if isinstance(expr, ast.BinOp):
            return unstable_expr(expr.left) or unstable_expr(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return unstable_expr(expr.operand)
        return False

    def classify(target, value, lineno) -> None:
        names = []
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, (ast.Tuple, ast.List)):
            names = [el.id for el in target.elts
                     if isinstance(el, ast.Name)]
        elif isinstance(target, ast.Subscript) and \
                isinstance(target.value, ast.Name):
            # holder[i] = producer(...) marks the holder placed
            # (the dev_const / feasibility [host, device] patterns).
            if isinstance(value, ast.Call) and \
                    _producer_call(value, kernels_by_name):
                st.placed.add(target.value.id)
            return
        if not names:
            return
        if isinstance(value, ast.Call):
            d = _dotted(value.func)
            if _producer_call(value, kernels_by_name):
                for n in names:
                    st.placed.add(n)
                    st.tainted.add(n)
                return
            if d and len(d) >= 2 and d[0] in ("np", "numpy") and \
                    d[-1] in _ARRAY_CTORS:
                has_dtype = any(kw.arg == "dtype"
                                for kw in value.keywords)
                shape_unstable = False
                if value.args:
                    shape = value.args[0]
                    elts = shape.elts if isinstance(
                        shape, (ast.Tuple, ast.List)) else [shape]
                    shape_unstable = any(unstable_expr(e) for e in elts)
                for n in names:
                    st.ctors[n] = (lineno, has_dtype, shape_unstable)
                    st.placed.discard(n)
                return
        if _is_bucketed(value):
            for n in names:
                st.unstable.discard(n)
            return
        if unstable_expr(value):
            for n in names:
                st.unstable.add(n)
            return
        # Plain rebinding propagates placement/taint (x = holder[1],
        # y = x): the two-pass walk stabilizes chains.
        if _expr_placed(value, st, kernels_by_name):
            for n in names:
                st.placed.add(n)
        if _expr_tainted(value, st, kernels_by_name):
            for n in names:
                st.tainted.add(n)

    # Two passes so loop-carried classifications stabilize.
    for _ in range(2):
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    classify(tgt, node.value, node.lineno)
            elif isinstance(node, ast.AnnAssign) and \
                    node.value is not None:
                classify(node.target, node.value, node.lineno)
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Name) and \
                        unstable_expr(node.value):
                    st.unstable.add(node.target.id)
    return st


def _expr_placed(expr, st: _Locals, kernels_by_name: dict) -> bool:
    """Is this call-site operand derived from an explicit placement?"""
    if isinstance(expr, ast.Name):
        return expr.id in st.placed
    if isinstance(expr, ast.Attribute):
        if expr.attr.endswith("_d") or expr.attr.endswith("_device") or \
                expr.attr == "usage_device":
            return True
        return _expr_placed(expr.value, st, kernels_by_name)
    if isinstance(expr, ast.Subscript):
        return _expr_placed(expr.value, st, kernels_by_name)
    if isinstance(expr, ast.Call):
        return _producer_call(expr, kernels_by_name)
    if isinstance(expr, ast.Starred):
        return _expr_placed(expr.value, st, kernels_by_name)
    return False


def _expr_tainted(expr, st: _Locals, kernels_by_name: dict) -> bool:
    """Does this expression carry a device value (a concretization of
    it is a device->host transfer)?"""
    if isinstance(expr, ast.Name):
        return expr.id in st.tainted
    if isinstance(expr, (ast.Attribute, ast.Subscript, ast.Starred)):
        return _expr_tainted(expr.value, st, kernels_by_name)
    if isinstance(expr, ast.Call):
        return _producer_call(expr, kernels_by_name)
    return False


# -- the region walk --------------------------------------------------------

class _DevRecord:
    __slots__ = ("key", "qual", "rel", "transfers", "kernel_calls",
                 "calls", "consults_mesh", "is_kernel", "d2h_sites")

    def __init__(self, key: str, qual: str, rel: str) -> None:
        self.key = key
        self.qual = qual
        self.rel = rel
        # (held, kind, line, text): kind in {"put", "implicit-h2d",
        # "d2h"} — the transfer sites, with held-lock context.
        self.transfers: list = []
        # (held, Kernel, ast.Call, line)
        self.kernel_calls: list = []
        # (held, callee_key, line, text) — resolved intra calls.
        self.calls: list = []
        self.consults_mesh = False
        self.is_kernel = False
        self.d2h_sites: list = []   # (held, line, text, implicit)


class _DevVisitor(blocking._RegionVisitor):
    """blocking's held-lock region walk, extended to record the
    device-plane events (kernel dispatches, placements, concretize
    sites) alongside the parent's lock bookkeeping."""

    def __init__(self, graph, pkg, info, cls_info, region, fn_node,
                 dev: _DevRecord, st: _Locals, kernels: dict,
                 kernels_by_name: dict) -> None:
        super().__init__(graph, pkg, info, cls_info, region, fn_node)
        self.dev = dev
        self.st = st
        self.kernels = kernels
        self.kernels_by_name = kernels_by_name

    def visit_Call(self, node: ast.Call) -> None:
        self._classify_dev(node)
        super().visit_Call(node)

    def _classify_dev(self, node: ast.Call) -> None:
        held = tuple(self.stack)
        dev = self.dev
        d = _dotted(node.func)
        text = ""
        try:
            text = ast.unparse(node.func)
        except Exception:
            pass

        if d and d[-1] == "dispatch_mesh":
            dev.consults_mesh = True

        # Explicit placement family (the device_put side).
        if d and d[-1] == "device_put":
            dev.transfers.append((held, "put", node.lineno, text))
            return
        if d and d[-1] in ("_put", "ensure_on_default", "put_counted"):
            dev.transfers.append((held, "put", node.lineno, text))
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("prepare", "install"):
            try:
                owner = ast.unparse(node.func.value)
            except Exception:
                owner = ""
            if "sharded" in owner:
                dev.transfers.append((held, "put", node.lineno, text))
                return

        # Device->host concretizations.
        if d and d[-1] in ("device_get", "fetch_host"):
            dev.transfers.append((held, "d2h", node.lineno, text))
            dev.d2h_sites.append((held, node.lineno, text, False))
            return
        if d and len(d) >= 2 and d[0] in ("np", "numpy") and \
                d[-1] in ("asarray", "array") and node.args and \
                _expr_tainted(node.args[0], self.st,
                              self.kernels_by_name):
            dev.transfers.append((held, "d2h", node.lineno, text))
            dev.d2h_sites.append((held, node.lineno, text, True))
            return
        if d and len(d) == 1 and d[0] in _CONCRETIZE_FUNCS and \
                node.args and _expr_tainted(node.args[0], self.st,
                                            self.kernels_by_name):
            dev.transfers.append((held, "d2h", node.lineno, text))
            dev.d2h_sites.append((held, node.lineno, text, True))
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _CONCRETIZE_METHODS and \
                _expr_tainted(node.func.value, self.st,
                              self.kernels_by_name):
            dev.transfers.append((held, "d2h", node.lineno, text))
            dev.d2h_sites.append((held, node.lineno, text, True))
            return

        # Kernel dispatches.
        callee, kind = self.graph.resolve_call(
            self.info, self.cls_key, self.local_types, node.func)
        if kind == "intra" and callee in self.kernels:
            dev.kernel_calls.append((held, self.kernels[callee], node,
                                     node.lineno))
            return
        # Unresolved bare-name kernel call (synthetic packages, local
        # aliases): fall back to the name table.
        name = d[-1] if d else None
        if name in self.kernels_by_name and (kind != "intra"):
            dev.kernel_calls.append(
                (held, self.kernels_by_name[name], node, node.lineno))


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def analyze_package(package_dir: str, graph: Optional[CallGraph] = None,
                    scan=None, coverage_out: Optional[dict] = None
                    ) -> list:
    if graph is None:
        graph = CallGraph.build(package_dir)
    pkg, _trees, err = scan or lockcheck.scan_package(package_dir)
    if err is not None:
        return []  # lockcheck already reports the parse error
    kernels = _find_kernels(graph)
    kernels_by_name: dict = {}
    for k in kernels.values():
        for n in k.names:
            kernels_by_name[n] = k
    kernel_fn_keys = set(kernels)

    cls_infos = {}
    for info in pkg.classes:
        cls_infos[(info.module, info.name)] = info

    markers = _load_markers(
        package_dir, {fn.rel for fn in graph.functions.values()})

    cov = {"kernels": len(kernels), "kernel_call_sites": 0,
           "placed_args": 0, "host_args": 0, "transfer_sites": 0,
           "hot_functions": 0, "waived": 0}

    records: dict = {}
    locals_of: dict = {}
    for key, fn in graph.functions.items():
        info = graph.modules.get(fn.module)
        if info is None:
            continue
        cls_info = cls_infos.get((fn.module, fn.cls)) if fn.cls else None
        dev = _DevRecord(key, fn.qual, fn.rel)
        dev.is_kernel = key in kernel_fn_keys
        st = _scan_locals(fn.node, kernels_by_name)
        region = blocking._Region(key, fn.qual, fn.rel)
        _DevVisitor(graph, pkg, info, cls_info, region, fn.node, dev,
                    st, kernels, kernels_by_name).run()
        dev.calls = region.calls
        records[key] = dev
        locals_of[key] = st

    findings: list = []
    # Waived SITES, deduped (rel, line, rule): one reviewed marker is
    # one ledger entry no matter how many passes or caller chains
    # touch it.
    waived_sites: set = set()

    def emit(rule, rel, where, msg, line):
        if _waived(markers, rel, line, rule):
            waived_sites.add((rel, line, rule))
            return
        findings.append(Finding(rule, rel, where, msg, line))

    def judge_args(kernel: Kernel, call: ast.Call, st: _Locals) -> list:
        """[(param_name, arg_expr, placed)] for every non-static
        operand of one kernel call (positional by index, keyword by
        name)."""
        out = []
        for pos, arg in enumerate(call.args):
            pname = kernel.params[pos] if pos < len(kernel.params) \
                else f"arg{pos}"
            if pname in kernel.static:
                continue
            out.append((pname, arg,
                        _expr_placed(arg, st, kernels_by_name)))
        for kw in call.keywords:
            if kw.arg is None or kw.arg in kernel.static:
                continue
            out.append((kw.arg, kw.value,
                        _expr_placed(kw.value, st, kernels_by_name)))
        return out

    # -- pass 1: sharding propagation ----------------------------------
    for key, dev in records.items():
        if dev.is_kernel:
            continue  # traced code: jit-to-jit composition, not dispatch
        st = locals_of[key]
        for held, kernel, call, line in dev.kernel_calls:
            cov["kernel_call_sites"] += 1
            fn = graph.functions[key]
            in_def_module = fn.module == kernel.module
            # Per-operand placement judgment (skipping static args).
            host_args = []
            for pname, arg, placed in judge_args(kernel, call, st):
                if placed:
                    cov["placed_args"] += 1
                else:
                    cov["host_args"] += 1
                    host_args.append((pname, arg))

            if kernel.sharded:
                for pname, arg in host_args:
                    try:
                        a_text = ast.unparse(arg)
                    except Exception:
                        a_text = pname
                    emit("sharding-mix", dev.rel,
                         f"{dev.qual}.{pname}",
                         f"host operand `{a_text}` flows into sharded "
                         f"kernel call (param `{pname}`): GSPMD commits "
                         "it unsharded — route it through mesh._put / "
                         "the dev_const holders", line)
            elif not in_def_module:
                if not dev.consults_mesh:
                    kname = sorted(kernel.names)[0]
                    emit("mesh-bypass", dev.rel,
                         f"{dev.qual}.{kname}",
                         f"dispatches unsharded kernel `{kname}` "
                         "without consulting parallel/mesh."
                         "dispatch_mesh — on a multi-device platform "
                         "this silently pins the fleet to one device",
                         line)

    # resident-bypass: raw device_put outside the seams.
    for key, dev in records.items():
        qual_last = dev.qual.split(".")[-1]
        sanctioned = qual_last in RESIDENT_SEAMS or \
            dev.qual.startswith("ShardedResidency.") or dev.is_kernel
        if sanctioned:
            continue
        for held, kind, line, text in dev.transfers:
            if kind == "put" and text.endswith("device_put"):
                emit("resident-bypass", dev.rel, dev.qual,
                     "raw jax.device_put outside the residency seams "
                     "(mesh._put / devices.put_counted / "
                     "ensure_on_default / ShardedResidency): the "
                     "upload bypasses the transfer odometer and the "
                     "residency policy", line)

    # -- pass 2: transfer discipline -----------------------------------
    # Count transfer sites; waive marker-justified roots out of the
    # may-transfer chains so a justified site doesn't flag its callers.
    chains: dict = {}
    for key, dev in records.items():
        cov["transfer_sites"] += len(dev.transfers)
        live_roots = []
        for held, kind, line, text in dev.transfers:
            if _waived(markers, dev.rel, line, "transfer-under-lock"):
                waived_sites.add((dev.rel, line, "transfer-under-lock"))
            else:
                live_roots.append((held, kind, line, text))
        if live_roots:
            held, kind, line, text = live_roots[0]
            chains[key] = [(f"{text or kind} [{kind}]", dev.rel, line)]
    changed = True
    while changed:
        changed = False
        for key, dev in records.items():
            for held, callee, line, text in dev.calls:
                if callee is None or callee not in chains:
                    continue
                cand = [(text or callee, dev.rel, line)] + chains[callee]
                if key not in chains or len(cand) < len(chains[key]):
                    chains[key] = cand
                    changed = True

    seen: set = set()
    for key, dev in records.items():
        if dev.is_kernel:
            continue
        for held, kind, line, text in dev.transfers:
            if not held:
                continue
            innermost = held[-1]
            # Dedup is line-qualified: two same-shaped sites under one
            # lock are separate findings, so a marker waiving the first
            # can never swallow the second.
            fkey = (dev.qual, innermost, kind, text, line)
            if fkey in seen:
                continue
            seen.add(fkey)
            emit("transfer-under-lock", dev.rel,
                 f"{dev.qual}[{innermost}]",
                 f"holds {innermost} across a device transfer "
                 f"({text or kind}): every other thread queues behind "
                 "the copy — upload outside the lock and revalidate",
                 line)
        for held, callee, line, text in dev.calls:
            if not held or callee is None:
                continue
            chain = chains.get(callee)
            if chain is None:
                continue
            waived_step = next(
                ((rel, ln) for _txt, rel, ln in chain
                 if _waived(markers, rel, ln, "transfer-under-lock")),
                None)
            if waived_step is not None:
                waived_sites.add(waived_step +
                                 ("transfer-under-lock",))
                continue
            innermost = held[-1]
            fkey = (dev.qual, innermost, callee)
            if fkey in seen:
                continue
            seen.add(fkey)
            emit("transfer-under-lock", dev.rel,
                 f"{dev.qual}[{innermost}]",
                 f"holds {innermost} across a call chain that "
                 f"transfers: {text or callee} -> " +
                 " -> ".join(s[0] for s in chain), line)

    # Hot-path reachability (BFS over resolved intra calls).
    hot: set = set()
    frontier = [key for key, dev in records.items()
                if dev.qual.split(".")[-1] in HOT_SUFFIXES]
    while frontier:
        key = frontier.pop()
        if key in hot:
            continue
        hot.add(key)
        dev = records.get(key)
        if dev is None:
            continue
        for _held, callee, _line, _text in dev.calls:
            if callee is not None and callee in records and \
                    callee not in hot:
                frontier.append(callee)
    cov["hot_functions"] = len(hot)

    for key in hot:
        dev = records[key]
        if dev.is_kernel:
            continue
        qual_last = dev.qual.split(".")[-1]
        st = locals_of[key]
        # Implicit host operands into kernels on the hot path.
        for held, kernel, call, line in dev.kernel_calls:
            if kernel.sharded:
                continue  # pass 1 owns the sharded family
            for pname, arg, placed in judge_args(kernel, call, st):
                if placed:
                    continue
                try:
                    a_text = ast.unparse(arg)
                except Exception:
                    a_text = pname
                emit("transfer-in-hot-loop", dev.rel,
                     f"{dev.qual}.{pname}",
                     f"host operand `{a_text}` is committed "
                     "implicitly by jit on the per-eval hot path — "
                     "place it explicitly (devices.put_counted / "
                     "the dev_const holders) so the transfer is "
                     "counted and guard-safe", line)
        # Unsanctioned tainted concretizations on the hot path.
        if qual_last not in D2H_SEAMS:
            for held, line, text, implicit in dev.d2h_sites:
                if not implicit:
                    continue  # explicit device_get: disciplined
                emit("transfer-in-hot-loop", dev.rel,
                     f"{dev.qual}.{text or 'concretize'}",
                     f"implicit device->host concretization "
                     f"({text}) on the per-eval hot path — fetch "
                     "through the collect seams "
                     "(fetch_results/devices.fetch_host)", line)

    # -- pass 3: recompile provenance ----------------------------------
    for key, dev in records.items():
        if dev.is_kernel:
            continue
        st = locals_of[key]
        for held, kernel, call, line in dev.kernel_calls:
            # (a) static args must be call-stable.
            for kw in call.keywords:
                if kw.arg not in kernel.static:
                    continue
                v = kw.value
                if isinstance(v, ast.Constant):
                    continue
                if isinstance(v, ast.Name) and v.id in st.unstable:
                    emit("recompile-churn", dev.rel,
                         f"{dev.qual}.{kw.arg}",
                         f"static arg `{kw.arg}={v.id}` derives from a "
                         "per-call-varying value with no bucketing "
                         "(_pad_to / pad_lanes / bit_length rounding): "
                         "every new value is a full XLA retrace", line)
            # (b) array operands with unbucketed dynamic shapes or
            # missing dtype feeding the kernel.
            for arg in list(call.args) + [kw.value
                                          for kw in call.keywords]:
                if not isinstance(arg, ast.Name):
                    continue
                ctor = st.ctors.get(arg.id)
                if ctor is None:
                    continue
                ctor_line, has_dtype, shape_unstable = ctor
                if shape_unstable:
                    emit("recompile-churn", dev.rel,
                         f"{dev.qual}.{arg.id}",
                         f"kernel operand `{arg.id}` is constructed "
                         "with a per-call-varying shape (len-derived, "
                         "unbucketed): each distinct size retraces the "
                         "kernel — bucket it (_pad_to / pad_lanes)",
                         ctor_line)
                elif not has_dtype:
                    emit("recompile-churn", dev.rel,
                         f"{dev.qual}.{arg.id}",
                         f"kernel operand `{arg.id}` is constructed "
                         "without an explicit dtype: the ambient "
                         "default (float64 vs float32) silently forks "
                         "the trace signature", ctor_line)

    cov["waived"] = len(waived_sites)
    if coverage_out is not None:
        coverage_out.update(cov)
    return findings
