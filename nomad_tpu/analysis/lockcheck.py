"""Lock-discipline analyzer: the `-race` + `go vet -copylocks` analogue.

Two passes over the package AST:

1. **Guarded-attribute discipline.**  Per class, every ``self.X =
   threading.Lock()/RLock()/Condition()`` marks ``X`` as a lock.  Every
   other ``self.attr`` access in the class's methods is classified by
   whether it happens inside a ``with self.<lock>:`` region.  An attribute
   with at least one lock-guarded access is *guarded state*; mutating it
   outside any lock (outside ``__init__``, which runs before the object
   is published) is the classic data race the Go race detector exists to
   catch — reported as ``bare-write``.  ``strict`` mode also reports bare
   *reads* of guarded state (``bare-read``, advisory: on CPython many are
   benign snapshot reads, but each deserves a reviewed justification).

2. **Lock-order graph.**  Nested acquisitions — syntactic ``with`` nesting
   plus one level of call-graph propagation (self-methods, module
   functions, and attributes whose type is inferrable from ``self.attr =
   ClassName(...)`` in ``__init__``) — build a directed graph over lock
   *sites* (``Class.attr`` / ``module.NAME``).  Cycles are deadlock risks
   (``lock-cycle``); a nested re-acquisition of the same plain-``Lock``
   site is an instant self-deadlock when both frames hit one instance
   (``nested-self-acquire``).

Module-level locks (``_lock = threading.Lock()``) participate in both
passes; guarded module globals are classified the same way.
"""
from __future__ import annotations

import ast
import os
from typing import Optional

from . import Finding

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

# Method calls that mutate their receiver in place.
MUTATOR_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "add", "discard", "update", "setdefault", "sort", "reverse",
    "appendleft", "popleft", "set", "cancel", "put", "get_nowait",
}
# Receiver-mutating calls that are themselves synchronization points or
# thread-safe by contract: not evidence of guarded state.
SYNC_SAFE_METHODS = {"set", "cancel", "wait", "notify", "notify_all",
                     "acquire", "release", "join", "start", "is_set"}
# Constructors whose instances are internally synchronized — attributes
# holding one are exempt from the discipline pass entirely.  deque
# qualifies for its atomic append/pop ends (the outbox/work-list
# pattern); cross-end iteration still deserves a lock, which the pass
# cannot distinguish, so that risk is accepted here.
THREADSAFE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
                    "Event", "Semaphore", "BoundedSemaphore", "Barrier",
                    "local", "deque"}


def queue_maxsize_unbounded(arg: ast.expr) -> bool:
    """stdlib Queue semantics: any literal maxsize <= 0 (0, -1) means
    unbounded.  Negative literals parse as UnaryOp(USub, Constant)."""
    if isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub) \
            and isinstance(arg.operand, ast.Constant) and \
            isinstance(arg.operand.value, (int, float)):
        return True  # any negative literal
    return isinstance(arg, ast.Constant) and \
        isinstance(arg.value, (int, float)) and arg.value <= 0


def _is_lock_ctor(node: ast.expr) -> Optional[str]:
    """threading.Lock() / Lock() / threading.Condition(x) -> kind."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "threading":
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return name if name in LOCK_FACTORIES else None


def _self_attr(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, module: str, path: str, node: ast.ClassDef) -> None:
        self.module = module
        self.path = path
        self.node = node
        self.name = node.name
        self.locks: dict = {}        # attr -> kind (Lock/RLock/Condition)
        self.lock_aliases: dict = {} # property name -> lock attr
        self.sync_safe: set = set()  # attrs holding Queue/Event/... objects
        self.bounded_queues: set = set()  # Queue attrs with maxsize > 0
        self.attr_types: dict = {}   # attr -> ClassName (from __init__)
        self.methods: dict = {}      # name -> FunctionDef
        # Typed concurrency annotations (nomad_tpu/utils/sync.py):
        # Immutable attrs are bound once pre-publication (bare reads fine,
        # ANY later write is a finding); CopySwap attrs are atomically
        # rebound under a lock (bare reads fine, writes must stay locked).
        self.immutable: set = set()
        self.copy_swap: set = set()
        # attr -> [guarded_reads, guarded_writes, bare_reads, bare_writes]
        self.access: dict = {}
        self.first_access: dict = {} # (attr, kind) -> (method, line)


def _marker_of(ann: Optional[ast.expr]) -> Optional[str]:
    """The sync-annotation marker named by an annotation expression:
    ``Immutable`` / ``CopySwap``, bare, subscripted (``Immutable[str]``),
    dotted (``sync.Immutable``), or stringified by
    ``from __future__ import annotations``."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    name = None
    if isinstance(ann, ast.Attribute):
        name = ann.attr
    elif isinstance(ann, ast.Name):
        name = ann.id
    return name if name in ("Immutable", "CopySwap") else None


def _scan_class(info: _ClassInfo) -> None:
    """Find lock attrs, lock-returning properties, and attr types."""
    for item in info.node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[item.name] = item
        elif isinstance(item, ast.AnnAssign) and \
                isinstance(item.target, ast.Name):
            # Class-body declaration: `addr: Immutable`
            marker = _marker_of(item.annotation)
            if marker == "Immutable":
                info.immutable.add(item.target.id)
            elif marker == "CopySwap":
                info.copy_swap.add(item.target.id)
    for meth in info.methods.values():
        for node in ast.walk(meth):
            targets = value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                marker = _marker_of(node.annotation)
                attr = _self_attr(node.target)
                if marker and attr:
                    (info.immutable if marker == "Immutable"
                     else info.copy_swap).add(attr)
                if node.value is None:
                    continue
                targets, value = [node.target], node.value
            if targets is None:
                continue
            kind = _is_lock_ctor(value)
            ctor = None
            if isinstance(value, ast.Call):
                if isinstance(value.func, ast.Name):
                    ctor = value.func.id
                elif isinstance(value.func, ast.Attribute):
                    ctor = value.func.attr
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if kind:
                    info.locks[attr] = kind
                elif ctor in THREADSAFE_CTORS:
                    info.sync_safe.add(attr)
                    if ctor.endswith("Queue") and (
                            value.args or any(
                                kw.arg == "maxsize"
                                for kw in value.keywords)):
                        # A variable maxsize must be assumed bounded.
                        arg = value.args[0] if value.args else next(
                            kw.value for kw in value.keywords
                            if kw.arg == "maxsize")
                        if not queue_maxsize_unbounded(arg):
                            info.bounded_queues.add(attr)
                elif isinstance(value, ast.Call) and \
                        isinstance(value.func, ast.Name):
                    info.attr_types[attr] = value.func.id
    # Conditions wrap their lock: Condition(self._lock) aliases both names
    # to one witness site so `with self._cond` guards `_lock` state too.
    for meth in info.methods.values():
        for node in ast.walk(meth):
            if isinstance(node, ast.Assign) and \
                    _is_lock_ctor(node.value) == "Condition" and \
                    node.value.args:
                inner = _self_attr(node.value.args[0])
                outer = _self_attr(node.targets[0])
                if inner and outer and inner in info.locks:
                    info.lock_aliases[outer] = inner
    # Properties returning a lock: `with obj.lock:` == `with obj._lock:`.
    for name, meth in info.methods.items():
        deco = {d.id for d in meth.decorator_list
                if isinstance(d, ast.Name)}
        if "property" not in deco:
            continue
        for stmt in meth.body:
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                attr = _self_attr(stmt.value)
                if attr in info.locks:
                    info.lock_aliases[name] = attr


def _lock_name_of(info: _ClassInfo, expr: ast.expr) -> Optional[str]:
    """The class lock attr acquired by `with <expr>:`, if any."""
    attr = _self_attr(expr)
    if attr is None:
        return None
    attr = info.lock_aliases.get(attr, attr)
    return attr if attr in info.locks else None


class _MethodVisitor(ast.NodeVisitor):
    """Record every self.attr access in one method with its syntactic
    lock context, plus intra-class call sites (for held-on-entry
    inference)."""

    def __init__(self, info: _ClassInfo, method: str) -> None:
        self.info = info
        self.method = method
        self.depth = 0          # with-lock nesting depth
        self.accesses: list = []  # (attr, write, locked_here, line, rebind)
        self.self_calls: list = []  # (callee, locked_here)

    def _record(self, attr: str, write: bool, line: int,
                rebind: bool = True) -> None:
        """``rebind`` distinguishes true rebinding (``self.x = ...``)
        from receiver mutation (``self.x.append(...)``): both are writes
        for the discipline pass, but only rebinding violates an
        ``Immutable`` annotation."""
        info = self.info
        if attr in info.locks or attr in info.lock_aliases or \
                attr in info.methods or attr in info.sync_safe:
            return
        self.accesses.append((attr, write, self.depth > 0, line,
                              rebind and write))

    # -- lock regions ------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        acquired = sum(1 for item in node.items
                       if _lock_name_of(self.info, item.context_expr))
        self.depth += acquired
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        self.depth -= acquired

    # -- accesses ----------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._target(tgt)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._target(node.target, aug=True)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._target(node.target)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._target(tgt)

    def _target(self, tgt: ast.expr, aug: bool = False) -> None:
        attr = _self_attr(tgt)
        if attr is not None:
            self._record(attr, True, tgt.lineno)
            if aug:
                self._record(attr, False, tgt.lineno)
            return
        if isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)
            if attr is not None:
                self._record(attr, True, tgt.lineno)
                self.visit(tgt.slice)
                return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._target(el)
            return
        self.visit(tgt)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        # self.method(...) — a call site for held-on-entry inference.
        if isinstance(fn, ast.Attribute) and \
                isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                and fn.attr in self.info.methods:
            self.self_calls.append((fn.attr, self.depth > 0))
            for arg in node.args:
                self.visit(arg)
            for kw in node.keywords:
                self.visit(kw.value)
            return
        # self.attr.mutator(...) counts as a write to self.attr.
        if isinstance(fn, ast.Attribute):
            attr = _self_attr(fn.value)
            if attr is not None:
                if fn.attr in MUTATOR_METHODS and \
                        fn.attr not in SYNC_SAFE_METHODS:
                    self._record(attr, True, node.lineno, rebind=False)
                else:
                    self._record(attr, False, node.lineno)
                for arg in node.args:
                    self.visit(arg)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None:
            self._record(attr, isinstance(node.ctx, (ast.Store, ast.Del)),
                         node.lineno)
            return
        self.generic_visit(node)

    # Nested defs run later / on other threads: their accesses are still
    # accesses of this class, but they do NOT inherit the lock context.
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved = self.depth
        self.depth = 0
        for stmt in node.body:
            self.visit(stmt)
        self.depth = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self.depth
        self.depth = 0
        self.visit(node.body)
        self.depth = saved


# ---------------------------------------------------------------------------
# Lock-order graph
# ---------------------------------------------------------------------------

class _OrderVisitor(ast.NodeVisitor):
    """Collect (held-site -> acquired-site) edges and call sites per
    function, for one class method or module function."""

    def __init__(self, analyzer: "_Package", module: str,
                 cls: Optional[_ClassInfo], fn_qual: str) -> None:
        self.an = analyzer
        self.module = module
        self.cls = cls
        self.fn_qual = fn_qual
        self.stack: list = []    # held lock sites, innermost last
        self.direct: set = set() # sites this function acquires directly
        self.edges: list = []    # (outer_site, inner_site, line)
        self.calls: list = []    # (held_sites_tuple, callee_key, line)

    def _site_of(self, expr: ast.expr) -> Optional[str]:
        # with self._lock:
        if self.cls is not None:
            name = _lock_name_of(self.cls, expr)
            if name:
                return f"{self.cls.name}.{name}"
        # with MODULE_LOCK:
        if isinstance(expr, ast.Name) and \
                expr.id in self.an.module_locks.get(self.module, ()):
            return f"{self.module}.{expr.id}"
        # with self.attr.lock / obj.lock — resolve attr type if known.
        if isinstance(expr, ast.Attribute):
            owner = expr.value
            attr_name = expr.attr
            cls_name = None
            if self.cls is not None:
                owner_attr = _self_attr(owner)
                if owner_attr is not None:
                    cls_name = self.cls.attr_types.get(owner_attr)
            if cls_name is not None:
                target = self.an.class_by_name(cls_name)
                if target is not None:
                    alias = target.lock_aliases.get(attr_name, attr_name)
                    if alias in target.locks:
                        return f"{target.name}.{alias}"
            # Unresolvable foreign lock: site keyed by attr name only, so
            # `with mirror.lock:` still participates in ordering.
            if attr_name in ("lock",) or attr_name.endswith("_lock"):
                return f"?.{attr_name}"
        return None

    def visit_With(self, node: ast.With) -> None:
        sites = []
        for item in node.items:
            site = self._site_of(item.context_expr)
            if site is not None:
                if self.stack and self.stack[-1] != site:
                    self.edges.append((self.stack[-1], site,
                                       node.lineno))
                elif self.stack and self.stack[-1] == site:
                    self.edges.append((site, site, node.lineno))
                self.direct.add(site)
                self.stack.append(site)
                sites.append(site)
        for stmt in node.body:
            self.visit(stmt)
        for _ in sites:
            self.stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        key = self._callee_key(node.func)
        if key is not None and self.stack:
            self.calls.append((tuple(self.stack), key, node.lineno))
        self.generic_visit(node)

    def _callee_key(self, fn: ast.expr) -> Optional[str]:
        # self.method()
        if isinstance(fn, ast.Attribute):
            owner_attr = _self_attr(fn.value)
            if isinstance(fn.value, ast.Name) and fn.value.id == "self" \
                    and self.cls is not None:
                return f"{self.cls.name}.{fn.attr}"
            if owner_attr is not None and self.cls is not None:
                cls_name = self.cls.attr_types.get(owner_attr)
                if cls_name:
                    return f"{cls_name}.{fn.attr}"
            # Unknown receiver: devirtualize by method-name uniqueness
            # among lock-holding classes (cheap, and wrong edges only
            # ever ADD cycles for a human to review).  Names shared with
            # builtin container/sync methods are excluded — `d.clear()`
            # must not resolve to SomeClass.clear.
            if fn.attr in MUTATOR_METHODS or fn.attr in SYNC_SAFE_METHODS \
                    or fn.attr in ("get", "keys", "values", "items",
                                   "copy", "close", "run"):
                return None
            owners = self.an.method_owners.get(fn.attr)
            if owners and len(owners) == 1:
                return f"{owners[0]}.{fn.attr}"
            return None
        # module_function()
        if isinstance(fn, ast.Name):
            return f"{self.module}:{fn.id}"
        return None

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # Nested defs (thread targets, callbacks) run with NO lock held.
        saved, self.stack = self.stack, []
        for stmt in node.body:
            self.visit(stmt)
        self.stack = saved

    visit_AsyncFunctionDef = visit_FunctionDef


class _Package:
    def __init__(self) -> None:
        self.classes: list = []
        self.module_locks: dict = {}   # module -> {name: kind}
        self.functions: dict = {}      # callee key -> _OrderVisitor
        self._by_name: dict = {}
        self.method_owners: dict = {}  # method name -> [lock-class names]
        # Set by _order_graph: cycles/self-acquire sites this module's
        # syntactic pass already reported, so the interprocedural pass
        # (blocking.py) reports only what it alone can see.
        self.cycle_sets: set = set()
        self.self_sites: set = set()

    def class_by_name(self, name: str) -> Optional[_ClassInfo]:
        hits = self._by_name.get(name)
        return hits[0] if hits and len(hits) == 1 else None

    def index(self) -> None:
        for info in self.classes:
            self._by_name.setdefault(info.name, []).append(info)
            if info.locks:
                for m in info.methods:
                    owners = self.method_owners.setdefault(m, [])
                    if info.name not in owners:
                        owners.append(info.name)


def _iter_sources(package_dir: str):
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = sorted(d for d in dirs if not d.startswith("__pycache"))
        for fname in sorted(files):
            if fname.endswith(".py"):
                yield os.path.join(root, fname)


def _relpath(path: str, package_dir: str) -> str:
    base = os.path.dirname(os.path.abspath(package_dir))
    return os.path.relpath(os.path.abspath(path), base)


def scan_package(package_dir: str):
    """Parse the tree and index locks/classes once.  Returns
    ``(pkg, trees, error_finding)`` — shared by this module's passes and
    the interprocedural passes in blocking.py, so the lock-site naming
    stays identical across both."""
    pkg = _Package()
    trees = []
    for path in _iter_sources(package_dir):
        with open(path) as fh:
            try:
                tree = ast.parse(fh.read(), filename=path)
            except SyntaxError as e:
                return pkg, trees, Finding(
                    "parse-error", _relpath(path, package_dir),
                    "<module>", str(e), e.lineno or 0)
        rel = _relpath(path, package_dir)
        # Dotted module path, not basename: the package has many
        # same-named files (__init__.py, client.py, config.py) whose
        # locks must stay distinct graph sites.
        parts = os.path.splitext(rel)[0].split(os.sep)
        if parts[-1] == "__init__":
            parts = parts[:-1]
        module = ".".join(parts)
        trees.append((rel, module, tree))
        # Module-level locks.
        for node in tree.body:
            if isinstance(node, ast.Assign):
                kind = _is_lock_ctor(node.value)
                if kind:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            pkg.module_locks.setdefault(
                                module, {})[tgt.id] = kind
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                info = _ClassInfo(module, rel, node)
                _scan_class(info)
                pkg.classes.append(info)
    pkg.index()
    return pkg, trees, None


def analyze_package(package_dir: str, strict: bool = False,
                    scan=None) -> list:
    pkg, trees, err = scan or scan_package(package_dir)
    if err is not None:
        return [err]

    findings: list = []
    findings.extend(_attr_discipline(pkg, strict))
    findings.extend(_module_global_discipline(pkg, trees, strict))
    findings.extend(_order_graph(pkg, trees))
    return findings


def _infer_entry_context(info: _ClassInfo, visitors: dict) -> tuple:
    """Fixpoint inference of per-method entry context.

    ``held``: private methods whose every intra-class call site runs
    with the lock held (the ``_locked``-suffix convention, generalized —
    a suffixed name is trusted even without visible call sites).
    ``ctor_only``: methods reachable only from ``__init__`` — they run
    pre-publication, like ``__init__`` itself.
    """
    callers: dict = {}   # callee -> [(caller, locked_at_site)]
    for name, v in visitors.items():
        for callee, locked in v.self_calls:
            callers.setdefault(callee, []).append((name, locked))

    held: set = {m for m in info.methods
                 if m.endswith("_locked") or m.endswith("Locked")}
    ctor_only: set = set()
    for _ in range(len(info.methods) + 1):
        changed = False
        for m in info.methods:
            sites = callers.get(m, [])
            if m not in ctor_only and m != "__init__" and sites and all(
                    caller == "__init__" or caller in ctor_only
                    for caller, _ in sites):
                ctor_only.add(m)
                changed = True
            # Constructor call sites run pre-publication; they neither
            # satisfy nor veto the locked-on-entry requirement.
            live = [(c, lk) for c, lk in sites
                    if c != "__init__" and c not in ctor_only]
            if m not in held and m.startswith("_") and live and all(
                    locked or caller in held
                    for caller, locked in live):
                held.add(m)
                changed = True
        if not changed:
            break
    return held, ctor_only


def _attr_discipline(pkg: _Package, strict: bool) -> list:
    findings = []
    for info in pkg.classes:
        if not info.locks:
            continue
        visitors: dict = {}
        for meth_name, meth in info.methods.items():
            v = _MethodVisitor(info, meth_name)
            v.visit(meth)
            visitors[meth_name] = v
        held, ctor_only = _infer_entry_context(info, visitors)

        immutable_writes: dict = {}  # attr -> (method, line)
        for meth_name, v in visitors.items():
            entry_held = meth_name in held
            pre_pub = meth_name == "__init__" or meth_name in ctor_only
            for attr, write, locked_here, line, rebind in v.accesses:
                guarded = locked_here or entry_held
                if attr in info.immutable and rebind and not pre_pub:
                    # An Immutable attr is bound once pre-publication;
                    # ANY later write (locked or not) breaks the
                    # annotation's contract that readers may skip the
                    # lock.
                    immutable_writes.setdefault(attr, (meth_name, line))
                slot = info.access.setdefault(attr, [0, 0, 0, 0])
                if pre_pub and not guarded:
                    continue  # no other thread can see the object yet
                idx = (0 if guarded else 2) + (1 if write else 0)
                slot[idx] += 1
                kind = ("guarded" if guarded else "bare",
                        "write" if write else "read")
                info.first_access.setdefault((attr, kind),
                                             (meth_name, line))

        for attr, (meth, line) in sorted(immutable_writes.items()):
            findings.append(Finding(
                "immutable-write", info.path, f"{info.name}.{attr}",
                f"attribute annotated Immutable is written in {meth} "
                "after construction", line))
        for attr, (g_r, g_w, b_r, b_w) in sorted(info.access.items()):
            if g_r + g_w == 0:
                continue  # never guarded: plain attribute
            if attr in info.immutable:
                continue  # immutable-write pass owns this attr
            if b_w:
                meth, line = info.first_access[(attr, ("bare", "write"))]
                guard = info.first_access.get(
                    (attr, ("guarded", "write")),
                    info.first_access.get((attr, ("guarded", "read"))))
                findings.append(Finding(
                    "bare-write", info.path, f"{info.name}.{attr}",
                    f"guarded attribute (locked in {guard[0]}) "
                    f"mutated outside any lock in {meth}", line))
            if strict and b_r and attr not in info.copy_swap:
                meth, line = info.first_access[(attr, ("bare", "read"))]
                findings.append(Finding(
                    "bare-read", info.path, f"{info.name}.{attr}",
                    f"guarded attribute read outside any lock in {meth}",
                    line, severity="info"))
    return findings


def _module_global_discipline(pkg: _Package, trees, strict: bool) -> list:
    """Globals written both inside and outside `with MODULE_LOCK:`."""
    findings = []
    for rel, module, tree in trees:
        locks = pkg.module_locks.get(module)
        if not locks:
            continue
        guarded_writes: dict = {}
        bare_writes: dict = {}

        def walk_fn(fn, depth: int) -> None:
            declared_global: set = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared_global.update(node.names)
            for stmt in fn.body:
                _walk_stmt(stmt, depth, declared_global)

        def _scan_expr(expr, depth: int, globals_: set) -> None:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, (ast.Store, ast.Del)) and \
                        sub.id in globals_:
                    tgt = guarded_writes if depth else bare_writes
                    tgt.setdefault(sub.id, sub.lineno)

        def _walk_stmt(node, depth: int, globals_: set) -> None:
            # Field-aware recursion: nested statements are classified at
            # THEIR depth only — a blanket ast.walk here would rescan a
            # `with LOCK:` body at the enclosing (bare) depth and turn
            # every conditionally-guarded write into a false positive.
            if isinstance(node, ast.With):
                d = depth + sum(
                    1 for it in node.items
                    if isinstance(it.context_expr, ast.Name)
                    and it.context_expr.id in locks)
                for it in node.items:
                    _scan_expr(it.context_expr, depth, globals_)
                for stmt in node.body:
                    _walk_stmt(stmt, d, globals_)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_fn(node, 0)
                return
            for _field, value in ast.iter_fields(node):
                if isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.stmt):
                            _walk_stmt(v, depth, globals_)
                        elif isinstance(v, ast.excepthandler):
                            for stmt in v.body:
                                _walk_stmt(stmt, depth, globals_)
                        elif isinstance(v, ast.expr):
                            _scan_expr(v, depth, globals_)
                elif isinstance(value, ast.stmt):
                    _walk_stmt(value, depth, globals_)
                elif isinstance(value, ast.expr):
                    _scan_expr(value, depth, globals_)

        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_fn(node, 0)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        walk_fn(item, 0)
        for name in sorted(set(guarded_writes) & set(bare_writes)):
            findings.append(Finding(
                "bare-write", rel, f"{module}.{name}",
                "module global written both under and outside "
                f"{module}'s lock", bare_writes[name]))
    return findings


def _order_graph(pkg: _Package, trees) -> list:
    """Build the cross-module lock-order graph; report cycles."""
    visitors: dict = {}
    for rel, module, tree in trees:
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                v = _OrderVisitor(pkg, module, None, f"{module}:{node.name}")
                for stmt in node.body:
                    v.visit(stmt)
                v.rel = rel
                visitors[v.fn_qual] = v
        for cnode in ast.walk(tree):
            if not isinstance(cnode, ast.ClassDef):
                continue
            info = next((c for c in pkg.classes
                         if c.node is cnode), None)
            if info is None:
                continue
            for mname, meth in info.methods.items():
                v = _OrderVisitor(pkg, module, info,
                                  f"{info.name}.{mname}")
                for stmt in meth.body:
                    v.visit(stmt)
                v.rel = rel
                visitors[v.fn_qual] = v

    # Direct + one-level call-propagated edges, to a fixpoint over
    # "locks a function may acquire" (2 rounds covers helper->helper).
    may_acquire: dict = {q: set(v.direct) for q, v in visitors.items()}
    for _ in range(3):
        changed = False
        for q, v in visitors.items():
            for _held, callee, _line in v.calls:
                extra = may_acquire.get(callee)
                if extra and not extra <= may_acquire[q]:
                    may_acquire[q] |= extra
                    changed = True
        if not changed:
            break

    edges: dict = {}
    self_edges: dict = {}
    for q, v in visitors.items():
        for outer, inner, line in v.edges:
            if outer == inner:
                self_edges.setdefault(outer, (v.rel, q, line))
            else:
                edges.setdefault((outer, inner), (v.rel, q, line))
        for held, callee, line in v.calls:
            for inner in may_acquire.get(callee, ()):
                outer = held[-1]
                if outer == inner:
                    self_edges.setdefault(outer, (v.rel, q, line))
                else:
                    edges.setdefault((outer, inner), (v.rel, q, line))

    findings = []
    # Self-nesting of a plain (non-reentrant) Lock: deadlock if both
    # frames ever hit the same instance.
    kind_of: dict = {}
    for info in pkg.classes:
        for attr, kind in info.locks.items():
            kind_of[f"{info.name}.{attr}"] = kind
    for module, locks in pkg.module_locks.items():
        for name, kind in locks.items():
            kind_of[f"{module}.{name}"] = kind
    for site, (rel, q, line) in sorted(self_edges.items()):
        if kind_of.get(site) == "Lock":
            findings.append(Finding(
                "nested-self-acquire", rel, q,
                f"non-reentrant lock {site} may be acquired while "
                f"already held (deadlock if the instances coincide)",
                line))

    # Cycles among distinct sites.
    graph: dict = {}
    for (a, b), meta in edges.items():
        graph.setdefault(a, {})[b] = meta
    for cycle in find_cycles(graph):
        rel, q, line = graph[cycle[0]][cycle[1]]
        findings.append(Finding(
            "lock-cycle", rel, q,
            "lock-order cycle: " + " -> ".join(cycle + (cycle[0],)),
            line))
        pkg.cycle_sets.add(frozenset(cycle))
    pkg.self_sites.update(s for s in self_edges
                          if kind_of.get(s) == "Lock")
    return findings


def find_cycles(graph: dict) -> list:
    """Elementary cycles in a node -> iterable-of-neighbors mapping,
    deduplicated by node set (small graphs).  Shared between the static
    order-graph pass and the runtime LockOrderWitness."""
    cycles: list = []
    seen_sets: set = set()

    def dfs(start, node, path, visited):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(tuple(path))
            elif nxt not in visited and nxt > start:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles
