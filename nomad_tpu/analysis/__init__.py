"""Static analysis: the repo's `go vet` + `-race` analogue.

The reference ships its concurrency story as tooling — `go test -race`
(scripts/test.sh:12-13) and `go vet` on every CI run.  This package is the
Python/JAX equivalent, purpose-built for the two invariant classes this
codebase lives on:

  - **Lock discipline** (`lockcheck`): per-class classification of
    attributes into lock-guarded vs bare, flagging guarded state mutated
    outside any lock (the Go race detector's bread-and-butter bug class),
    plus a cross-module lock-order graph with deadlock-cycle detection.
  - **JAX tracer safety** (`jaxlint`): walks every `jax.jit` kernel and
    its intra-package callees for impurity, tracer concretization and
    traced-value branching — the silent retrace/incorrectness modes that
    would erode kernel parity without ever failing a behavioral test.
  - **Interprocedural concurrency** (`callgraph` + `blocking`): a
    whole-program call graph drives blocking-under-lock detection
    (a lock held across an RPC send, retry sleep, or device round-trip
    three frames down), cross-function lock-order cycles, and
    thread/future/event lifecycle checks; the graph's self-coverage
    (resolved vs dynamic call sites) rides the lint's JSON output so
    blind spots are visible instead of silent.
  - **Runtime sanitizers** (`sanitizers`): a lock-order witness
    (instrumented locks record REAL acquisition chains; observed cycles
    fail the suite) and a jit-recompile sentinel (a kernel retracing past
    its budget fails the test run) cross-check the static results.

Findings are gated through a reviewed allowlist (`LINT_ALLOWLIST.txt` at
the repo root); `nomad-tpu lint` and `tests/test_static_analysis.py` run
the pass over `nomad_tpu/` and fail on any unallowlisted finding.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Iterable, Optional

__all__ = [
    "Finding", "run_lint", "load_allowlist", "partition_findings",
    "default_package_root", "default_allowlist_path",
]


@dataclass(frozen=True)
class Finding:
    """One analyzer finding.

    ``key`` (the allowlist identity) deliberately excludes line numbers so
    entries survive unrelated edits; ``line`` is for humans.
    """

    rule: str         # e.g. "bare-write", "lock-cycle", "traced-branch"
    path: str         # repo-relative file path
    where: str        # Class.attr, Class.method, or function qualname
    message: str
    line: int = 0
    severity: str = "error"   # "error" gates CI; "info" is advisory

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.where}"

    def render(self) -> str:
        loc = f"{self.path}:{self.line}" if self.line else self.path
        return f"{loc}: [{self.rule}] {self.where}: {self.message}"


def default_package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_allowlist_path() -> str:
    root = default_package_root()
    return os.path.join(os.path.dirname(root), "LINT_ALLOWLIST.txt")


def load_allowlist(path: str) -> dict:
    """Parse the allowlist: one ``finding-key # justification`` per line.

    Every entry MUST carry a justification comment — an allowlist is a
    reviewed ledger of accepted risk, not a mute button; entries without
    one are rejected so they can't slip through review.
    """
    entries: dict = {}
    if not os.path.exists(path):
        return entries
    with open(path) as fh:
        for lineno, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            key, sep, why = line.partition("#")
            key = key.strip()
            why = why.strip()
            if not sep or not why:
                raise ValueError(
                    f"{path}:{lineno}: allowlist entry {key!r} has no "
                    f"justification comment (format: 'key  # why')")
            entries[key] = why
    return entries


def run_lint(package_dir: Optional[str] = None,
             strict: bool = False,
             coverage_out: Optional[dict] = None) -> list:
    """Run every static pass over a package tree; returns [Finding].

    The tree is parsed once for lockcheck (``scan_package``) and once
    for the call graph; the interprocedural passes (blocking.py) ride
    both, AFTER lockcheck so its syntactic lock-order results are known
    and not double-reported.  Pass a dict as ``coverage_out`` to receive
    the call graph's self-coverage stats (functions indexed, call sites
    resolved vs dynamic) — the analyzer's own blind spots, surfaced in
    ``nomad-tpu lint --json`` instead of silent.
    """
    from . import (blocking, callgraph, consensuslint, devlint, faultlint,
                   jaxlint, lockcheck)

    package_dir = package_dir or default_package_root()
    if not os.path.isdir(package_dir):
        raise FileNotFoundError(package_dir)
    scan = lockcheck.scan_package(package_dir)
    _pkg, trees, err = scan
    graph = callgraph.CallGraph.build(
        package_dir, parsed=trees if err is None else None)
    findings: list = []
    findings.extend(lockcheck.analyze_package(package_dir, strict=strict,
                                              scan=scan))
    findings.extend(blocking.analyze_package(package_dir, graph=graph,
                                             scan=scan))
    findings.extend(jaxlint.analyze_package(package_dir))
    dev_cov: dict = {}
    findings.extend(devlint.analyze_package(package_dir, graph=graph,
                                            scan=scan,
                                            coverage_out=dev_cov))
    cons_cov: dict = {}
    findings.extend(consensuslint.analyze_package(package_dir, graph=graph,
                                                  scan=scan,
                                                  coverage_out=cons_cov))
    fault_cov: dict = {}
    findings.extend(faultlint.analyze_package(package_dir, graph=graph,
                                              scan=scan,
                                              coverage_out=fault_cov))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if coverage_out is not None:
        coverage_out.update(graph.coverage())
        # The device-plane passes' own self-coverage (kernels found,
        # operands judged placed vs host, transfer sites, hot-path
        # closure size, marker-waived sites) rides the same JSON block.
        coverage_out["devlint"] = dev_cov
        # The consensus-plane passes' self-coverage: apply-closure
        # size, fence targets, and the endpoint read-consistency
        # contract table (ROADMAP item 1's machine-readable input).
        coverage_out["consensuslint"] = cons_cov
        # The failure-plane passes' self-coverage: serving-entry
        # closure size, the boundary→fault-site coverage table (the
        # injectability contract the chaos suite drives), and the
        # retry-closure census.
        coverage_out["faultlint"] = fault_cov
    return findings


def partition_findings(findings: Iterable[Finding], allowlist: dict
                       ) -> tuple[list, list, list]:
    """Split findings into (gating, allowlisted, stale_allowlist_keys).

    ``stale`` entries — allowlist keys matching no current finding — are
    surfaced so the ledger shrinks as real fixes land instead of
    accreting dead waivers.
    """
    gating: list = []
    allowed: list = []
    seen: set = set()
    for f in findings:
        if f.key in allowlist:
            seen.add(f.key)
            allowed.append(f)
        elif f.severity == "error":
            gating.append(f)
    stale = [k for k in allowlist if k not in seen]
    return gating, allowed, stale
