"""Interprocedural call graph over the package AST.

PR 1's analyzers are per-function: they see a ``with self._lock:`` body
but not what the functions *called inside it* do.  This module gives the
other passes the missing edge set — a whole-program call graph with
enough name/attr resolution to follow the package's real call patterns:

  - ``self.method()`` resolved through the enclosing class AND its
    in-package bases (simple MRO walk — ``PipelinedEvalRunner`` calling
    ``self._begin_eval`` resolves into ``BatchEvalRunner``);
  - ``self.attr.method()`` through attribute types inferred from
    ``self.attr = ClassName(...)`` assignments (any method, not just
    ``__init__``) and from ``self.attr: ClassName`` annotations;
  - ``obj.method()`` through local-variable types (``x = ClassName(...)``),
    parameter annotations (``def f(x: ClassName)``), and module-level
    constants (``POLICY = RetryPolicy(...)`` → ``POLICY.call()``);
  - ``module.func()`` / ``from x import f; f()`` through the import
    table, including relative imports;
  - decorator-aware leaves: ``@jax.jit``-decorated functions keep their
    identity, and ``kernel = jax.jit(_impl)`` aliases ``kernel`` to
    ``_impl`` so callers of the wrapper reach the real body.

Nested ``def``s are indexed as their own nodes (``Outer.inner``) and do
NOT contribute their calls to the enclosing function: a thread target or
callback runs on another thread/at another time, so its blocking or
acquisitions are not the creator's.

Resolution is best-effort by design; what matters is that the *misses
are counted*.  ``CallGraph.coverage()`` reports resolved vs dynamic
call sites so the lint's blind spots are visible instead of silent
(surfaced in ``nomad-tpu lint --json``).
"""
from __future__ import annotations

import ast
import builtins
import os
from typing import Iterable, Optional

_BUILTIN_NAMES = frozenset(dir(builtins))

# Wrappers whose call returns the wrapped function unchanged for
# call-graph purposes: `kernel = jax.jit(_impl, ...)` makes `kernel()`
# reach `_impl`.
_TRANSPARENT_WRAPPERS = {"jit", "partial", "lru_cache", "wraps"}


class CallSite:
    """One call expression inside a function body."""

    __slots__ = ("line", "callee", "kind", "text")

    def __init__(self, line: int, callee: Optional[str], kind: str,
                 text: str) -> None:
        self.line = line
        self.callee = callee   # FuncNode key ("mod:Qual") or dotted ext name
        self.kind = kind       # "intra" | "external" | "builtin" | "dynamic"
        self.text = text       # rendered call target, for messages


class FuncNode:
    __slots__ = ("key", "module", "rel", "cls", "qual", "node", "calls",
                 "line")

    def __init__(self, key: str, module: str, rel: str,
                 cls: Optional[str], qual: str, node) -> None:
        self.key = key         # "module:Qual"
        self.module = module
        self.rel = rel         # repo-relative path
        self.cls = cls         # simple class name or None
        self.qual = qual       # "name" / "Class.method" / "Class.m.inner"
        self.node = node
        self.line = node.lineno
        self.calls: list = []  # [CallSite]


class ClassNode:
    __slots__ = ("key", "module", "name", "node", "bases", "methods",
                 "attr_types")

    def __init__(self, key: str, module: str, name: str, node) -> None:
        self.key = key          # "module.Class"
        self.module = module
        self.name = name
        self.node = node
        self.bases: list = []   # base class keys (resolved, in order)
        self.methods: dict = {} # method name -> FuncNode key
        self.attr_types: dict = {}  # attr -> class key or external dotted


class ModuleInfo:
    __slots__ = ("module", "rel", "tree", "imports", "functions", "classes",
                 "global_types", "aliases")

    def __init__(self, module: str, rel: str, tree) -> None:
        self.module = module
        self.rel = rel
        self.tree = tree
        # name -> ("mod", dotted) | ("sym", dotted_module, symbol)
        self.imports: dict = {}
        self.functions: dict = {}    # name -> FuncNode key
        self.classes: dict = {}      # name -> ClassNode key
        self.global_types: dict = {} # NAME -> class key (module constants)
        self.aliases: dict = {}      # name -> FuncNode key (jit wrappers)


def _iter_sources(package_dir: str):
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = sorted(d for d in dirs if not d.startswith("__pycache"))
        for fname in sorted(files):
            if fname.endswith(".py"):
                yield os.path.join(root, fname)


def _module_name(path: str, package_dir: str) -> tuple[str, str]:
    base = os.path.dirname(os.path.abspath(package_dir))
    rel = os.path.relpath(os.path.abspath(path), base)
    parts = os.path.splitext(rel)[0].split(os.sep)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts), rel


def _render(expr: ast.expr) -> str:
    try:
        return ast.unparse(expr)
    except Exception:
        return "<call>"


class CallGraph:
    def __init__(self) -> None:
        self.modules: dict = {}      # dotted -> ModuleInfo
        self.functions: dict = {}    # key -> FuncNode
        self.classes: dict = {}      # "module.Class" -> ClassNode
        self._class_by_name: dict = {}  # simple name -> [class keys]
        self._stats = {"functions": 0, "call_sites": 0, "resolved": 0,
                       "external": 0, "builtin": 0, "dynamic": 0}

    # -- queries -----------------------------------------------------------
    def coverage(self) -> dict:
        out = dict(self._stats)
        sites = out["call_sites"]
        out["resolved_fraction"] = round(
            (out["resolved"] + out["external"] + out["builtin"]) /
            sites, 4) if sites else 1.0
        return out

    def callees(self, key: str) -> Iterable[CallSite]:
        fn = self.functions.get(key)
        return fn.calls if fn is not None else ()

    def class_of(self, key: str) -> Optional[ClassNode]:
        fn = self.functions.get(key)
        if fn is None or fn.cls is None:
            return None
        return self.classes.get(f"{fn.module}.{fn.cls}")

    def resolve_method(self, class_key: str, name: str) -> Optional[str]:
        """Find ``name`` on the class or its in-package bases (MRO-ish
        depth-first, left-to-right)."""
        seen: set = set()
        stack = [class_key]
        while stack:
            ck = stack.pop(0)
            if ck in seen:
                continue
            seen.add(ck)
            cls = self.classes.get(ck)
            if cls is None:
                continue
            hit = cls.methods.get(name)
            if hit is not None:
                return hit
            stack = cls.bases + stack
        return None

    def unique_class(self, name: str) -> Optional[str]:
        hits = self._class_by_name.get(name)
        return hits[0] if hits and len(hits) == 1 else None

    # -- construction ------------------------------------------------------
    @classmethod
    def build(cls, package_dir: str,
              parsed=None) -> "CallGraph":
        """``parsed`` is lockcheck.scan_package's ``trees`` —
        ``[(rel, module, tree)]`` — so one parse of the package serves
        both analyzers; omitted, the tree is read from disk."""
        graph = cls()
        trees = []
        if parsed is not None:
            for rel, module, tree in parsed:
                info = ModuleInfo(module, rel, tree)
                graph.modules[module] = info
                trees.append(info)
        else:
            for path in _iter_sources(package_dir):
                with open(path) as fh:
                    try:
                        tree = ast.parse(fh.read(), filename=path)
                    except SyntaxError:
                        continue  # lockcheck reports parse errors
                module, rel = _module_name(path, package_dir)
                info = ModuleInfo(module, rel, tree)
                graph.modules[module] = info
                trees.append(info)
        for info in trees:
            graph._index_module(info)
        for info in trees:
            graph._resolve_bases(info)
            graph._infer_attr_types(info)
        for info in trees:
            graph._resolve_module(info)
        return graph

    def _index_module(self, info: ModuleInfo) -> None:
        for node in info.tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    info.imports[alias.asname or
                                 alias.name.split(".")[0]] = \
                        ("mod", alias.name)
            elif isinstance(node, ast.ImportFrom):
                target = self._resolve_from(info, node)
                if target is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    info.imports[alias.asname or alias.name] = \
                        ("sym", target, alias.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(info, node, None, node.name)
            elif isinstance(node, ast.ClassDef):
                self._index_class(info, node)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                self._index_global_assign(info, node)
        # Function-level imports (several modules defer heavy imports):
        # indexed flat — shadowing is not worth modeling.
        for node in ast.walk(info.tree):
            if isinstance(node, ast.ImportFrom) and node not in \
                    info.tree.body:
                target = self._resolve_from(info, node)
                if target is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    info.imports.setdefault(
                        alias.asname or alias.name,
                        ("sym", target, alias.name))
            elif isinstance(node, ast.Import) and node not in \
                    info.tree.body:
                for alias in node.names:
                    info.imports.setdefault(
                        alias.asname or alias.name.split(".")[0],
                        ("mod", alias.name))

    def _resolve_from(self, info: ModuleInfo,
                      node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module
        parts = info.module.split(".")
        # level=1 from a module means its package; __init__ modules ARE
        # their package, so they drop one level less.
        is_pkg = info.rel.endswith("__init__.py")
        drop = node.level - (1 if is_pkg else 0)
        if drop > 0:
            parts = parts[:-drop] if drop < len(parts) else []
        base = ".".join(parts)
        if node.module:
            return f"{base}.{node.module}" if base else node.module
        return base or None

    def _index_function(self, info: ModuleInfo, node, cls: Optional[str],
                        qual: str) -> FuncNode:
        key = f"{info.module}:{qual}"
        fn = FuncNode(key, info.module, info.rel, cls, qual, node)
        self.functions[key] = fn
        self._stats["functions"] += 1
        if cls is None and "." not in qual:
            info.functions[node.name] = key
        # Nested defs become their own nodes (direct children only; each
        # recursion level indexes its own).
        for child in _child_defs(node):
            self._index_function(info, child, cls,
                                 f"{qual}.{child.name}")
        return fn

    def _index_class(self, info: ModuleInfo, node: ast.ClassDef) -> None:
        ckey = f"{info.module}.{node.name}"
        cnode = ClassNode(ckey, info.module, node.name, node)
        self.classes[ckey] = cnode
        self._class_by_name.setdefault(node.name, []).append(ckey)
        info.classes[node.name] = ckey
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = self._index_function(info, item, node.name,
                                          f"{node.name}.{item.name}")
                cnode.methods[item.name] = fn.key
            elif isinstance(item, ast.ClassDef):
                self._index_class(info, item)  # nested class: flat index

    def _index_global_assign(self, info: ModuleInfo,
                             node: ast.Assign) -> None:
        call = node.value
        fn = call.func
        # `kernel = jax.jit(_impl)` / `f = partial(g, ...)`: alias.
        wrapper = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if wrapper in _TRANSPARENT_WRAPPERS and call.args and \
                isinstance(call.args[0], ast.Name):
            inner = call.args[0].id
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    info.aliases[tgt.id] = f"{info.module}:{inner}"
            return
        # `POLICY = RetryPolicy(...)`: module constant with a known type.
        ctor = fn.id if isinstance(fn, ast.Name) else None
        if ctor:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    info.global_types[tgt.id] = ("name", ctor)

    def _resolve_bases(self, info: ModuleInfo) -> None:
        for name, ckey in info.classes.items():
            cnode = self.classes[ckey]
            for base in cnode.node.bases:
                bkey = self._class_key_of_expr(info, base)
                if bkey is not None:
                    cnode.bases.append(bkey)

    def _class_key_of_expr(self, info: ModuleInfo,
                           expr: ast.expr) -> Optional[str]:
        """Resolve a class-reference expression to a ClassNode key."""
        if isinstance(expr, ast.Name):
            return self._class_key_of_name(info, expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            imp = info.imports.get(expr.value.id)
            if imp and imp[0] == "mod":
                return self._lookup_class(imp[1], expr.attr)
        return None

    def _class_key_of_name(self, info: ModuleInfo,
                           name: str) -> Optional[str]:
        if name in info.classes:
            return info.classes[name]
        imp = info.imports.get(name)
        if imp and imp[0] == "sym":
            hit = self._lookup_class(imp[1], imp[2])
            if hit is not None:
                return hit
        return self.unique_class(name)

    def _lookup_class(self, module: str, name: str) -> Optional[str]:
        target = self.modules.get(module)
        if target is not None and name in target.classes:
            return target.classes[name]
        # Re-export through a package __init__: chase one level.
        if target is not None:
            imp = target.imports.get(name)
            if imp and imp[0] == "sym":
                deeper = self.modules.get(imp[1])
                if deeper is not None and imp[2] in deeper.classes:
                    return deeper.classes[imp[2]]
        return None

    def _infer_attr_types(self, info: ModuleInfo) -> None:
        """self.attr = ClassName(...) / self.attr: ClassName /
        self.attr = annotated_param — from any method, so
        lazily-constructed and injected collaborators resolve too."""
        for ckey in info.classes.values():
            cnode = self.classes[ckey]
            for meth in cnode.node.body:
                if not isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                param_types: dict = {}
                margs = meth.args
                for a in list(margs.posonlyargs) + list(margs.args) + \
                        list(margs.kwonlyargs):
                    if a.annotation is not None:
                        hit = self._class_key_of_expr(
                            info, _unquote(a.annotation))
                        if hit is not None:
                            param_types[a.arg] = hit
                for node in ast.walk(meth):
                    target = value = ann = None
                    if isinstance(node, ast.Assign):
                        target, value = node.targets[0], node.value
                    elif isinstance(node, ast.AnnAssign):
                        target, value, ann = node.target, node.value, \
                            node.annotation
                    else:
                        continue
                    attr = _self_attr(target)
                    if attr is None:
                        continue
                    hit = self._value_type(info, value)
                    if hit is None and isinstance(value, ast.Name):
                        hit = param_types.get(value.id)
                    if hit is None and ann is not None:
                        hit = self._class_key_of_expr(info,
                                                      _unquote(ann))
                    if hit is not None:
                        cnode.attr_types.setdefault(attr, hit)

    def _value_type(self, info: ModuleInfo,
                    value: Optional[ast.expr]) -> Optional[str]:
        """The class key a value expression constructs or references:
        ``ClassName(...)``, ``x if c else GLOBAL`` (either arm), or a
        typed module constant (``GLOBAL_BREAKER`` imported from a module
        whose top level assigns it a known constructor)."""
        if value is None:
            return None
        if isinstance(value, ast.Call):
            return self._class_key_of_expr(info, value.func)
        if isinstance(value, ast.IfExp):
            return self._value_type(info, value.body) or \
                self._value_type(info, value.orelse)
        if isinstance(value, ast.Name):
            g = info.global_types.get(value.id)
            if g is not None:
                return self._class_key_of_name(info, g[1])
            imp = info.imports.get(value.id)
            if imp and imp[0] == "sym":
                target = self.modules.get(imp[1])
                if target is not None:
                    g = target.global_types.get(imp[2])
                    if g is not None:
                        return self._class_key_of_name(target, g[1])
        return None

    # -- call resolution ---------------------------------------------------
    def _resolve_module(self, info: ModuleInfo) -> None:
        for fn in list(self.functions.values()):
            if fn.module != info.module:
                continue
            _FunctionResolver(self, info, fn).run()

    def resolve_call(self, info: ModuleInfo, cls_key: Optional[str],
                     local_types: dict, fn_expr: ast.expr
                     ) -> tuple[Optional[str], str]:
        """Resolve one call's target.  Returns (callee, kind) where
        ``callee`` is a FuncNode key for kind="intra", a dotted name for
        "external"/"builtin", and None for "dynamic"."""
        # f(...)
        if isinstance(fn_expr, ast.Name):
            name = fn_expr.id
            if name in info.aliases:
                return info.aliases[name], "intra"
            if name in info.functions:
                return info.functions[name], "intra"
            if name in info.classes:
                ctor = self.resolve_method(info.classes[name], "__init__")
                return (ctor, "intra") if ctor else \
                    (info.classes[name], "intra-class")
            imp = info.imports.get(name)
            if imp is not None:
                if imp[0] == "sym":
                    target = self.modules.get(imp[1])
                    if target is not None:
                        if imp[2] in target.functions:
                            return target.functions[imp[2]], "intra"
                        if imp[2] in target.classes:
                            ck = target.classes[imp[2]]
                            ctor = self.resolve_method(ck, "__init__")
                            return (ctor, "intra") if ctor else \
                                (ck, "intra-class")
                        if imp[2] in target.aliases:
                            return target.aliases[imp[2]], "intra"
                        # chase one re-export level
                        deep = target.imports.get(imp[2])
                        if deep and deep[0] == "sym":
                            d = self.modules.get(deep[1])
                            if d is not None and deep[2] in d.functions:
                                return d.functions[deep[2]], "intra"
                    return f"{imp[1]}.{imp[2]}", "external"
                return f"{imp[1]}.{name}", "external"
            if name in _BUILTIN_NAMES:
                return name, "builtin"
            return None, "dynamic"

        if not isinstance(fn_expr, ast.Attribute):
            return None, "dynamic"
        owner = fn_expr.value
        meth = fn_expr.attr

        # self.method(...) / self.attr.method(...)
        s_attr = _self_attr(owner)
        if isinstance(owner, ast.Name) and owner.id == "self" and \
                cls_key is not None:
            hit = self.resolve_method(cls_key, meth)
            return (hit, "intra") if hit else (None, "dynamic")
        if s_attr is not None and cls_key is not None:
            cnode = self.classes.get(cls_key)
            tkey = self._attr_type(cls_key, s_attr) if cnode else None
            if tkey is not None:
                hit = self.resolve_method(tkey, meth)
                if hit is not None:
                    return hit, "intra"
            return None, "dynamic"

        if isinstance(owner, ast.Name):
            # module.func(...)
            imp = info.imports.get(owner.id)
            if imp is not None and imp[0] == "mod":
                target = self.modules.get(imp[1])
                if target is not None:
                    if meth in target.functions:
                        return target.functions[meth], "intra"
                    if meth in target.classes:
                        ck = target.classes[meth]
                        ctor = self.resolve_method(ck, "__init__")
                        return (ctor, "intra") if ctor else \
                            (ck, "intra-class")
                return f"{imp[1]}.{meth}", "external"
            # typed local / module constant
            tkey = local_types.get(owner.id)
            if tkey is None:
                g = info.global_types.get(owner.id)
                if g is not None:
                    tkey = self._class_key_of_name(info, g[1])
            if tkey is not None:
                if isinstance(tkey, str) and tkey in self.classes:
                    hit = self.resolve_method(tkey, meth)
                    if hit is not None:
                        return hit, "intra"
                elif isinstance(tkey, str):
                    return f"{tkey}.{meth}", "external"
            return None, "dynamic"
        # str constant receiver: ", ".join(...) et al.
        if isinstance(owner, ast.Constant):
            return f"{type(owner.value).__name__}.{meth}", "builtin"
        return None, "dynamic"

    def _attr_type(self, cls_key: str, attr: str) -> Optional[str]:
        """Attr type through the class and its bases."""
        seen: set = set()
        stack = [cls_key]
        while stack:
            ck = stack.pop(0)
            if ck in seen:
                continue
            seen.add(ck)
            cnode = self.classes.get(ck)
            if cnode is None:
                continue
            hit = cnode.attr_types.get(attr)
            if hit is not None:
                return hit
            stack = cnode.bases + stack
        return None


def _child_defs(fn_node) -> list:
    """Function defs nested DIRECTLY inside ``fn_node`` (not inside a
    deeper def)."""
    out: list = []

    def walk(node) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            elif not isinstance(child, (ast.Lambda, ast.ClassDef)):
                walk(child)

    walk(fn_node)
    return out


def _self_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _unquote(ann: ast.expr) -> ast.expr:
    """Annotations may be strings under `from __future__ import
    annotations`, Optional[X] / X | None unions, or marker subscripts
    (Immutable[str]); peel down to the class-reference expression."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return ann
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        left, right = ann.left, ann.right
        pick = right if (isinstance(left, ast.Constant) and
                         left.value is None) else left
        return _unquote(pick)
    if isinstance(ann, ast.Subscript):
        if isinstance(ann.value, ast.Name) and \
                ann.value.id == "Optional":
            return _unquote(ann.slice)
        return ann.value
    return ann


class _FunctionResolver(ast.NodeVisitor):
    """Collect + resolve every call in ONE function body (nested defs
    excluded — they are their own nodes)."""

    def __init__(self, graph: CallGraph, info: ModuleInfo,
                 fn: FuncNode) -> None:
        self.graph = graph
        self.info = info
        self.fn = fn
        self.cls_key = f"{fn.module}.{fn.cls}" if fn.cls else None
        self.local_types: dict = {}

    def run(self) -> None:
        node = self.fn.node
        # Parameter annotations seed local types.
        args = node.args
        for a in list(args.posonlyargs) + list(args.args) + \
                list(args.kwonlyargs):
            if a.annotation is not None:
                hit = self.graph._class_key_of_expr(
                    self.info, _unquote(a.annotation))
                if hit is not None:
                    self.local_types[a.arg] = hit
        for stmt in node.body:
            self.visit(stmt)

    # Nested defs/lambdas: skip (indexed separately).
    def visit_FunctionDef(self, node) -> None:
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node) -> None:
        pass

    def visit_Assign(self, node: ast.Assign) -> None:
        # x = ClassName(...)  →  local type
        if isinstance(node.value, ast.Call):
            hit = self.graph._class_key_of_expr(self.info,
                                                node.value.func)
            if hit is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self.local_types[tgt.id] = hit
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if isinstance(node.target, ast.Name):
            hit = self.graph._class_key_of_expr(
                self.info, _unquote(node.annotation))
            if hit is not None:
                self.local_types[node.target.id] = hit
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        callee, kind = self.graph.resolve_call(
            self.info, self.cls_key, self.local_types, node.func)
        stats = self.graph._stats
        stats["call_sites"] += 1
        if kind == "intra":
            stats["resolved"] += 1
        elif kind == "intra-class":
            # Constructor of an in-package class with no __init__ —
            # resolved for coverage purposes, nothing to walk into.
            stats["resolved"] += 1
            callee, kind = None, "dynamic"
        elif kind in ("external", "builtin"):
            stats[kind] += 1
        else:
            stats["dynamic"] += 1
        self.fn.calls.append(CallSite(node.lineno, callee, kind,
                                      _render(node.func)))
        self.generic_visit(node)
