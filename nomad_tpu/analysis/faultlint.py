"""Failure-plane lint: deadline propagation, fault-injectability
coverage, and retry safety.

The disciplines this package bets its failure behaviour on already
exist in code — PR 3's sixteen ``faultinject`` sites + ``RetryPolicy``,
PR 6's ``_deadline``/``_abs_deadline`` admission envelope — but nothing
*proved* that new code keeps them: one unbounded wait on a request path
is how a recovery spike becomes a sustained congestion state
(metastable failure), and one uninjectable I/O edge is a failure mode
no chaos plan can ever rehearse.  Three passes ride the PR-4
interprocedural call graph, same shape as devlint/consensuslint:

**Deadline propagation** — from every RPC-serving entry (the
``Endpoints`` handler table, minus the heartbeat/liveness lane) and
every worker/applier/committer loop, the reachable closure is walked
and every blocking wait primitive (``Event``/``Condition`` ``wait`` /
``wait_for``, ``Future.result``/``.wait``, blocking ``queue.get``,
thread ``join``) must carry a timeout:

  - ``unbounded-wait``: a wait with no timeout (or an explicit
    ``timeout=None``) reachable from a request-serving entry.  The
    finding renders the entry→wait call chain.
  - ``deadline-drop``: a function that demonstrably *handles* the
    budget (calls ``restamp_forward`` / ``absolute_deadline`` /
    ``remaining`` / ``stamp_arrival``) and then blocks without clipping
    to it — including the forwarding-transport form: a body that
    re-bases the envelope with ``restamp_forward`` and then invokes a
    ``conn_pool``/``rpc`` ``.call(...)`` without a ``timeout=``, so the
    hop waits the transport default instead of the caller's remaining
    budget.

Socket/device primitives are deliberately NOT pass-1 roots: sockets are
``settimeout``-governed (the runtime ``BudgetWitnessSanitizer`` covers
that plane) and device round-trips are devlint's domain.

**Fault-injectability coverage** — the blocking/I-O root inventory
(socket ops, TLS handshake, dial, select, subprocess, device
dispatch/collect, fsync/replace) is intersected with ``faultinject``
consultation (``fire``/``fire_rpc`` with a literal site name):

  - ``uninjectable-io``: an I/O boundary function with no consulted
    site on its call path (itself, a caller — including the function
    that arms it as a thread target — or a callee).
  - ``dead-site``: a site registered in ``SITES`` that no live code
    consults.

The full boundary→site coverage table ships in ``nomad-tpu lint
-json`` (``coverage.faultlint.boundaries``) and the gate asserts every
boundary is covered or carries a reviewed waiver.

**Retry safety** — closures handed to ``RetryPolicy.call`` (and the
queued-flush re-send paths) are taint-checked for non-idempotent state
mutation: accumulation (``+=`` / ``.append`` / ``.extend`` / ``.add`` /
``.insert``) on state that outlives the attempt, without a fencing
token (``token`` / ``fence`` / ``modify_index`` reference) and without
a newest-wins replacement (``.clear()`` + ``.update()`` on the same
receiver).  Rule ``retry-unsafe``.  The same rule covers the shed
discipline: a committed-state applier (consensuslint's apply surface)
must never reach a load-shed path — broker enqueues inside the apply
closure must pass ``force=True``, and no apply-closure function may
call a function that raises ``ErrOverloaded`` (a replayed log entry
that gets shed is a lost committed write).

Reachability is resolved-edges-only (the call graph's documented
approximation): dynamic attribute chains (``self.server.*`` on
unannotated params) do not propagate, which is why the loop surfaces
are classified as entries directly.  Deliberate exceptions carry
``# faultlint-ok(<rule>): <why>`` markers (devlint grammar: inline
waives the line, a comment block waives the block and the first code
line after it); markers with no justification text do not waive, and
waived sites are counted in the coverage block.
"""
from __future__ import annotations

import ast
import os
import re

from typing import Optional

from . import Finding
from .callgraph import CallGraph
from .jaxlint import _dotted
from .blocking import _kwarg, _is_false, _QUEUE_RECEIVER_RE, \
    _THREAD_RECEIVER_RE
from .consensuslint import _snake, _direct_body, _endpoint_tables, \
    _is_apply_root

_MARKER_RE = re.compile(r"#\s*faultlint-ok\((?P<rule>[a-z-]+)\)\s*:\s*\S")

# -- pass 1: deadline propagation --------------------------------------------

# Loop surfaces that serve admitted work without going through the
# endpoint table: dequeue→schedule workers, the plan applier, and the
# commit pipeline.  Their run loops are entries in their own right.
_LOOP_CLASS_RE = re.compile(r"(worker|applier|committer)", re.IGNORECASE)

# Calls that mark a function as budget-handling: it touched the
# _deadline/_abs_deadline envelope, so an unbounded wait in the same
# body is a *drop*, not mere ignorance.
_BUDGET_CALLS = frozenset({
    "restamp_forward", "absolute_deadline", "remaining", "stamp_arrival",
})

# -- pass 2: fault-injectability ---------------------------------------------

# Attribute-call method names that ARE an I/O boundary.
_IO_METHOD_KINDS = {
    "sendall": "network", "recv": "network", "recvfrom": "network",
    "accept": "network", "connect": "network", "wrap_socket": "network",
    "communicate": "subprocess",
    "dispatch_device": "device", "collect_device": "device",
}
# External dotted callables that are boundaries.
_IO_EXTERNAL_KINDS = {
    ("socket", "create_connection"): "network",
    ("select", "select"): "network",
    ("subprocess", "run"): "subprocess",
    ("subprocess", "call"): "subprocess",
    ("subprocess", "check_call"): "subprocess",
    ("subprocess", "check_output"): "subprocess",
    ("subprocess", "Popen"): "subprocess",
    ("os", "fsync"): "disk",
    ("os", "replace"): "disk",
}

# -- pass 3: retry safety -----------------------------------------------------

_ACCUM_METHODS = frozenset({"append", "extend", "add", "insert"})
_FENCE_NAME_RE = re.compile(r"(token|fence|modify_index)", re.IGNORECASE)


# -- markers (devlint grammar, faultlint-ok spelling) -------------------------

def _load_markers(package_dir: str, rels) -> dict:
    """(rel, line) -> {rule, ...} for every justified faultlint-ok
    marker (same propagation rules as devlint._load_markers)."""
    base = os.path.dirname(os.path.abspath(package_dir))
    out: dict = {}
    for rel in rels:
        path = os.path.join(base, rel)
        try:
            with open(path) as fh:
                lines = fh.read().splitlines()
        except OSError:
            continue
        for i, text in enumerate(lines, 1):
            for m in _MARKER_RE.finditer(text):
                rule = m.group("rule")
                out.setdefault((rel, i), set()).add(rule)
                if not text.lstrip().startswith("#"):
                    continue
                j = i + 1
                while j <= len(lines) and \
                        lines[j - 1].lstrip().startswith("#"):
                    out.setdefault((rel, j), set()).add(rule)
                    j += 1
                if j <= len(lines) and lines[j - 1].strip():
                    out.setdefault((rel, j), set()).add(rule)
    return out


def _waived(markers: dict, rel: str, line: int, rule: str) -> bool:
    return rule in markers.get((rel, line), ())


# -- shared helpers -----------------------------------------------------------

class _FnFacts:
    """One direct-body walk per function, shared by all three passes."""

    __slots__ = ("calls", "raises_overloaded")

    def __init__(self, fn) -> None:
        # [(ast.Call, dotted-or-None)]
        self.calls: list = []
        self.raises_overloaded = False
        for n in _direct_body(fn.node):
            if isinstance(n, ast.Call):
                self.calls.append((n, _dotted(n.func)))
            elif isinstance(n, ast.Raise) and n.exc is not None:
                d = _dotted(n.exc.func if isinstance(n.exc, ast.Call)
                            else n.exc)
                if d and "Overloaded" in d[-1]:
                    self.raises_overloaded = True


def _prepass(graph: CallGraph) -> dict:
    return {key: _FnFacts(fn) for key, fn in graph.functions.items()}


def _recv_text(call: ast.Call) -> str:
    if not isinstance(call.func, ast.Attribute):
        return ""
    try:
        return ast.unparse(call.func.value)
    except Exception:
        return ""


def _timeout_expr(call: ast.Call, pos: int):
    """The timeout argument: positional index ``pos`` or ``timeout=``."""
    if len(call.args) > pos:
        return call.args[pos]
    return _kwarg(call, "timeout")


def _is_none_expr(e) -> bool:
    return e is None or (isinstance(e, ast.Constant) and e.value is None)


def _wait_root(call: ast.Call) -> Optional[tuple]:
    """``(label, bounded)`` when the call is a pass-1 wait primitive.

    Boundedness is syntactic: a timeout expression that is present and
    not the ``None`` literal counts as bounded (the runtime witness
    catches a variable that evaluates to None).
    """
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    name = f.attr
    if name == "wait":
        return ("blocking wait", not _is_none_expr(_timeout_expr(call, 0)))
    if name == "wait_for":
        return ("blocking wait", not _is_none_expr(_timeout_expr(call, 1)))
    if name == "result":
        return ("Future.result", not _is_none_expr(_timeout_expr(call, 0)))
    recv = _recv_text(call).lower()
    base = recv.rsplit(".", 1)[-1]
    if name == "join":
        if not (_THREAD_RECEIVER_RE.search(base) or "thread" in recv):
            return None
        return ("Thread.join", not _is_none_expr(_timeout_expr(call, 0)))
    if name == "get":
        if not _QUEUE_RECEIVER_RE.search(base):
            return None
        block = call.args[0] if call.args else _kwarg(call, "block")
        if _is_false(block):
            return ("queue.get", True)
        t = call.args[1] if len(call.args) > 1 else _kwarg(call, "timeout")
        return ("queue.get", not _is_none_expr(t))
    return None


def _callers_map(graph: CallGraph, facts: dict) -> dict:
    """Reverse resolved-intra edges, plus ``Thread(target=self.x)``
    arming edges (the thread body is 'called by' the armer) — the
    consensuslint fencing-pass relation."""
    callers: dict = {}
    for key, fn in graph.functions.items():
        for cs in fn.calls:
            if cs.kind == "intra" and cs.callee in graph.functions:
                callers.setdefault(cs.callee, set()).add(key)
        cls_node = graph.class_of(key)
        if cls_node is None:
            continue
        for n, _d in facts[key].calls:
            for kw in n.keywords:
                if kw.arg != "target":
                    continue
                d = _dotted(kw.value)
                if d and len(d) == 2 and d[0] == "self":
                    callee = graph.resolve_method(cls_node.key, d[1])
                    if callee is not None:
                        callers.setdefault(callee, set()).add(key)
    return callers


def _budget_aware(ff: _FnFacts) -> bool:
    return any(d and d[-1] in _BUDGET_CALLS for _n, d in ff.calls)


def _heartbeat_lane(graph: CallGraph) -> set:
    """Full RPC names in the liveness lane: the ``HEARTBEAT_LANE``
    module constant (overload.py), string constants only.  Module
    constants are top-level statements, so only the tree's direct body
    is scanned."""
    lane: set = set()
    for info in graph.modules.values():
        for n in info.tree.body:
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "HEARTBEAT_LANE"
                    for t in n.targets):
                for c in ast.walk(n.value):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, str):
                        lane.add(c.value)
    return lane


def _serving_entries(graph: CallGraph) -> tuple:
    """``(entries dict key->label, exempt count)``: endpoint handlers
    (minus the heartbeat/liveness lane) plus worker/applier/committer
    run loops."""
    entries: dict = {}
    exempt = 0
    found = _endpoint_tables(graph)
    if found is not None:
        _module, cls, services, _consistent = found
        lane = _heartbeat_lane(graph)
        for svc, methods in sorted(services.items()):
            for m in methods:
                full = f"{svc}.{m}"
                if full in lane or "heartbeat" in full.lower():
                    exempt += 1
                    continue
                key = graph.resolve_method(cls.key, f"{svc.lower()}_{_snake(m)}")
                if key is not None:
                    entries[key] = f"rpc:{full}"
    for key, fn in sorted(graph.functions.items()):
        if fn.cls is None or fn.qual.count(".") > 1:
            continue
        last = fn.qual.split(".")[-1]
        if last in ("run", "_run") and _LOOP_CLASS_RE.search(fn.cls):
            entries.setdefault(key, f"loop:{fn.qual}")
    return entries, exempt


def _deadline_pass(graph: CallGraph, facts: dict, emit,
                   cov: dict) -> None:
    entries, exempt = _serving_entries(graph)
    closure: set = set(entries)
    parents: dict = {}
    frontier = list(entries)
    while frontier:
        key = frontier.pop()
        for cs in graph.functions[key].calls:
            if cs.kind != "intra" or cs.callee not in graph.functions:
                continue
            if cs.callee in closure:
                continue
            closure.add(cs.callee)
            parents[cs.callee] = key
            frontier.append(cs.callee)

    def chain(key: str) -> str:
        path = [key]
        while path[-1] in parents:
            path.append(parents[path[-1]])
        quals = [graph.functions[k].qual for k in reversed(path)]
        return " -> ".join(quals)

    wait_sites = unbounded = 0
    for key in sorted(closure):
        fn = graph.functions[key]
        aware = _budget_aware(facts[key])
        for n, _d in facts[key].calls:
            hit = _wait_root(n)
            if hit is None:
                continue
            label, bounded = hit
            wait_sites += 1
            if bounded:
                continue
            unbounded += 1
            via = chain(key)
            if aware:
                emit("deadline-drop", fn.rel, f"{fn.qual}[{label}]",
                     f"function handles the deadline envelope but this "
                     f"{label} has no timeout ({via}) — the budget is "
                     f"dropped on the floor at the wait", n.lineno)
            else:
                emit("unbounded-wait", fn.rel, f"{fn.qual}[{label}]",
                     f"{label} with no timeout on a request-serving "
                     f"path ({via}) — one stuck wait pins the serving "
                     f"thread past every caller deadline", n.lineno)

    # Transport form of deadline-drop: a body that re-bases the
    # envelope (restamp_forward) and then forwards over the pool/rpc
    # transport without clipping the transport wait to the re-based
    # budget.  Package-wide: the conn-pool receiver is a dynamic
    # attribute chain, so closure membership can't see it.
    drops = 0
    for key in sorted(graph.functions):
        fn = graph.functions[key]
        ff = facts[key]
        if not any(d and d[-1] == "restamp_forward" for _n, d in ff.calls):
            continue
        for n, _d in ff.calls:
            if not isinstance(n.func, ast.Attribute) or \
                    n.func.attr != "call":
                continue
            recv = _recv_text(n).lower()
            if not ("pool" in recv or "rpc" in recv or "conn" in recv):
                continue
            if _kwarg(n, "timeout") is not None or len(n.args) >= 4:
                continue
            drops += 1
            emit("deadline-drop", fn.rel, f"{fn.qual}[forward]",
                 "forwarding hop re-bases the budget (restamp_forward) "
                 "but the transport call has no timeout= — the hop "
                 "waits the transport default, not the caller's "
                 "remaining envelope", n.lineno)

    cov["entries"] = len(entries)
    cov["entries_exempt_liveness"] = exempt
    cov["entry_closure"] = len(closure)
    cov["wait_sites"] = wait_sites
    cov["unbounded_waits"] = unbounded
    cov["transport_drops"] = drops


# -- pass 2 -------------------------------------------------------------------

def _registered_sites(graph: CallGraph) -> tuple:
    """``(ordered site names, rel, line)`` from the ``SITES = (...)``
    string-tuple assignment (a top-level module constant);
    ``([], None, 0)`` when absent."""
    for module, info in sorted(graph.modules.items()):
        for n in info.tree.body:
            if not (isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "SITES"
                    for t in n.targets)):
                continue
            if not isinstance(n.value, (ast.Tuple, ast.List)):
                continue
            elts = n.value.elts
            if elts and all(isinstance(e, ast.Constant) and
                            isinstance(e.value, str) for e in elts):
                rel = os.path.join(*module.split(".")) + ".py"
                return [e.value for e in elts], rel, n.lineno
    return [], None, 0


def _consults(ff: _FnFacts) -> set:
    """Site names this function consults (fire/fire_rpc with a literal
    site)."""
    out: set = set()
    for n, d in ff.calls:
        if d is None or d[-1] not in ("fire", "fire_rpc"):
            continue
        if n.args and isinstance(n.args[0], ast.Constant) and \
                isinstance(n.args[0].value, str):
            out.add(n.args[0].value)
    return out


def _io_roots(ff: _FnFacts) -> list:
    """``(kind, what, line)`` I/O boundary roots in the direct body."""
    roots: list = []
    for n, d in ff.calls:
        if isinstance(n.func, ast.Attribute):
            kind = _IO_METHOD_KINDS.get(n.func.attr)
            if kind is not None:
                roots.append((kind, f".{n.func.attr}()", n.lineno))
                continue
        if d is not None and len(d) >= 2:
            kind = _IO_EXTERNAL_KINDS.get(tuple(d[-2:]))
            if kind is not None:
                roots.append((kind, ".".join(d[-2:]) + "()", n.lineno))
    return roots


def _injectability_pass(graph: CallGraph, facts: dict, emit, cov: dict,
                        markers: dict, waived_sites: set) -> None:
    sites, sites_rel, sites_line = _registered_sites(graph)
    consults: dict = {k: _consults(ff) for k, ff in facts.items()}
    consults = {k: v for k, v in consults.items() if v}

    site_consults: dict = {s: 0 for s in sites}
    for v in consults.values():
        for s in v:
            if s in site_consults:
                site_consults[s] += 1
            else:
                site_consults[s] = site_consults.get(s, 0) + 1

    callers = _callers_map(graph, facts)
    callees: dict = {}
    for key, fn in graph.functions.items():
        for cs in fn.calls:
            if cs.kind == "intra" and cs.callee in graph.functions:
                callees.setdefault(key, set()).add(cs.callee)

    def reach(key: str, edges: dict) -> set:
        seen = {key}
        frontier = [key]
        while frontier:
            k = frontier.pop()
            for nxt in edges.get(k, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return seen

    boundaries: list = []
    covered = waived = 0
    for key in sorted(graph.functions):
        fn = graph.functions[key]
        roots = _io_roots(facts[key])
        if not roots:
            continue
        covered_by: Optional[str] = None
        own = consults.get(key)
        if own:
            covered_by = sorted(own)[0]
        else:
            related = reach(key, callers) | reach(key, callees)
            hits = sorted(s for k in related
                          for s in consults.get(k, ()))
            if hits:
                covered_by = hits[0]
        # One row (and at most one finding) per function+kind; the
        # first root line anchors it.
        seen_kinds: set = set()
        for kind, what, line in roots:
            if kind in seen_kinds:
                continue
            seen_kinds.add(kind)
            row_waived = covered_by is None and \
                _waived(markers, fn.rel, line, "uninjectable-io")
            boundaries.append({
                "function": fn.qual, "path": fn.rel, "line": line,
                "kind": kind, "root": what,
                "covered_by": covered_by, "waived": row_waived,
            })
            if covered_by is not None:
                covered += 1
                continue
            if row_waived:
                waived += 1
            emit("uninjectable-io", fn.rel, f"{fn.qual}[{kind}]",
                 f"{kind} boundary ({what}) with no consulted "
                 f"faultinject site on its call path — this edge's "
                 f"failure modes can never be rehearsed by a chaos "
                 f"plan", line)

    dead = []
    for s in sites:
        if site_consults.get(s, 0) == 0:
            dead.append(s)
            emit("dead-site", sites_rel or "", s,
                 f"fault site {s!r} is registered in SITES but no "
                 f"live code consults it — plans targeting it "
                 f"silently do nothing", sites_line)

    total = len(boundaries)
    cov["sites"] = {s: site_consults.get(s, 0) for s in sites}
    cov["dead_sites"] = dead
    cov["boundaries"] = boundaries
    cov["boundary_count"] = total
    cov["boundaries_covered"] = covered
    cov["boundaries_waived"] = waived
    cov["covered_fraction"] = (
        (covered + waived) / total if total else 1.0)


# -- pass 3 -------------------------------------------------------------------

def _resolve_closure_arg(graph: CallGraph, fn, call: ast.Call):
    """The FuncNode for the first argument of a RetryPolicy.call site:
    a local nested def or a ``self.method`` reference."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Name):
        key = f"{fn.key.split(':')[0]}:{fn.qual}.{arg.id}"
        return graph.functions.get(key)
    d = _dotted(arg)
    if d and len(d) == 2 and d[0] == "self":
        cls_node = graph.class_of(fn.key)
        if cls_node is not None:
            key = graph.resolve_method(cls_node.key, d[1])
            if key is not None:
                return graph.functions.get(key)
    return None


def _retry_call_sites(graph: CallGraph, fn, ff: _FnFacts) -> list:
    """Calls in ``fn`` that hand a closure to RetryPolicy.call: the
    resolved edge when the policy is a typed global/local, else the
    receiver-name heuristic (``*policy*``/``*retry*``)."""
    resolved_lines = {cs.line for cs in fn.calls
                      if cs.kind == "intra" and
                      cs.callee.endswith(":RetryPolicy.call")}
    out = []
    for n, _d in ff.calls:
        if not isinstance(n.func, ast.Attribute) or \
                n.func.attr != "call":
            continue
        recv = _recv_text(n).lower()
        if n.lineno in resolved_lines or \
                "policy" in recv or "retry" in recv:
            out.append(n)
    return out


def _closure_taint(closure_fn) -> list:
    """``(what, line)`` non-idempotent mutations in a retried closure."""
    node = closure_fn.node
    body_src = ast.unparse(node)
    if _FENCE_NAME_RE.search(body_src):
        return []        # fencing-token discipline present
    local_names: set = set()
    replaced: set = set()     # receivers with .clear() + .update()
    cleared: set = set()
    updated: set = set()
    for n in _direct_body(node):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    local_names.add(t.id)
        elif isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute):
            base = _recv_text(n)
            if n.func.attr == "clear":
                cleared.add(base)
            elif n.func.attr == "update":
                updated.add(base)
    replaced = cleared & updated
    taints: list = []
    for n in _direct_body(node):
        if isinstance(n, ast.AugAssign) and isinstance(n.op, ast.Add):
            if isinstance(n.target, ast.Name) and \
                    n.target.id in local_names:
                continue
            taints.append(("+= accumulation", n.lineno))
        elif isinstance(n, ast.Call) and \
                isinstance(n.func, ast.Attribute) and \
                n.func.attr in _ACCUM_METHODS:
            base = _recv_text(n)
            if base in replaced:
                continue
            root = base.split(".")[0].split("[")[0]
            if root in local_names:
                continue
            taints.append((f".{n.func.attr}() accumulation", n.lineno))
    return taints


def _sheds(facts: dict) -> set:
    """Functions that raise ErrOverloaded (a load-shed path)."""
    return {key for key, ff in facts.items() if ff.raises_overloaded}


def _retry_pass(graph: CallGraph, facts: dict, emit,
                cov: dict) -> None:
    closures = tainted = 0
    for key in sorted(graph.functions):
        fn = graph.functions[key]
        for call in _retry_call_sites(graph, fn, facts[key]):
            closure_fn = _resolve_closure_arg(graph, fn, call)
            if closure_fn is None:
                continue
            closures += 1
            for what, line in _closure_taint(closure_fn):
                tainted += 1
                emit("retry-unsafe", closure_fn.rel,
                     f"{closure_fn.qual}[{what.split()[0]}]",
                     f"closure retried by RetryPolicy.call mutates "
                     f"surviving state ({what}) with no fencing token "
                     f"and no newest-wins replacement — a retried "
                     f"attempt double-applies", line)

    # Shed discipline: the committed-state apply closure must never
    # reach ErrOverloaded.  Broker enqueues inside it need force=True;
    # resolved calls into shed-raising functions are flagged outright.
    sheds = _sheds(facts)
    roots = sorted(k for k, fn in graph.functions.items()
                   if _is_apply_root(fn))
    closure: set = set(roots)
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        for cs in graph.functions[key].calls:
            if cs.kind == "intra" and cs.callee in graph.functions \
                    and cs.callee not in closure:
                closure.add(cs.callee)
                frontier.append(cs.callee)
    shed_calls = 0
    for key in sorted(closure):
        fn = graph.functions[key]
        if key in sheds:
            continue        # the admission plane itself, not an applier
        resolved_shed_lines = {cs.line for cs in fn.calls
                               if cs.kind == "intra" and
                               cs.callee in sheds}
        for n, _d in facts[key].calls:
            if not isinstance(n.func, ast.Attribute):
                continue
            is_broker_enqueue = (n.func.attr == "enqueue" and
                                 "broker" in _recv_text(n).lower())
            if not is_broker_enqueue and \
                    n.lineno not in resolved_shed_lines:
                continue
            forced = _kwarg(n, "force")
            if forced is not None and \
                    isinstance(forced, ast.Constant) and \
                    forced.value is True:
                continue
            shed_calls += 1
            emit("retry-unsafe", fn.rel, f"{fn.qual}[shed-reachable]",
                 "committed-state applier reaches a load-shed path "
                 "without force=True — a replayed log entry could "
                 "raise ErrOverloaded and a committed write would be "
                 "lost", n.lineno)

    cov["retry_closures"] = closures
    cov["retry_tainted"] = tainted
    cov["shed_raisers"] = len(sheds)
    cov["apply_shed_calls"] = shed_calls


# -- entry --------------------------------------------------------------------

def analyze_package(package_dir: str, graph: Optional[CallGraph] = None,
                    scan=None, coverage_out: Optional[dict] = None
                    ) -> list:
    if graph is None:
        graph = CallGraph.build(package_dir)
    markers = _load_markers(
        package_dir, sorted({fn.rel for fn in graph.functions.values()}))
    findings: list = []
    waived_sites: set = set()
    emitted: set = set()
    cov: dict = {}

    def emit(rule: str, rel: str, where: str, message: str,
             line: int) -> None:
        if (rel, line, rule) in emitted:
            return
        emitted.add((rel, line, rule))
        if _waived(markers, rel, line, rule):
            waived_sites.add((rel, line, rule))
            return
        findings.append(Finding(rule=rule, path=rel, where=where,
                                message=message, line=line))

    facts = _prepass(graph)
    _deadline_pass(graph, facts, emit, cov)
    _injectability_pass(graph, facts, emit, cov, markers, waived_sites)
    _retry_pass(graph, facts, emit, cov)
    cov["waived"] = len(waived_sites)
    if coverage_out is not None:
        coverage_out.update(cov)
    return findings
