"""Consensus-plane lint: FSM determinism, leadership fencing, and the
read-consistency contract.

Every replica applying the same raft log must reach byte-identical
state — the PR-8 fingerprint tests sample that property on a handful of
recorded histories, but ROADMAP items 1 (follower-served reads) and 2
(multi-raft write plane) need it *proven* over the whole apply surface.
Three passes ride the PR-4 interprocedural call graph:

**Apply-determinism taint** — the closure reachable from the FSM apply
surface (``NomadFSM.apply`` / ``_apply_*`` / ``restore`` / ``snapshot``)
and the store write surface (``upsert_*`` / ``delete_*`` / ``update_*``
/ restore commit) is the replicated state machine; values arriving via
the log entry are the only clean inputs.  Inside that closure the pass
flags every source of replica divergence:

  - ``apply-wall-clock``: ``time.time``/``monotonic``/``perf_counter``
    and ``datetime.now`` family reads — two replicas apply the same
    entry at different wall times.
  - ``apply-rng``: unseeded randomness (``random.*`` module calls,
    ``uuid4``/``uuid1``, ``os.urandom``, ``secrets.*``).  Seeded
    instance generators (``self._rng``) are replayable and exempt; ids
    must be minted leader-side BEFORE the entry is logged.
  - ``apply-env``: environment/host identity reads (``os.environ`` /
    ``os.getenv`` / ``socket.gethostname`` / ``platform.node``) — per-
    host values that differ across replicas.
  - ``apply-iter-order``: set iteration whose order escapes into an
    ordered output (a list comprehension / ``list()`` / an appending
    loop) — set order depends on ``PYTHONHASHSEED``, so the escaped
    order differs per process.  Dict iteration is deliberately allowed:
    insertion order is deterministic under identical replay.
  - ``apply-float-accum``: float accumulation (``sum`` / ``+=`` loops)
    over an unordered collection — float addition is not associative,
    so the hash-order walk changes the result bits.

The notification/observability planes never feed replicated state and
are excluded as sinks (``obs.*`` modules, ``StateWatch``); exclusions
are counted in the coverage block, not silent.

**Leadership fencing** — leader-only machinery (broker ``force=True``
enqueues, HeartbeatManager arming, PlanApplier/_Committer dispatch,
controller actuation, GC core-eval creation) must be reachable only
through a leadership-fenced entry, so a future follower serving reads
can be proven never to mutate leader state.  A function is fenced if it
syntactically checks leadership (``is_leader()`` / ``self._leader`` /
``_forward()`` / ``_leading()``) or IS a leadership transition hook
(``establish_leadership`` and friends); fencing then propagates down
the call graph — a function is fenced when every resolved in-package
caller is fenced (``Thread(target=self.x)`` counts as a call from the
function that arms the thread).  Rule: ``leader-fence``.

**Read-consistency contract** — every RPC endpoint that reads the store
is classified (``stale-safe`` / ``leader-only`` / ``write`` /
``server-local``) from the handler's own shape: stale-safe reads must
flow through the blocking-query ``min_index`` discipline
(``_blocking``) AND be registered in ``CONSISTENT_READS``; any direct
store read must sit behind the ``_forward`` leader fence.  Rules:
``read-consistency`` (an unfenced direct store read) and
``stale-read-bypass`` (a blocking read outside ``CONSISTENT_READS``, or
a CONSISTENT_READS handler reading state outside the discipline).  The
classification table is emitted in ``nomad-tpu lint -json``
(``coverage.consensuslint.endpoint_contract``) as the machine-readable
contract ROADMAP item 1 builds on.

Deliberate exceptions carry an inline justification marker on (or one
line above) the site — ``# consensus-ok(<rule>): <why>`` — the devlint
marker grammar; markers with no justification text do not waive.
Waived sites are counted in the coverage block so the ledger stays
visible.
"""
from __future__ import annotations

import ast
import os
import re

from typing import Optional

from . import Finding
from .callgraph import CallGraph
from .jaxlint import _dotted

_MARKER_RE = re.compile(r"#\s*consensus-ok\((?P<rule>[a-z-]+)\)\s*:\s*\S")

# -- pass 1: apply-determinism ----------------------------------------------

# Modules whose path contains one of these parts are observability /
# tracing planes: they never feed replicated state (fingerprint() covers
# tables + changelog only), so the taint walk treats them as sinks.
SINK_MODULE_PARTS = frozenset({"obs"})

# Classes excluded as sinks: the watch/notify plane fans events out to
# subscribers, it never writes a table.
SINK_CLASSES = frozenset({"StateWatch"})

_WALL_CLOCK_TIME = frozenset({
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns", "clock_gettime",
})
_WALL_CLOCK_DT = frozenset({"now", "utcnow", "today"})
_HOST_SOCKET = frozenset({"gethostname", "getfqdn", "gethostbyname"})

# -- pass 2: leadership fencing ---------------------------------------------

# Leadership transition hooks: bodies that RUN the transition are the
# fence, by definition (plus teardown, which must be able to stop
# leader machinery regardless of the current flag).
FENCE_HOOKS = frozenset({
    "establish_leadership", "revoke_leadership", "_on_leadership_change",
    "abandon", "shutdown",
})

# Call names that read the leadership flag: seeing one in a function
# body makes it a syntactic fence.
_FENCE_CALLS = frozenset({"is_leader", "_forward", "_leading"})

# Receiver substrings that mark .start()/.submit() dispatch as
# leader-plane machinery (PlanApplier, the plan _Committer, the
# feedback controller).
_DISPATCH_RECEIVERS = ("applier", "controller", "committer")


def _snake(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        if ch.isupper() and i > 0:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)


# -- markers (devlint grammar, consensus-ok spelling) ------------------------

def _load_markers(package_dir: str, rels) -> dict:
    """(rel, line) -> {rule, ...} for every justified consensus-ok
    marker (same propagation rules as devlint._load_markers)."""
    base = os.path.dirname(os.path.abspath(package_dir))
    out: dict = {}
    for rel in rels:
        path = os.path.join(base, rel)
        try:
            with open(path) as fh:
                lines = fh.read().splitlines()
        except OSError:
            continue
        for i, text in enumerate(lines, 1):
            for m in _MARKER_RE.finditer(text):
                rule = m.group("rule")
                out.setdefault((rel, i), set()).add(rule)
                if not text.lstrip().startswith("#"):
                    # Inline marker: waives its own line only.
                    continue
                # Comment-block marker: waive the continuation comment
                # lines and the first code line the block lands on; a
                # blank line ends the block unattached.
                j = i + 1
                while j <= len(lines) and \
                        lines[j - 1].lstrip().startswith("#"):
                    out.setdefault((rel, j), set()).add(rule)
                    j += 1
                if j <= len(lines) and lines[j - 1].strip():
                    out.setdefault((rel, j), set()).add(rule)
    return out


def _waived(markers: dict, rel: str, line: int, rule: str) -> bool:
    return rule in markers.get((rel, line), ())


# -- pass 1 helpers ----------------------------------------------------------

def _is_apply_root(fn) -> bool:
    """The replicated-write surface: FSM apply/restore/snapshot and the
    store/restore write methods (name-driven so synthetic test packages
    participate)."""
    if fn.cls is None:
        return False
    last = fn.qual.split(".")[-1]
    if fn.qual.count(".") > 1:
        return False        # nested defs join via the call walk, not as roots
    if fn.cls.endswith("FSM"):
        return (last in ("apply", "restore", "snapshot") or
                last.startswith("_apply_"))
    if fn.cls.endswith("Store") or fn.cls.endswith("Restore"):
        return (last.startswith(("upsert_", "delete_", "update_")) or
                last.endswith("_restore") or last == "commit")
    return False


def _is_sink(fn) -> bool:
    if fn.cls in SINK_CLASSES:
        return True
    return bool(SINK_MODULE_PARTS & set(fn.module.split(".")))


def _banned_call(d: tuple) -> Optional[tuple]:
    """(rule, what) when the dotted call target is a nondeterminism
    source; None otherwise."""
    if len(d) == 2 and d[0] == "time" and d[1] in _WALL_CLOCK_TIME:
        return ("apply-wall-clock", f"wall-clock read {'.'.join(d)}()")
    if d[-1] in _WALL_CLOCK_DT and "datetime" in d[:-1]:
        return ("apply-wall-clock", f"wall-clock read {'.'.join(d)}()")
    if len(d) >= 2 and d[0] == "random":
        return ("apply-rng", f"unseeded RNG {'.'.join(d)}()")
    if d[-1] in ("uuid4", "uuid1") and (len(d) == 1 or d[0] == "uuid"):
        return ("apply-rng", f"RNG id mint {'.'.join(d)}()")
    if d[-1] == "urandom":
        return ("apply-rng", f"entropy read {'.'.join(d)}()")
    if d[0] == "secrets":
        return ("apply-rng", f"entropy read {'.'.join(d)}()")
    if d[:2] == ("os", "environ") or d == ("os", "getenv"):
        return ("apply-env", f"environment read {'.'.join(d)}")
    if d[0] == "socket" and d[-1] in _HOST_SOCKET:
        return ("apply-env", f"host identity read {'.'.join(d)}()")
    if d == ("platform", "node"):
        return ("apply-env", "host identity read platform.node()")
    return None


def _unordered_expr(e: ast.expr, names: set) -> bool:
    """True when the expression's value is an unordered (hash-ordered)
    collection: set/frozenset constructions, names bound to one, and
    set-algebra over them.  ``sorted(...)``/``list(...)`` launder the
    order and are NOT unordered."""
    if isinstance(e, (ast.Set, ast.SetComp)):
        return True
    if isinstance(e, ast.Call):
        d = _dotted(e.func)
        if d in (("set",), ("frozenset",)):
            return True
        if d and d[-1] in ("union", "intersection", "difference",
                           "symmetric_difference") and \
                isinstance(e.func, ast.Attribute) and \
                _unordered_expr(e.func.value, names):
            return True
        return False
    if isinstance(e, ast.Name):
        return e.id in names
    if isinstance(e, ast.BinOp) and isinstance(
            e.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return _unordered_expr(e.left, names) or \
            _unordered_expr(e.right, names)
    return False


def _unordered_names(fn_node) -> set:
    """Names assigned from unordered expressions (two fixpoint rounds
    cover one level of chaining; branch-insensitive by design)."""
    names: set = set()
    for _ in range(2):
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign) and \
                    _unordered_expr(n.value, names):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
    return names


def _scan_order_escapes(fn_node, emit) -> None:
    """Flag set-order escaping into ordered output / float accumulation
    over unordered collections.  ``emit(rule, what, line)``."""
    names = _unordered_names(fn_node)

    def unordered(e: ast.expr) -> bool:
        return _unordered_expr(e, names)

    for n in ast.walk(fn_node):
        if isinstance(n, ast.ListComp):
            if any(unordered(g.iter) for g in n.generators):
                emit("apply-iter-order",
                     "set iteration order escapes into a list",
                     n.lineno)
        elif isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d is None or not n.args:
                continue
            arg = n.args[0]
            arg_unordered = unordered(arg) or (
                isinstance(arg, ast.GeneratorExp) and
                any(unordered(g.iter) for g in arg.generators))
            if not arg_unordered:
                continue
            if d == ("sum",):
                emit("apply-float-accum",
                     "accumulation over an unordered collection "
                     "(sum over a set)", n.lineno)
            elif d in (("list",), ("tuple",)):
                emit("apply-iter-order",
                     "set iteration order escapes into a sequence",
                     n.lineno)
        elif isinstance(n, ast.For) and unordered(n.iter):
            for b in ast.walk(n):
                if isinstance(b, ast.Call) and \
                        isinstance(b.func, ast.Attribute) and \
                        b.func.attr in ("append", "extend", "insert"):
                    emit("apply-iter-order",
                         "set iteration order escapes via "
                         f".{b.func.attr}()", b.lineno)
                    break
                if isinstance(b, ast.AugAssign) and \
                        isinstance(b.op, ast.Add):
                    emit("apply-float-accum",
                         "accumulation (+=) over an unordered "
                         "collection", b.lineno)
                    break


def _determinism_pass(graph: CallGraph, emit, cov: dict) -> None:
    roots = sorted(k for k, fn in graph.functions.items()
                   if _is_apply_root(fn))
    closure: set = set(roots)
    parents: dict = {}
    sinks_hit: set = set()
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        fn = graph.functions[key]
        for cs in fn.calls:
            if cs.kind != "intra" or cs.callee not in graph.functions:
                continue
            if cs.callee in closure:
                continue
            callee = graph.functions[cs.callee]
            if _is_sink(callee):
                sinks_hit.add(cs.callee)
                continue
            closure.add(cs.callee)
            parents[cs.callee] = key
            frontier.append(cs.callee)

    def chain(key: str) -> str:
        path = [key]
        while path[-1] in parents:
            path.append(parents[path[-1]])
        quals = [graph.functions[k].qual for k in reversed(path)]
        return " -> ".join(quals)

    banned = 0
    for key in sorted(closure):
        fn = graph.functions[key]
        via = chain(key)

        def emit_site(rule: str, what: str, line: int,
                      _fn=fn, _via=via) -> None:
            nonlocal banned
            banned += 1
            emit(rule, _fn.rel, f"{_fn.qual}[{what.split(' ')[-1]}]",
                 f"{what} on the replicated apply path ({_via}) — "
                 f"replicas applying the same log entry diverge",
                 line)

        for node in ast.walk(fn.node):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d is None:
                    continue
                hit = _banned_call(d)
                if hit is not None:
                    emit_site(hit[0], hit[1], node.lineno)
            elif isinstance(node, ast.Attribute):
                d = _dotted(node)
                if d is not None and d[:2] == ("os", "environ"):
                    emit_site("apply-env", "environment read os.environ",
                              node.lineno)
        _scan_order_escapes(fn.node, emit_site)

    cov["apply_roots"] = len(roots)
    cov["apply_closure"] = len(closure)
    cov["sinks_excluded"] = len(sinks_hit)
    cov["apply_banned_sites"] = banned


# -- pass 2 helpers ----------------------------------------------------------

def _leader_target(call: ast.Call) -> Optional[str]:
    """Short label when the call site is leader-only machinery."""
    fnode = call.func
    if isinstance(fnode, ast.Name):
        if fnode.id == "_enqueue_core_eval":
            return "core-eval-create"
        return None
    if not isinstance(fnode, ast.Attribute):
        return None
    meth = fnode.attr
    try:
        owner = ast.unparse(fnode.value).lower()
    except Exception:
        owner = ""
    if meth == "enqueue":
        for kw in call.keywords:
            if kw.arg == "force" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return "broker-force-enqueue"
        return None
    if meth == "reset_heartbeat_timer":
        return "heartbeat-arm"
    if meth == "initialize" and "heartbeat" in owner:
        return "heartbeat-arm"
    if meth == "_enqueue_core_eval":
        return "core-eval-create"
    if meth == "set_enabled" and call.args and \
            isinstance(call.args[0], ast.Constant) and \
            call.args[0].value is True:
        return "leader-plane-enable"
    if meth in ("start", "submit") and \
            any(s in owner for s in _DISPATCH_RECEIVERS):
        return "leader-dispatch"
    return None


def _syntactic_fence(fn) -> bool:
    last = fn.qual.split(".")[-1]
    if last in FENCE_HOOKS:
        return True
    for n in ast.walk(fn.node):
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d and d[-1] in _FENCE_CALLS:
                return True
        elif isinstance(n, ast.Attribute) and n.attr == "_leader" and \
                isinstance(n.ctx, ast.Load):
            # A READ of the flag is a fence; `self._leader = False` in
            # an initializer is not.
            return True
    return False


def _fencing_pass(graph: CallGraph, emit, cov: dict) -> None:
    # Reverse edges over resolved intra calls, plus Thread(target=
    # self.x) arming edges: the thread body is "called by" the armer.
    callers: dict = {}
    for key, fn in graph.functions.items():
        for cs in fn.calls:
            if cs.kind == "intra" and cs.callee in graph.functions:
                callers.setdefault(cs.callee, set()).add(key)
        cls_node = graph.class_of(key)
        if cls_node is None:
            continue
        for n in ast.walk(fn.node):
            if not isinstance(n, ast.Call):
                continue
            for kw in n.keywords:
                if kw.arg != "target":
                    continue
                d = _dotted(kw.value)
                if d and len(d) == 2 and d[0] == "self":
                    callee = graph.resolve_method(cls_node.key, d[1])
                    if callee is not None:
                        callers.setdefault(callee, set()).add(key)

    fenced = {k for k, fn in graph.functions.items()
              if _syntactic_fence(fn)}
    changed = True
    while changed:
        changed = False
        for key in graph.functions:
            if key in fenced:
                continue
            cs = callers.get(key)
            if cs and cs <= fenced:
                fenced.add(key)
                changed = True

    sites = 0
    for key in sorted(graph.functions):
        fn = graph.functions[key]
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            target = _leader_target(node)
            if target is None:
                continue
            sites += 1
            if key in fenced:
                continue
            emit("leader-fence", fn.rel, f"{fn.qual}[{target}]",
                 f"leader-only machinery ({target}) reachable without a "
                 f"leadership fence — add an is_leader()/_leader check "
                 f"on the path or a consensus-ok waiver",
                 node.lineno)
    cov["fence_targets"] = sites
    cov["fenced_functions"] = len(fenced)


# -- pass 3: read-consistency contract ---------------------------------------

def _direct_body(fn_node):
    """Walk a function body WITHOUT descending into nested defs (the
    ``run`` closures handed to ``_blocking`` are the disciplined read,
    not a direct one)."""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _endpoint_tables(graph: CallGraph):
    """(module, service->methods dict, consistent_reads set) for the
    module defining class ``Endpoints``; None when absent."""
    for module, info in graph.modules.items():
        ck = info.classes.get("Endpoints")
        if ck is None:
            continue
        cls = graph.classes.get(ck)
        install_key = cls.methods.get("install") if cls else None
        if install_key is None:
            continue
        install = graph.functions[install_key]
        services: dict = {}
        for n in ast.walk(install.node):
            if not isinstance(n, ast.Dict):
                continue
            for k, v in zip(n.keys, n.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str) and \
                        isinstance(v, (ast.List, ast.Tuple)) and \
                        all(isinstance(e, ast.Constant) and
                            isinstance(e.value, str) for e in v.elts):
                    services[k.value] = [e.value for e in v.elts]
        consistent: set = set()
        for n in ast.walk(info.tree):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "CONSISTENT_READS"
                    for t in n.targets):
                for c in ast.walk(n.value):
                    if isinstance(c, ast.Constant) and \
                            isinstance(c.value, str):
                        consistent.add(c.value)
        if services:
            return module, cls, services, consistent
    return None


def _contract_pass(graph: CallGraph, emit, cov: dict) -> None:
    found = _endpoint_tables(graph)
    if found is None:
        cov["endpoints"] = 0
        cov["endpoint_contract"] = {}
        return
    module, cls, services, consistent = found

    handler_names = {f"{svc.lower()}_{_snake(m)}": f"{svc}.{m}"
                     for svc, methods in services.items()
                     for m in methods}

    shapes: dict = {}     # full name -> (fn, blocking, forward, read, delegate)
    for hname, full in sorted(handler_names.items()):
        key = graph.resolve_method(cls.key, hname)
        fn = graph.functions.get(key) if key else None
        if fn is None:
            continue
        blocking = forward = reads = False
        delegate = None
        for n in _direct_body(fn.node):
            if not isinstance(n, ast.Call):
                continue
            d = _dotted(n.func)
            if d is None:
                continue
            if d[-1] == "_blocking":
                blocking = True
            elif d[-1] == "_forward":
                forward = True
            elif d[-1] == "_state" or d[-3:] == ("fsm", "state"):
                reads = True
            elif len(d) == 2 and d[0] == "self" and d[1] in handler_names:
                delegate = handler_names[d[1]]
        shapes[full] = (fn, blocking, forward, reads, delegate)

    contract: dict = {}

    def classify(full: str, seen=()) -> str:
        if full in contract:
            return contract[full]
        fn, blocking, forward, reads, delegate = shapes[full]
        if blocking and not reads:
            c = "stale-safe" if full in consistent else "local-read"
        elif reads:
            c = "leader-only" if forward else "unfenced-read"
        elif forward:
            c = "write"
        elif delegate and delegate in shapes and full not in seen:
            c = classify(delegate, seen + (full,))
        else:
            c = "server-local"
        contract[full] = c
        return c

    for full in sorted(shapes):
        c = classify(full)
        fn = shapes[full][0]
        if c == "unfenced-read":
            emit("read-consistency", fn.rel, full,
                 "endpoint reads the store directly with no _forward "
                 "leader fence — a follower would answer from "
                 "unreplicated-yet state with no stale opt-in",
                 fn.line)
        elif c == "local-read":
            emit("stale-read-bypass", fn.rel, full,
                 "blocking store read not registered in "
                 "CONSISTENT_READS — follower-local answers with no "
                 "leader default; add it to the contract table",
                 fn.line)
        elif full in consistent and not shapes[full][1]:
            emit("stale-read-bypass", fn.rel, full,
                 "CONSISTENT_READS endpoint reads outside the "
                 "_blocking min_index discipline — stale reads can't "
                 "be index-bounded", fn.line)
    cov["endpoints"] = len(shapes)
    cov["endpoint_contract"] = {k: v for k, v in sorted(contract.items())}
    cov["stale_safe_reads"] = sum(
        1 for v in contract.values() if v == "stale-safe")
    cov["leader_only_reads"] = sum(
        1 for v in contract.values() if v == "leader-only")


# -- entry -------------------------------------------------------------------

def analyze_package(package_dir: str, graph: Optional[CallGraph] = None,
                    scan=None, coverage_out: Optional[dict] = None
                    ) -> list:
    if graph is None:
        graph = CallGraph.build(package_dir)
    markers = _load_markers(
        package_dir, sorted({fn.rel for fn in graph.functions.values()}))
    findings: list = []
    waived_sites: set = set()
    emitted: set = set()
    cov: dict = {}

    def emit(rule: str, rel: str, where: str, message: str,
             line: int) -> None:
        if (rel, line, rule) in emitted:
            return
        emitted.add((rel, line, rule))
        if _waived(markers, rel, line, rule):
            waived_sites.add((rel, line, rule))
            return
        findings.append(Finding(rule=rule, path=rel, where=where,
                                message=message, line=line))

    _determinism_pass(graph, emit, cov)
    _fencing_pass(graph, emit, cov)
    _contract_pass(graph, emit, cov)
    cov["waived"] = len(waived_sites)
    if coverage_out is not None:
        coverage_out.update(cov)
    return findings
