"""JAX tracer-safety lint: keep the jit kernels pure and retrace-stable.

Every ``jax.jit`` entry point in the package — decorator form
(``@jax.jit``, ``@partial(jax.jit, static_argnames=...)``) or wrapper
form (``name = jax.jit(_fn, ...)``) — is walked together with its
intra-package callees for the failure modes behavioral tests cannot see:

  - ``impure-call``: ``time``/``random``/``print``/``open``/``os``/
    ``datetime``/``uuid``/``logging`` calls trace ONCE and then freeze
    (or worse, silently leak host state into the compiled graph);
  - ``attr-mutation`` / ``global-mutation``: writes to object attributes
    or module globals inside traced code run at trace time only — the
    kernel looks right until the cache stops missing;
  - ``concretize``: ``float()``/``int()``/``bool()``/``.item()``/
    ``.tolist()``/``np.asarray()`` on a traced value aborts tracing (or
    forces a device sync on every call);
  - ``traced-branch``: Python ``if``/``while`` on a traced expression —
    the ConcretizationTypeError class, and with ``jnp`` scalars the
    silent one-retrace-per-value cache explosion.

The taint model is deliberately simple and conservative: non-static
parameters are traced; any expression built from a traced value is
traced; ``.shape``/``.ndim``/``.dtype``/``len()`` of a traced value are
STATIC (shapes are compile-time under jit, so shape-dependent branching
is legal and common).  ``static_argnames``/``static_argnums`` from the
jit declaration are honored — branching on ``unroll`` or ``k_cap`` is
exactly what static args are for.  Callees get every parameter marked
traced (an intra-package helper may be called with tracers even if some
call sites pass host values); helpers that are genuinely host-only earn
an allowlist line instead of a lint pass, which keeps the reviewed
ledger honest about what runs under trace.
"""
from __future__ import annotations

import ast
import os
from typing import Optional

from . import Finding

IMPURE_ROOTS = {"time", "random", "os", "datetime", "uuid", "logging",
                "threading", "subprocess", "socket"}
IMPURE_CALLS = {"print", "open", "input", "exec", "eval", "perf_counter",
                "monotonic"}
# numpy RNG is impure under trace; jax.random is fine (explicit keys).
IMPURE_ATTR_CHAINS = {("np", "random"), ("numpy", "random")}
CONCRETIZE_FUNCS = {"float", "int", "bool", "complex"}
CONCRETIZE_METHODS = {"item", "tolist", "__bool__", "__float__"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding"}
MAX_CALLEE_DEPTH = 3


def _dotted(node: ast.expr) -> Optional[tuple]:
    """a.b.c -> ("a","b","c") for Name/Attribute chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


class _Module:
    def __init__(self, rel: str, modname: str, tree: ast.Module,
                 dotted: str = "", is_pkg: bool = False) -> None:
        self.rel = rel
        self.modname = modname
        self.tree = tree
        self.dotted = dotted or modname
        self.functions: dict = {}   # name -> FunctionDef (incl. nested)
        self.imports: dict = {}     # local name -> (dotted module, name)
        # The package a relative import resolves against: the module's
        # parent for plain files, the package itself for __init__.py.
        parts = self.dotted.split(".")
        pkg = parts if is_pkg else parts[:-1]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.setdefault(node.name, node)
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    src = node.module or ""
                else:
                    base = pkg[:len(pkg) - (node.level - 1)]
                    src = ".".join(base + ([node.module]
                                           if node.module else []))
                if not src:
                    continue
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = \
                        (src, alias.name)


class _JitRoot:
    def __init__(self, module: _Module, fn: ast.FunctionDef,
                 static: set, line: int) -> None:
        self.module = module
        self.fn = fn
        self.static = static
        self.line = line


def _static_names_from_call(call: ast.Call, fn: ast.FunctionDef) -> set:
    """static_argnames=(...) / static_argnums=(...) -> param name set."""
    static: set = set()
    params = [a.arg for a in fn.args.args]
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, str):
                    static.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and \
                        isinstance(el.value, int) and \
                        0 <= el.value < len(params):
                    static.add(params[el.value])
    return static


def _is_jax_jit(node: ast.expr) -> bool:
    return _dotted(node) in (("jax", "jit"), ("jit",))


def _find_jit_roots(mod: _Module) -> list:
    roots = []
    for node in ast.walk(mod.tree):
        # @jax.jit / @partial(jax.jit, ...) decorators.
        if isinstance(node, ast.FunctionDef):
            for deco in node.decorator_list:
                if _is_jax_jit(deco):
                    roots.append(_JitRoot(mod, node, set(), node.lineno))
                elif isinstance(deco, ast.Call):
                    if _is_jax_jit(deco.func):
                        roots.append(_JitRoot(
                            mod, node,
                            _static_names_from_call(deco, node),
                            node.lineno))
                    elif _dotted(deco.func) in (("partial",),
                                                ("functools", "partial")) \
                            and deco.args and _is_jax_jit(deco.args[0]):
                        roots.append(_JitRoot(
                            mod, node,
                            _static_names_from_call(deco, node),
                            node.lineno))
        # name = jax.jit(_fn, ...) wrapper form (possibly nested in
        # vmap/partial: jax.jit(jax.vmap(partial(_fn, ...), ...))).
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call) and \
                _is_jax_jit(node.value.func):
            jit_call = node.value
            fn_node = _unwrap_fn(mod, jit_call.args[0]) \
                if jit_call.args else None
            if fn_node is not None:
                roots.append(_JitRoot(
                    mod, fn_node,
                    _static_names_from_call(jit_call, fn_node),
                    node.lineno))
    return roots


def _unwrap_fn(mod: _Module, expr: ast.expr
               ) -> Optional[ast.FunctionDef]:
    """Resolve jit(vmap(partial(_fn, ...)))-style wrapping to _fn."""
    for _ in range(6):
        if isinstance(expr, ast.Name):
            return mod.functions.get(expr.id)
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d and d[-1] in ("vmap", "partial", "pmap", "shard_map",
                               "checkpoint", "remat", "grad"):
                if expr.args:
                    expr = expr.args[0]
                    continue
            return None
        return None
    return None


class _TaintVisitor(ast.NodeVisitor):
    """One function body, forward taint pass (run twice for loops)."""

    def __init__(self, lint: "_Lint", mod: _Module, fn: ast.FunctionDef,
                 tainted: set, chain: str, depth: int) -> None:
        self.lint = lint
        self.mod = mod
        self.fn = fn
        self.tainted = set(tainted)
        self.chain = chain
        self.depth = depth
        self.reported: set = set()

    # -- taint computation -------------------------------------------------
    def is_tainted(self, node: ast.expr) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d and d[0] == "len":
                return False
            if d and d[-1] in ("range", "arange", "iota") and \
                    not any(self.is_tainted(a) for a in node.args):
                return False
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("astype", "reshape", "sum", "at",
                                       "add", "get", "set", "mean", "min",
                                       "max"):
                if self.is_tainted(node.func.value):
                    return True
            return any(self.is_tainted(a) for a in node.args) or \
                any(self.is_tainted(kw.value) for kw in node.keywords)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self.is_tainted(node.left) or \
                any(self.is_tainted(c) for c in node.comparators)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(el) for el in node.elts)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or \
                self.is_tainted(node.orelse) or self.is_tainted(node.test)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        return False

    def _report(self, rule: str, line: int, msg: str) -> None:
        key = (rule, self.mod.rel, self.chain, line)
        if key in self.lint.reported:
            return
        self.lint.reported.add(key)
        self.lint.findings.append(Finding(
            rule, self.mod.rel, self.chain, msg, line))

    # -- statements --------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        tainted = self.is_tainted(node.value)
        for tgt in node.targets:
            self._bind(tgt, tainted)
            if isinstance(tgt, ast.Attribute):
                self._report(
                    "attr-mutation", node.lineno,
                    f"attribute store `{ast.unparse(tgt)} = ...` inside "
                    "traced code runs at trace time only")
            elif isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id in {a.arg for a in self.fn.args.args}:
                self._report(
                    "attr-mutation", node.lineno,
                    f"in-place subscript store into parameter "
                    f"`{tgt.value.id}` inside traced code")

    def _bind(self, tgt: ast.expr, tainted: bool) -> None:
        if isinstance(tgt, ast.Name):
            if tainted:
                self.tainted.add(tgt.id)
            else:
                self.tainted.discard(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._bind(el, tainted)
        elif isinstance(tgt, ast.Starred):
            self._bind(tgt.value, tainted)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self.visit(node.value)
        if isinstance(node.target, ast.Name):
            if self.is_tainted(node.value):
                self.tainted.add(node.target.id)
        elif isinstance(node.target, ast.Attribute):
            self._report("attr-mutation", node.lineno,
                         f"augmented attribute store "
                         f"`{ast.unparse(node.target)}` in traced code")

    def visit_Global(self, node: ast.Global) -> None:
        self._report("global-mutation", node.lineno,
                     f"`global {', '.join(node.names)}` inside traced "
                     "code mutates host state at trace time only")

    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        if self.is_tainted(node.test):
            self._report(
                "traced-branch", node.lineno,
                f"Python `if {ast.unparse(node.test)}` on a traced "
                "value (use jnp.where / lax.cond, or make it static)")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_While(self, node: ast.While) -> None:
        self.visit(node.test)
        if self.is_tainted(node.test):
            self._report(
                "traced-branch", node.lineno,
                f"Python `while {ast.unparse(node.test)}` on a traced "
                "value (use lax.while_loop)")
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    def visit_For(self, node: ast.For) -> None:
        self.visit(node.iter)
        if self.is_tainted(node.iter):
            self._report(
                "traced-branch", node.lineno,
                f"Python `for` over traced `{ast.unparse(node.iter)}` "
                "unrolls at trace time (use lax.scan / fori_loop)")
        self._bind(node.target, self.is_tainted(node.iter))
        for stmt in node.body + node.orelse:
            self.visit(stmt)

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is not None:
            self._check_call(node, d)
        self.generic_visit(node)

    def _check_call(self, node: ast.Call, d: tuple) -> None:
        line = node.lineno
        args_tainted = any(self.is_tainted(a) for a in node.args)
        if d[0] in IMPURE_ROOTS or d[-1] in IMPURE_CALLS:
            self._report("impure-call", line,
                         f"impure call `{'.'.join(d)}(...)` in traced "
                         "code executes at trace time only")
            return
        if len(d) >= 2 and (d[0], d[1]) in IMPURE_ATTR_CHAINS:
            self._report("impure-call", line,
                         f"`{'.'.join(d)}` is host RNG; use jax.random "
                         "with an explicit key")
            return
        if len(d) == 1 and d[0] in CONCRETIZE_FUNCS and args_tainted:
            self._report("concretize", line,
                         f"`{d[0]}()` concretizes a traced value")
            return
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in CONCRETIZE_METHODS and \
                self.is_tainted(node.func.value):
            self._report("concretize", line,
                         f"`.{node.func.attr}()` on a traced value "
                         "forces host materialization")
            return
        if d[0] in ("np", "numpy") and d[-1] in ("asarray", "array") \
                and args_tainted:
            self._report("concretize", line,
                         f"`{'.'.join(d)}` materializes a traced value "
                         "on host (use jnp)")
            return
        # Intra-package callee: descend (all params traced).
        if len(d) == 1 and self.depth < MAX_CALLEE_DEPTH:
            self.lint.check_callee(self.mod, d[0],
                                   f"{self.chain} -> {d[0]}",
                                   self.depth + 1)

    # -- nested defs: traced closures (lax.scan bodies etc.) ---------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        inner = _TaintVisitor(
            self.lint, self.mod, node,
            self.tainted | {a.arg for a in node.args.args},
            f"{self.chain}.{node.name}", self.depth)
        inner.run()

    visit_AsyncFunctionDef = visit_FunctionDef

    def run(self) -> None:
        # Two passes: loop-carried taint (x set late, used early in a
        # `for`) stabilizes in the second pass; reports dedup globally.
        for _ in range(2):
            for stmt in self.fn.body:
                self.visit(stmt)


class _Lint:
    def __init__(self, modules: dict) -> None:
        self.modules = modules      # modname -> _Module
        self.findings: list = []
        self.reported: set = set()  # (rule, rel, chain, line) dedup
        self._seen: set = set()     # (module, fn name) analyzed as callee

    def check_root(self, root: _JitRoot) -> None:
        params = {a.arg for a in root.fn.args.args}
        tainted = params - root.static
        v = _TaintVisitor(self, root.module, root.fn, tainted,
                          f"{root.module.modname}.{root.fn.name}", 0)
        v.run()

    def check_callee(self, mod: _Module, name: str, chain: str,
                     depth: int) -> None:
        target_mod, fn = self._resolve(mod, name)
        if fn is None:
            return
        key = (target_mod.dotted, fn.name)
        if key in self._seen:
            return
        self._seen.add(key)
        tainted = {a.arg for a in fn.args.args}
        v = _TaintVisitor(self, target_mod, fn, tainted, chain, depth)
        v.run()

    def _resolve(self, mod: _Module, name: str):
        fn = mod.functions.get(name)
        if fn is not None:
            return mod, fn
        imp = mod.imports.get(name)
        if imp is not None:
            src_module, src_name = imp
            # Dotted lookup first (exact); a `from pkg import helper`
            # where pkg is a package falls through to pkg/__init__.
            target = self.modules.get(src_module)
            if target is not None:
                return target, target.functions.get(src_name)
        return mod, None


def analyze_package(package_dir: str) -> list:
    modules: dict = {}   # dotted module path -> _Module
    mods: list = []
    base = os.path.dirname(os.path.abspath(package_dir))
    for root, dirs, files in os.walk(package_dir):
        dirs[:] = sorted(d for d in dirs if not d.startswith("__pycache"))
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            path = os.path.join(root, fname)
            with open(path) as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError:
                    continue  # lockcheck reports parse errors
            rel = os.path.relpath(path, base)
            modname = os.path.splitext(fname)[0]
            is_pkg = fname == "__init__.py"
            dotted_parts = os.path.splitext(rel)[0].split(os.sep)
            if is_pkg:
                dotted_parts = dotted_parts[:-1]
            dotted = ".".join(dotted_parts)
            m = _Module(rel, modname, tree, dotted=dotted, is_pkg=is_pkg)
            modules[dotted] = m
            mods.append(m)

    lint = _Lint(modules)
    for m in mods:
        for jit_root in _find_jit_roots(m):
            lint.check_root(jit_root)
    return lint.findings
