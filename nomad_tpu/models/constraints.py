"""Constraint compilation: host predicates -> per-node boolean masks.

Nomad constraints are stringly-typed (=, !=, lexical order, semver,
regexp over arbitrary attrs — reference scheduler/feasible.go:259-376), so
they cannot run on the MXU.  The TPU design compiles each constraint ONCE per
fleet generation into a boolean mask over the node axis, evaluated host-side
with the exact same predicate functions the sequential scheduler uses (golden
parity by construction), then ships masks to HBM where the device pipeline
just ANDs them (SURVEY.md section 7, "Constraint vectorization").

Masks are cached in ``FleetStatics.mask_cache`` keyed by the constraint's
value tuple, so a 10k-node fleet pays the Python predicate walk once per
(constraint, fleet-generation), not once per placement.

``distinct_hosts`` is NOT compiled here — it depends on the in-flight plan,
so it is evaluated on device from the per-node same-job alloc count tensor.
"""
from __future__ import annotations

import numpy as np

from nomad_tpu.structs import CONSTRAINT_DISTINCT_HOSTS, Constraint
from nomad_tpu.utils.predicates import (
    check_constraint_values,
    resolve_constraint_target,
)

from .fleet import FleetStatics


class _MaskCtx:
    """Minimal EvalContext stand-in carrying the predicate caches."""

    __slots__ = ("regexp_cache", "constraint_cache")

    def __init__(self) -> None:
        self.regexp_cache: dict = {}
        self.constraint_cache: dict = {}


_mask_ctx = _MaskCtx()


def _constraint_key(c: Constraint) -> tuple:
    return ("c", c.l_target, c.operand, c.r_target)


def compile_constraint_mask(fleet: FleetStatics, c: Constraint) -> np.ndarray:
    """bool[n_pad] mask of nodes meeting one hard constraint."""
    key = _constraint_key(c)
    mask = fleet.mask_cache.get(key)
    if mask is not None:
        return mask

    mask = np.zeros(fleet.n_pad, dtype=bool)
    if fleet.uniform and fleet.n_real and _targets_uniform(c):
        # Uniform fleet (NodeSlab-backed, shared attributes/meta/class/
        # dc): the predicate's verdict on ONE representative row holds
        # for every row — O(1) instead of a 100k-1M-node Python walk.
        mask[:fleet.n_real] = _constraint_verdict(fleet.nodes[0], c)
    else:
        for i in range(fleet.n_real):
            mask[i] = _constraint_verdict(fleet.nodes[i], c)

    fleet.mask_cache[key] = mask
    return mask


# Interpolation targets that resolve PER ROW even on a uniform fleet:
# ids and names are dense slab columns, never template-shared.
# ($node.datacenter IS covered by the uniform flag — it is only set
# when the slab's rows share one datacenter string; $attr.*/$meta.*
# read the shared template; literals and unknown $-targets are
# row-independent by construction.)
_PER_ROW_TARGETS = ("$node.id", "$node.name")


def _targets_uniform(c: Constraint) -> bool:
    return c.l_target not in _PER_ROW_TARGETS and \
        c.r_target not in _PER_ROW_TARGETS


def _constraint_verdict(node, c: Constraint) -> bool:
    l_val, ok = resolve_constraint_target(c.l_target, node)
    if not ok:
        return False
    r_val, ok = resolve_constraint_target(c.r_target, node)
    if not ok:
        return False
    return check_constraint_values(_mask_ctx, c.operand, l_val, r_val)


def compile_driver_mask(fleet: FleetStatics, driver: str) -> np.ndarray:
    """bool[n_pad] mask of nodes whose 'driver.<name>' attr parses true."""
    key = ("d", driver)
    mask = fleet.mask_cache.get(key)
    if mask is not None:
        return mask

    attr = f"driver.{driver}"
    mask = np.zeros(fleet.n_pad, dtype=bool)
    rows = range(1) if fleet.uniform and fleet.n_real \
        else range(fleet.n_real)
    for i in rows:
        value = fleet.attr_rows[i].get(attr)
        if value is not None and \
                str(value).strip().lower() in ("1", "t", "true"):
            mask[i] = True
    if fleet.uniform and fleet.n_real:
        mask[:fleet.n_real] = mask[0]

    fleet.mask_cache[key] = mask
    return mask


def compile_dc_mask(fleet: FleetStatics, datacenters: list) -> np.ndarray:
    """bool[n_pad] mask of nodes in one of the job's datacenters."""
    key = ("dc", tuple(sorted(datacenters)))
    mask = fleet.mask_cache.get(key)
    if mask is not None:
        return mask

    dc_set = set(datacenters)
    mask = np.zeros(fleet.n_pad, dtype=bool)
    if fleet.uniform and fleet.n_real:
        mask[:fleet.n_real] = fleet.datacenters[0] in dc_set
    else:
        for i in range(fleet.n_real):
            mask[i] = fleet.datacenters[i] in dc_set

    fleet.mask_cache[key] = mask
    return mask


def group_mask_key(datacenters: list, job_constraints: list,
                   tg_constraints: list, drivers) -> tuple:
    """Value-semantic cache key for a composed group mask: two task groups
    with identical constraints/drivers/datacenters share one mask row (count
    expansion makes this the common case)."""
    cons = tuple(sorted(
        (c.l_target, c.operand, c.r_target)
        for c in job_constraints + tg_constraints
        if c.hard and c.operand != CONSTRAINT_DISTINCT_HOSTS))
    return (tuple(sorted(datacenters)), cons, tuple(sorted(drivers)))


def compile_group_mask(
    fleet: FleetStatics,
    datacenters: list,
    job_constraints: list,
    tg_constraints: list,
    drivers,
) -> tuple[np.ndarray, bool]:
    """Full static feasibility mask for one task group.

    AND of: ready, datacenter, job constraints, task-group+task constraints,
    driver presence — i.e. the entire feasibility half of the iterator chain
    (reference scheduler/stack.go:126-143) as one boolean vector.

    Returns (mask, distinct_hosts?) — distinct_hosts is resolved on device.
    """
    distinct = any(
        c.hard and c.operand == CONSTRAINT_DISTINCT_HOSTS
        for c in job_constraints + tg_constraints)
    key = ("g",) + group_mask_key(datacenters, job_constraints,
                                  tg_constraints, drivers)
    hit = fleet.mask_cache.get(key)
    if hit is not None:
        return hit, distinct

    mask = fleet.ready.copy()
    mask &= compile_dc_mask(fleet, datacenters)
    for c in job_constraints + tg_constraints:
        if not c.hard or c.operand == CONSTRAINT_DISTINCT_HOSTS:
            continue
        mask &= compile_constraint_mask(fleet, c)
    for driver in sorted(drivers):
        mask &= compile_driver_mask(fleet, driver)
    fleet.mask_cache[key] = mask
    return mask, distinct
