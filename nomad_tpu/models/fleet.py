"""Fleet tensorization: the state -> HBM bridge.

Converts the host data model (Node/Allocation objects in the MVCC store)
into the device-resident tensors the TPU scheduler consumes:

  capacity  f32[N, D]   node.resources       (D = ALL_FIT_DIMS = 6)
  reserved  f32[N, D]   node.reserved
  ready     bool[N]     status == ready and not draining
  dc_codes  i32[N]      interned datacenter id

plus host-side numpy mirrors used to compile constraint masks
(nomad_tpu/models/constraints.py).  Capability parity role: this is the
TPU-native replacement for the iterator walk over memdb state in
/root/reference/scheduler/feasible.go + rank.go — instead of lazily visiting
nodes, the whole fleet is resident on device and every candidate is scored in
one dispatch.

Caching contract: the state store is copy-on-write at table granularity, so
the identity of a snapshot's frozen ``nodes`` table dict is a sound cache key
— if any node changes, the store swaps in a new dict.  ``fleet_cache`` keys
static tensors on that identity; per-eval dynamic state (usage, job counts)
is rebuilt from the allocs table (vectorized, numpy) and cached the same way.

Port/bandwidth dims are a *sound over-approximation* of the exact host-side
NetworkIndex accounting (reference nomad/structs/network.go): the device mask
never admits a node the exact check would reject on total bandwidth, and the
exact per-device/port assignment runs host-side after selection
(SURVEY.md section 7, "Network/port allocation").
"""
from __future__ import annotations

import itertools
import threading

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from nomad_tpu.structs import (
    ALL_FIT_DIMS,
    NODE_STATUS_READY,
    Allocation,
    Node,
    Resources,
)
from nomad_tpu.utils.sync import CopySwap

NDIMS = len(ALL_FIT_DIMS)  # cpu, memory_mb, disk_mb, iops, mbits, port_slots

# Dynamic port range size: the port_slots capacity over-approximation
# (reference nomad/structs/network.go:9-18 — 20000..60000 dynamic ports).
PORT_SLOTS_CAPACITY = 40000.0


def _res_vector(res: Optional[Resources]) -> np.ndarray:
    if res is None:
        return np.zeros(NDIMS, dtype=np.float32)
    return np.asarray(res.as_vector(), dtype=np.float32)


def alloc_vec(alloc: "Allocation") -> np.ndarray:
    """Cached resource vector of an allocation.  Sound because committed
    allocations are replaced, never mutated (the store immutability
    contract, tests/test_state_store.py) — a new record is a new object
    with an empty cache; dataclasses.replace()-based copies don't carry
    the cache either.

    Slab-backed allocs (structs/alloc_slab.py) read the vector straight
    from the slab's per-slot columns — shared read-only across the
    slot's rows — without materializing ``resources``; an alloc whose
    ``resources`` was already materialized (or reassigned) keeps the
    object truth."""
    d = alloc.__dict__
    vec = d.get("_res_vec")
    if vec is None:
        slab = d.get("_slab")
        if slab is not None and "resources" not in d:
            vec = slab.vec(d["_srow"])
        else:
            vec = _res_vector(alloc.resources)
        d["_res_vec"] = vec
    return vec


def _pad_to(n: int) -> int:
    """Next power of two >= n (>= 8); buckets shapes so jit caches stay hot."""
    p = 8
    while p < n:
        p *= 2
    return p


_FLEET_GEN = itertools.count()


class ShardedResidency:
    """THE residency policy for node-axis-sharded device caches.

    Every mesh-resident twin — statics capacity/reserved, per-job
    feasibility rows, the usage mirror's sharded copies — lives in one
    of these instead of a per-call-site dict: entries are keyed by
    (class, ..., mesh) where ``key[0]`` names the entry's CLASS
    ("capres" / "feas" / "usage"), bounded at ``max_resident`` entries
    PER CLASS with the whole class evicted at its bound (alternating
    fused batch shapes resolve different meshes and must not thrash
    each other below it) — class-scoped so a stream of distinct job
    versions churning feasibility entries can never evict the
    fleet-generation-lived capacity/reserved or usage twins.  Each
    entry carries its scatters-since-upload counter so incremental
    maintenance (UsageMirror) and one-shot uploads (statics) ride the
    same bookkeeping.  When a mesh is configured for a dispatch
    (parallel/mesh.dispatch_mesh), the arrays here are the PRIMARY
    device copies — the single-buffer ``device_cache`` entries serve
    only single-device platforms and host-executor evals."""

    __slots__ = ("max_resident", "_res")

    def __init__(self, max_resident: int = 4) -> None:
        self.max_resident = max_resident
        self._res: dict = {}   # key -> [arrays tuple, scatter count]

    def lookup(self, key):
        entry = self._res.get(key)
        return entry[0] if entry is not None else None

    def prepare(self, mesh, arrays, spec=None):
        """EXPLICIT sharded upload (counted) of ``arrays`` for ``mesh``
        (node axis by default; pass ``spec`` for e.g. [G, N] group-major
        rows) WITHOUT touching the residency dict — callers that serve
        readers under a lock (the usage mirror) upload through this
        outside the lock, then ``adopt`` the result under it, so no
        thread ever waits out a fleet-sized transfer behind the lock."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from nomad_tpu.parallel.devices import note_transfer
        from nomad_tpu.parallel.mesh import FLEET_AXIS
        sharding = NamedSharding(
            mesh, P(FLEET_AXIS) if spec is None else spec)
        note_transfer("h2d", len(arrays))
        return tuple(jax.device_put(a, sharding) for a in arrays)

    def adopt(self, key, arrays):
        """Make already-uploaded ``arrays`` (from ``prepare``) resident
        under ``key``; the per-class eviction bound applies here."""
        if key not in self._res:
            kind = key[0]
            same = [k for k in self._res if k[0] == kind]
            if len(same) >= self.max_resident:
                for k in same:
                    del self._res[k]
        self._res[key] = [arrays, 0]
        return arrays

    def install(self, key, mesh, arrays, spec=None):
        """prepare + adopt in one step, for callers holding no lock."""
        return self.adopt(key, self.prepare(mesh, arrays, spec=spec))

    def replace(self, key, arrays) -> None:
        """Swap a maintained entry's arrays (scatter update) and count
        the scatter against its refresh budget."""
        entry = self._res[key]
        entry[0] = arrays
        entry[1] += 1

    def scatters(self, key) -> int:
        entry = self._res.get(key)
        return entry[1] if entry is not None else 0

    def drop(self, key) -> None:
        self._res.pop(key, None)

    def clear(self) -> None:
        self._res.clear()

    def keys(self) -> list:
        return list(self._res)


@dataclass
class FleetStatics:
    """Node-static tensors + host mirrors, cached per nodes-table generation."""

    n_real: int
    n_pad: int
    node_ids: list                      # index -> node id (real rows only)
    index_of: dict                      # node id -> index
    nodes: list                         # index -> Node (host objects)
    capacity: np.ndarray                # f32[n_pad, D]
    reserved: np.ndarray                # f32[n_pad, D]
    ready: np.ndarray                   # bool[n_pad] (padding rows False)
    datacenters: np.ndarray             # object[n_pad] (host-side dc strings)
    # Host-side attribute/meta mirrors for constraint compilation:
    attr_rows: list                     # index -> node.attributes dict
    meta_rows: list                     # index -> node.meta dict
    # True when the fleet came off a NodeSlab declaring row uniformity
    # (shared attributes/meta/class/datacenter): constraint masks then
    # compile against ONE representative row and broadcast
    # (models/constraints.py) instead of walking 100k-1M nodes.
    uniform: bool = False
    mask_cache: dict = field(default_factory=dict)   # constraint-key -> bool[n_pad]
    # Device-resident mirrors, populated lazily (jax arrays).  Keys:
    # "capres" -> (capacity, reserved); ("feas", group-keys) -> bool[G, N].
    # Keeping these resident avoids re-uploading the fleet every eval —
    # at 10k nodes the feasibility matrix transfer dominates eval latency.
    device_cache: dict = field(default_factory=dict)
    # Mesh-resident twins (capacity/reserved, sharded feasibility rows)
    # behind the one residency policy; PRIMARY when a mesh is
    # configured for the dispatch.
    sharded: ShardedResidency = field(default_factory=ShardedResidency)
    # node_index -> (frozen used_ports, bw_used, bw_avail, ip, device) or
    # None: the node-static half of the fast network assigner
    # (scheduler/jax_binpack.py _node_net_init).
    net_base: dict = field(default_factory=dict)
    # Process-unique generation id: lets per-job prep caches key on the
    # fleet generation WITHOUT holding a strong ref that would pin
    # evicted generations (and their device buffers) alive.
    gen: int = field(default_factory=lambda: next(_FLEET_GEN))
    # Lazily attached incremental usage mirror (see mirror_for()).
    mirror: Optional["UsageMirror"] = None

    def device_capacity_reserved(self):
        from nomad_tpu.parallel.devices import ensure_on_default, \
            on_default_platform
        hit = self.device_cache.get("capres")
        if hit is None or not on_default_platform(hit[0]):
            hit = (ensure_on_default(None, self.capacity),
                   ensure_on_default(None, self.reserved))
            self.device_cache["capres"] = hit
        return hit

    def device_capacity_reserved_sharded(self, mesh):
        """Mesh-resident (node-axis-sharded) capacity/reserved — the
        PRIMARY copies for sharded dispatches — uploaded once per
        (fleet generation, mesh) under the unified residency policy."""
        key = ("capres", mesh)
        hit = self.sharded.lookup(key)
        if hit is None:
            hit = self.sharded.install(key, mesh,
                                       (self.capacity, self.reserved))
        return hit

    def device_feasible_sharded(self, mesh, feas_key, host: np.ndarray):
        """Mesh-resident [G, N] feasibility rows for one prep-cache
        feasibility entry, node axis sharded (group axis replicated),
        uploaded once per (feas_key, mesh) like capacity/reserved."""
        from jax.sharding import PartitionSpec as P

        from nomad_tpu.parallel.mesh import FLEET_AXIS
        key = ("feas", feas_key, mesh)
        hit = self.sharded.lookup(key)
        if hit is None:
            hit = self.sharded.install(key, mesh, (host,),
                                       spec=P(None, FLEET_AXIS))
        return hit[0]


def build_fleet(nodes: list[Node]) -> FleetStatics:
    """State -> fleet tensors.  Columnar fast path: when every node is
    an unmutated row of ONE NodeSlab (structs/node_slab.py — the
    100k-1M-node bulk-load shape), the static tensors come straight
    off the slab's dense vectors and shared template, with no per-node
    Python walk; a single mutated or foreign row falls the whole build
    back to the exact object path."""
    from nomad_tpu.structs import node_slab_of

    slab = node_slab_of(nodes)
    if slab is not None:
        return _build_fleet_slab(nodes, slab)
    n_real = len(nodes)
    n_pad = _pad_to(n_real)

    capacity = np.zeros((n_pad, NDIMS), dtype=np.float32)
    reserved = np.zeros((n_pad, NDIMS), dtype=np.float32)
    ready = np.zeros(n_pad, dtype=bool)
    datacenters = np.empty(n_pad, dtype=object)
    attr_rows, meta_rows, node_ids = [], [], []
    index_of: dict = {}

    for i, node in enumerate(nodes):
        node_ids.append(node.id)
        index_of[node.id] = i
        cap = _res_vector(node.resources)
        cap[5] = PORT_SLOTS_CAPACITY  # port_slots capacity over-approximation
        capacity[i] = cap
        reserved[i] = _res_vector(node.reserved)
        ready[i] = node.status == NODE_STATUS_READY and not node.drain
        datacenters[i] = node.datacenter
        attr_rows.append(node.attributes)
        meta_rows.append(node.meta)

    return FleetStatics(
        n_real=n_real,
        n_pad=n_pad,
        node_ids=node_ids,
        index_of=index_of,
        nodes=list(nodes),
        capacity=capacity,
        reserved=reserved,
        ready=ready,
        datacenters=datacenters,
        attr_rows=attr_rows,
        meta_rows=meta_rows,
    )


def _build_fleet_slab(nodes: list, slab) -> FleetStatics:
    """FleetStatics off one NodeSlab's columns: broadcast vectors, the
    shared attribute/meta template per row, and ``uniform=True`` when
    the slab's rows share one datacenter — the flag the constraint
    compiler uses to judge ONE representative row for the whole
    fleet."""
    n_real = slab.n
    n_pad = _pad_to(n_real)
    capacity = np.zeros((n_pad, NDIMS), dtype=np.float32)
    capacity[:n_real] = slab.capacity_vec()
    capacity[:n_real, 5] = PORT_SLOTS_CAPACITY
    reserved = np.zeros((n_pad, NDIMS), dtype=np.float32)
    reserved[:n_real] = slab.reserved_vec()
    ready = np.zeros(n_pad, dtype=bool)
    ready[:n_real] = slab.ready()
    datacenters = np.empty(n_pad, dtype=object)
    uniform = isinstance(slab.datacenters, str)
    if uniform:
        datacenters[:n_real] = slab.datacenters
    else:
        for i in range(n_real):
            datacenters[i] = slab.datacenters[i]
    attrs = slab.template.attributes
    meta = slab.template.meta
    return FleetStatics(
        n_real=n_real,
        n_pad=n_pad,
        node_ids=list(slab.ids),
        index_of={nid: i for i, nid in enumerate(slab.ids)},
        nodes=list(nodes),
        capacity=capacity,
        reserved=reserved,
        ready=ready,
        datacenters=datacenters,
        # Shared template per row: mask compilation treats these as
        # read-only (the store immutability contract), and the uniform
        # flag means it rarely reads past row 0 anyway.
        attr_rows=_SharedRows(attrs, n_real),
        meta_rows=_SharedRows(meta, n_real),
        uniform=uniform,
    )


class _SharedRows:
    """A list-shaped view serving ONE shared row dict for every index —
    the uniform fleet's attr/meta mirror without n_real pointers."""

    __slots__ = ("row", "n")

    def __init__(self, row, n: int) -> None:
        self.row = row
        self.n = n

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, i):
        if isinstance(i, int) and -self.n <= i < self.n:
            return self.row
        raise IndexError(i)


def net_base_for(statics: FleetStatics, node_index: int, node):
    """Node-static network base for the fast port/bandwidth paths:
    ``(frozen reserved-ports, reserved mbits, bandwidth capacity, ip,
    device)`` or None for topologies that need the exact NetworkIndex
    walk (multi-network nodes, unresolvable ip).  Cached on the fleet
    statics; shared by the scheduler's fast assigner
    (scheduler/jax_binpack.FastPlacementMixin) and the plan verifier
    (server/plan_apply)."""
    base_cache = statics.net_base
    base = base_cache.get(node_index, False)
    if base is not False:
        return base
    from nomad_tpu.structs.network import _cidr_ips

    base = None
    nets = [n for n in node.resources.networks if n.device] \
        if node.resources is not None else []
    if len(nets) == 1:
        n0 = nets[0]
        ip = n0.ip
        if not ip:
            for ip in _cidr_ips(n0.cidr):
                break
        if ip:
            used: set = set()
            bw_used = 0
            if node.reserved is not None:
                for rn in node.reserved.networks:
                    used.update(rn.reserved_ports)
                    bw_used += rn.mbits
            base = (frozenset(used), bw_used, n0.mbits, ip,
                    n0.device)
    base_cache[node_index] = base
    return base


# Sentinel net key for allocs whose offers span ips/devices (or carry
# in-alloc oddities): forces the exact NetworkIndex path for their node.
NET_KEY_ODD = ("__odd__", "__odd__")


def _net_row(alloc: Allocation):
    """The verifier's network row for one alloc: ``(ports, mbits,
    (ip, device))`` aggregated over the FIRST network of each task —
    exactly the set NetworkIndex.add_allocs accounts
    (structs/network.py:87-95, reference nomad/structs/network.go
    AddAllocs) — or None when the alloc reserves no network.  Offers
    spanning multiple ips or devices get NET_KEY_ODD.  Cached on the
    alloc under the same immutability contract as ``alloc_vec`` (store
    objects are replaced, never mutated) — the plan verifier reads the
    row once per verify and once per window fold."""
    d = alloc.__dict__
    row = d.get("_net_row")
    if row is not None:
        return row[0]
    slab = d.get("_slab")
    if slab is not None and "task_resources" not in d:
        # Columnar fast path: ports/mbits/(ip, device) straight from
        # the slab columns — no task_resources materialization.  The
        # slab builds exactly what _net_row_build would compute on the
        # materialized row (single-network offers by construction).
        built = slab.net_row(d["_srow"])
    else:
        built = _net_row_build(alloc)
    d["_net_row"] = (built,)
    return built


def _net_row_build(alloc: Allocation):
    ports: list = []
    mbits = 0
    key = None
    for task_res in alloc.task_resources.values():
        nets = task_res.networks
        if not nets:
            continue
        n0 = nets[0]
        ports.extend(n0.reserved_ports)
        mbits += n0.mbits
        k = (n0.ip, n0.device)
        if key is None:
            key = k
        elif k != key:
            key = NET_KEY_ODD
    if key is None and not mbits:
        return None
    return (tuple(ports), mbits, key or NET_KEY_ODD)


# Sentinel: a freshly-built mirror view whose device-usage attachment
# has not resolved yet (UsageMirror._attach_device runs outside the
# mirror lock and replaces it with a real buffer or None).  Never
# escapes view()/view_at().
_PENDING_DEVICE = object()


@dataclass
class FleetView:
    """One eval's dynamic view: statics + usage + same-job alloc counts."""

    statics: FleetStatics
    usage: np.ndarray       # f32[n_pad, D] — sum of non-terminal alloc asks
    job_counts: np.ndarray  # i32[n_pad] — proposed allocs of the eval's job
    # Set when the view came from a UsageMirror with no plan deltas:
    # usage_device is the mirror's device-resident copy of exactly `usage`,
    # so the dispatch can skip the host->device upload entirely.
    usage_device: Optional[object] = None

    def dispatch_usage(self):
        """The usage argument for a device dispatch: the resident device
        copy when the mirror has one, else the host array (uploaded by
        jit)."""
        return self.usage_device if self.usage_device is not None \
            else self.usage


def build_usage(statics: FleetStatics, allocs: list[Allocation],
                job_id: str = "") -> FleetView:
    """Aggregate per-node usage + same-job counts from an alloc list.

    Vectorized host-side: one np.add.at scatter instead of a Python loop per
    (alloc x dim).  Terminal allocs must already be filtered by the caller.
    """
    usage = np.zeros((statics.n_pad, NDIMS), dtype=np.float32)
    job_counts = np.zeros(statics.n_pad, dtype=np.int32)
    if allocs:
        idx = np.empty(len(allocs), dtype=np.int64)
        vecs = np.empty((len(allocs), NDIMS), dtype=np.float32)
        keep = 0
        for a in allocs:
            i = statics.index_of.get(a.node_id, -1)
            if i < 0:
                continue
            idx[keep] = i
            vecs[keep] = alloc_vec(a)
            if job_id and a.job_id == job_id:
                job_counts[i] += 1
            keep += 1
        np.add.at(usage, idx[:keep], vecs[:keep])
    return FleetView(statics=statics, usage=usage, job_counts=job_counts)


class UsageMirror:
    """Incremental state->HBM bridge for the dynamic half of the fleet.

    Maintains per-node aggregate usage, per-job sparse alloc counts and a
    device-resident usage copy, updated from the store's alloc changelog
    (state/store.py ``alloc_log``) with a RefreshIndex-style fence: a sync
    applies only the deltas between the mirror's allocs index and the
    snapshot's, so the eval hot path does O(changed) host work instead of
    rebuilding usage from every alloc in the store (SURVEY.md section 7
    "Incremental device state"; reference analogue: the alloc-watch feed
    of nomad/state/state_store.go:115-156).

    Concurrency: one mutator at a time (internal lock); readers take the
    current arrays by reference — sync replaces arrays copy-on-write, so
    a view handed to an in-flight eval never mutates under it.  The
    device copy is likewise never donated: a scatter allocates a new
    device buffer, so device arrays held by in-flight dispatches stay
    valid.  The mirror only moves forward: ``sync`` against a snapshot
    older than the mirror returns False and the caller falls back to a
    from-scratch ``build_usage`` for that eval.
    """

    # Re-upload the full usage tensor after this many incremental device
    # scatters, bounding float drift between host and device mirrors.
    DEVICE_REFRESH_EVERY = 2048
    # Scatter at most this many changed rows per sync; beyond it a fresh
    # upload is cheaper.
    MAX_SCATTER_ROWS = 1024

    def __init__(self, statics: FleetStatics) -> None:
        self.statics = statics
        self.usage = np.zeros((statics.n_pad, NDIMS), dtype=np.float32)
        self.job_counts: dict = {}   # job_id -> {node_index: count}
        self.alloc_rows: dict = {}   # alloc_id -> (ni, vec, job_id)
        self.index = -1
        self.rebuilds = 0            # full O(allocs) rebuilds (observability)
        self._lineage: object = None
        self._log_ref: Optional[list] = None
        self._log_pos = 0
        # Invariant: _usage_d is None or exactly equals self.usage.
        self._usage_d = None
        self._scatters_since_upload = 0
        # Mesh twins of _usage_d behind the unified residency policy
        # (ShardedResidency): node-axis-sharded resident copies — the
        # PRIMARY usage for sharded dispatches — one per mesh, bounded,
        # maintained by the same scatters as the single-device copy.
        # Invariant: every resident value exactly equals self.usage.
        self._sharded = ShardedResidency()
        # Per-node port/bandwidth tracking for the vectorized plan
        # verifier (server/plan_apply).  Disabled until sync_net() is
        # first called so scheduler-only users pay nothing; once
        # enabled, maintained incrementally by the same delta walk as
        # usage.  All keyed by node index, empties pruned:
        #   net_rows:   alloc_id -> (ni, ports, mbits, (ip, device))
        #   node_ports: ni -> {port: live count}
        #   node_dup:   ni -> number of ports with count > 1
        #   node_bw:    ni -> sum of live offer mbits
        #   node_net_keys: ni -> {(ip, device): count} (NET_KEY_ODD rows
        #                  force the exact path for their node)
        self._net_ready = False
        self.net_rows: dict = {}
        self.node_ports: dict = {}
        self.node_dup: dict = {}
        self.node_bw: dict = {}
        self.node_net_keys: dict = {}
        # Reentrant so a caller can hold the mirror across a composite
        # read (sync_net + the plan verifier's verdict loop) while the
        # internal sync paths re-acquire: the net dicts are mutated in
        # place by _apply_deltas, so unlike the copy-on-write usage
        # array they must not be read unlocked.
        self._lock = threading.RLock()
        # Published fence (index, lineage, net_ready): ONE CopySwap
        # tuple rebound under the lock by _publish_fence, read
        # lock-free by the sync fast paths — an already-current caller
        # must never block behind another thread's O(allocs) rebuild.
        # (This replaces the three bare-read allowlist waivers the old
        # unlocked index/_lineage/_net_ready reads carried: the
        # contract now lives in the annotation the lint enforces.)
        self._fence: CopySwap = (-1, None, False)

    @property
    def lock(self):
        """Hold this across any multi-step read of the in-place-mutated
        net structures (node_ports/node_net_keys/net_rows/alloc_rows);
        the usage array itself is replaced copy-on-write and may be
        taken by reference."""
        return self._lock

    # -- sync --------------------------------------------------------------
    def _current(self, t) -> bool:
        """True when the mirror already matches this generation.  The
        fence is the monotonic allocs raft index plus the store lineage
        token — NOT table-dict identity, because the store mutates tables
        in place when no snapshot shares them.  The lineage token changes
        on snapshot restore (which can replace the world without raising
        the index); it survives clones and changelog compaction."""
        return (self.index == t.indexes["allocs"]
                and self._lineage is t.lineage)

    def _sync_locked(self, t) -> bool:
        if self._current(t):
            return True
        target = t.indexes["allocs"]
        if self._lineage is t.lineage and self.index > target:
            return False
        table = t.tables["allocs"]
        log = t.alloc_log
        # A new log list under the SAME lineage can only be compaction
        # (the kept tail retains every entry above alloc_log_base), so
        # scanning it from position 0 is sound.
        if self.index < 0 or self.index < t.alloc_log_base or \
                self._lineage is not t.lineage:
            self._rebuild(table)
        else:
            changed = self._changed_ids(log, target)
            if changed:
                self._apply_deltas(table, changed)
        self.index = target
        self._lineage = t.lineage
        self._log_ref = log
        self._log_pos = self._position_after(log, target)
        self._publish_fence()
        return True

    def _publish_fence(self) -> None:
        """Rebind the lock-free fence tuple (called under the lock
        after any index/lineage/net_ready move)."""
        self._fence = (self.index, self._lineage, self._net_ready)

    def sync(self, state) -> bool:
        """Bring the mirror to ``state``'s allocs table (store or
        snapshot).  O(changed allocs) when the changelog covers the gap;
        full rebuild otherwise.  Returns False (mirror untouched) when the
        snapshot is older than the mirror — the mirror is monotonic.

        Already-current fast path: one lock-free read of the CopySwap
        fence tuple — a caller whose snapshot the mirror already covers
        must return immediately even while another thread holds the
        lock through a full O(allocs) rebuild (the old per-attribute
        double-checked reads provided this; the fence keeps it without
        their waivers)."""
        t = state._t
        index, lineage, _net = self._fence
        if index == t.indexes["allocs"] and lineage is t.lineage:
            return True
        with self._lock:
            return self._sync_locked(t)

    def sync_net(self, state) -> bool:
        """sync() plus per-node port/bandwidth tracking: enabled (full
        net rebuild) on first call, maintained incrementally by every
        later sync.  Same monotonicity and fast-path contract as
        sync()."""
        t = state._t
        index, lineage, net_ready = self._fence
        if net_ready and index == t.indexes["allocs"] and \
                lineage is t.lineage:
            return True
        with self._lock:
            ok = self._sync_locked(t)
            if ok and not self._net_ready:
                self._rebuild_net(t.tables["allocs"])
                self._net_ready = True
                self._publish_fence()
            return ok

    def _changed_ids(self, log: list, target: int) -> set:
        start = self._log_pos if log is self._log_ref else 0
        changed: set = set()
        n = len(log)
        for i in range(start, n):
            idx, ids = log[i]
            if idx <= self.index:
                continue
            if idx > target:
                break
            changed.update(ids)
        return changed

    @staticmethod
    def _position_after(log: list, target: int) -> int:
        n = len(log)
        pos = n
        while pos > 0 and log[pos - 1][0] > target:
            pos -= 1
        return pos

    def _rebuild(self, table: dict) -> None:
        statics = self.statics
        index_of = statics.index_of
        usage = np.zeros((statics.n_pad, NDIMS), dtype=np.float32)
        job_counts: dict = {}
        rows: dict = {}
        for alloc in table.values():
            if alloc.terminal_status():
                continue
            ni = index_of.get(alloc.node_id, -1)
            if ni < 0:
                continue
            vec = alloc_vec(alloc)
            usage[ni] += vec
            job_counts.setdefault(alloc.job_id, {})[ni] = \
                job_counts.get(alloc.job_id, {}).get(ni, 0) + 1
            rows[alloc.id] = (ni, vec, alloc.job_id)
        self.usage = usage
        self.job_counts = job_counts
        self.alloc_rows = rows
        self.rebuilds += 1
        self._usage_d = None
        self._sharded.clear()
        if self._net_ready:
            self._rebuild_net(table)

    # -- net tracking (vectorized plan verifier) ---------------------------
    def _rebuild_net(self, table: dict) -> None:
        index_of = self.statics.index_of
        self.net_rows = {}
        self.node_ports = {}
        self.node_dup = {}
        self.node_bw = {}
        self.node_net_keys = {}
        for alloc in table.values():
            if alloc.terminal_status():
                continue
            ni = index_of.get(alloc.node_id, -1)
            if ni < 0:
                continue
            self._net_add(alloc.id, ni, alloc)

    def _net_add(self, aid: str, ni: int, alloc: Allocation) -> None:
        row = _net_row(alloc)
        if row is None:
            return
        ports, mbits, key = row
        self.net_rows[aid] = (ni, ports, mbits, key)
        if mbits:
            self.node_bw[ni] = self.node_bw.get(ni, 0) + mbits
        keys = self.node_net_keys.setdefault(ni, {})
        keys[key] = keys.get(key, 0) + 1
        if ports:
            pc = self.node_ports.setdefault(ni, {})
            dup = 0
            for p in ports:
                c = pc.get(p, 0) + 1
                pc[p] = c
                if c == 2:
                    dup += 1
            if dup:
                self.node_dup[ni] = self.node_dup.get(ni, 0) + dup

    def _net_remove(self, aid: str) -> None:
        row = self.net_rows.pop(aid, None)
        if row is None:
            return
        ni, ports, mbits, key = row
        if mbits:
            bw = self.node_bw.get(ni, 0) - mbits
            if bw:
                self.node_bw[ni] = bw
            else:
                self.node_bw.pop(ni, None)
        keys = self.node_net_keys.get(ni)
        if keys is not None:
            c = keys.get(key, 0) - 1
            if c > 0:
                keys[key] = c
            else:
                keys.pop(key, None)
                if not keys:
                    self.node_net_keys.pop(ni, None)
        if ports:
            pc = self.node_ports.get(ni)
            if pc is not None:
                dup = 0
                for p in ports:
                    c = pc.get(p, 0) - 1
                    if c > 0:
                        pc[p] = c
                        if c == 1:
                            dup += 1
                    else:
                        pc.pop(p, None)
                if dup:
                    d = self.node_dup.get(ni, 0) - dup
                    if d > 0:
                        self.node_dup[ni] = d
                    else:
                        self.node_dup.pop(ni, None)
                if not pc:
                    self.node_ports.pop(ni, None)

    def _apply_deltas(self, table: dict, changed: set) -> None:
        statics = self.statics
        index_of = statics.index_of
        # Copy-on-write so views handed to in-flight evals stay frozen.
        usage = self.usage.copy()
        touched_rows: set = set()
        touched_jobs: dict = {}
        for aid in changed:
            old = self.alloc_rows.get(aid)
            if old is not None:
                ni, vec, jid = old
                usage[ni] -= vec
                jc = touched_jobs.get(jid)
                if jc is None:
                    jc = touched_jobs[jid] = dict(
                        self.job_counts.get(jid, ()))
                jc[ni] = jc.get(ni, 0) - 1
                del self.alloc_rows[aid]
                touched_rows.add(ni)
            if self._net_ready:
                self._net_remove(aid)
            new = table.get(aid)
            if new is not None and not new.terminal_status():
                ni = index_of.get(new.node_id, -1)
                if ni < 0:
                    continue
                vec = alloc_vec(new)
                usage[ni] += vec
                jid = new.job_id
                jc = touched_jobs.get(jid)
                if jc is None:
                    jc = touched_jobs[jid] = dict(
                        self.job_counts.get(jid, ()))
                jc[ni] = jc.get(ni, 0) + 1
                self.alloc_rows[aid] = (ni, vec, jid)
                touched_rows.add(ni)
                if self._net_ready:
                    self._net_add(aid, ni, new)
        for jid, jc in touched_jobs.items():
            jc = {ni: c for ni, c in jc.items() if c > 0}
            if jc:
                self.job_counts[jid] = jc
            else:
                self.job_counts.pop(jid, None)
        self._update_device(usage, touched_rows)
        self.usage = usage

    # -- device mirror -----------------------------------------------------
    def _update_device(self, new_usage: np.ndarray,
                       touched_rows: set) -> None:
        """Keep the device copies (single-device and mesh-sharded) equal
        to the (about-to-be-installed) host usage: scatter the touched
        rows, or drop a copy when a fresh upload is cheaper.  Called
        under the lock from _apply_deltas."""
        sharded = self._sharded
        if self._usage_d is None and not sharded.keys():
            return
        big = len(touched_rows) > self.MAX_SCATTER_ROWS
        idx = rows = None
        if not big:
            idx = np.fromiter(touched_rows, dtype=np.int32,
                              count=len(touched_rows))
            rows = new_usage[idx]
        if self._usage_d is not None:
            if big or self._scatters_since_upload >= \
                    self.DEVICE_REFRESH_EVERY:
                self._usage_d = None
            else:
                self._usage_d = _scatter_rows(self._usage_d, idx, rows)
                self._scatters_since_upload += 1
        for key in sharded.keys():
            if big or sharded.scatters(key) >= self.DEVICE_REFRESH_EVERY:
                sharded.drop(key)
            else:
                (buf,) = sharded.lookup(key)
                sharded.replace(key, (_scatter_rows(buf, idx, rows),))

    def device_usage(self):
        """Device-resident copy of the mirror's usage (uploaded on first
        use, then scatter-maintained alongside every host delta).

        The upload itself happens OUTSIDE the mirror lock: at 131k+
        nodes the full usage tensor is fleet-sized, and holding the lock
        across its host->device copy would park every worker's sync and
        view build behind one thread's transfer (devlint
        transfer-under-lock — the analyzer finding that restructured
        this path).  The install is revalidated under the lock exactly
        ONCE — a mirror that moved on mid-upload just gets the fresh
        copy of the snapshot we read, uninstalled (a retry loop would
        re-upload a fleet-sized tensor per lost race under a sustained
        commit stream)."""
        from nomad_tpu.parallel.devices import on_default_platform, \
            put_counted
        with self._lock:
            host = self.usage
            buf = self._usage_d
        if buf is not None and on_default_platform(buf):
            return buf
        fresh = put_counted(host)
        with self._lock:
            if self.usage is host and (
                    self._usage_d is None or
                    not on_default_platform(self._usage_d)):
                self._usage_d = fresh
                self._scatters_since_upload = 0
        return fresh

    def _attach_device(self, view: "FleetView") -> "FleetView":
        """Resolve a view's pending device-usage attachment (set by
        _view_locked when the view rides the mirror's own array): reuse
        the resident copy, or upload one OUTSIDE the lock and install it
        when the mirror hasn't moved.  Either way the view gets a device
        copy of exactly ITS snapshot array."""
        if view is None or view.usage_device is not _PENDING_DEVICE:
            return view
        view.usage_device = None
        from nomad_tpu.parallel.devices import on_default_platform, \
            put_counted
        host = view.usage
        with self._lock:
            buf = self._usage_d if self.usage is host else None
        if buf is not None and on_default_platform(buf):
            view.usage_device = buf
            return view
        fresh = put_counted(host)
        with self._lock:
            if self.usage is host and (
                    self._usage_d is None or
                    not on_default_platform(self._usage_d)):
                self._usage_d = fresh
                self._scatters_since_upload = 0
        view.usage_device = fresh
        return view

    def device_usage_sharded(self, mesh, expect_usage):
        """Mesh-resident (node-axis-sharded) copy of the mirror's usage
        — the PRIMARY usage for a sharded dispatch — or None when the
        mirror has moved past the caller's view (``expect_usage`` is
        the view's host array — the caller must then upload it itself).
        Uploaded on first use PER MESH under the unified residency
        policy (alternating fused batch sizes get different meshes and
        must not thrash each other), scatter-maintained alongside
        every host delta like the single-device copy.  The upload runs
        OUTSIDE the mirror lock (ShardedResidency.prepare/adopt) for
        the same reason as device_usage: a fleet-sized sharded upload
        must not serialize every other worker's sync."""
        key = ("usage", mesh)
        with self._lock:
            if self.usage is not expect_usage:
                return None
            hit = self._sharded.lookup(key)
            if hit is not None:
                return hit[0]
        arrays = self._sharded.prepare(mesh, (expect_usage,))
        with self._lock:
            if self.usage is not expect_usage:
                # Moved past us mid-upload: the copy no longer matches
                # the mirror; the caller falls back to its own view.
                return None
            hit = self._sharded.lookup(key)
            if hit is None:
                hit = self._sharded.adopt(key, arrays)
            return hit[0]

    def window_lease(self, mesh):
        """Residency LEASE for a window verify: the mesh-resident usage
        twin for the mirror's CURRENT generation, or None when it is not
        resident.  Must be called under ``self.lock`` — the lease rule
        is that a verify reads a consistent generation WITHOUT copying
        under the mirror lock: resident twins are maintained exactly
        equal to ``self.usage`` by _update_device, device arrays are
        immutable (a later sync REPLACES the twin, never mutates it),
        so the returned array stays valid for the whole window after
        the lock releases.  Never uploads (that would be a fleet-sized
        transfer under the lock — devlint transfer-under-lock); cold
        callers warm the twin through device_usage_sharded OUTSIDE the
        lock and take the lease on a later window."""
        hit = self._sharded.lookup(("usage", mesh))
        return hit[0] if hit is not None else None

    # -- views -------------------------------------------------------------
    def _view_locked(self, plan, job_id: str) -> FleetView:
        statics = self.statics
        jc_dense = np.zeros(statics.n_pad, dtype=np.int32)
        sparse = self.job_counts.get(job_id)
        if sparse:
            for ni, c in sparse.items():
                jc_dense[ni] = c
        usage = self.usage
        deltas = plan is not None and \
            (plan.node_update or plan.node_allocation)
        if not deltas:
            # The device copy is attached OUTSIDE the lock
            # (_attach_device): the sentinel marks the view as riding
            # the mirror's own array, so the attachment can validate
            # against it after the upload.
            return FleetView(statics=statics, usage=usage,
                             job_counts=jc_dense,
                             usage_device=_PENDING_DEVICE)
        usage = usage.copy()
        index_of = statics.index_of
        for updates in plan.node_update.values():
            for alloc in updates:
                row = self.alloc_rows.get(alloc.id)
                if row is None:
                    continue
                ni, vec, jid = row
                usage[ni] -= vec
                if jid == job_id:
                    jc_dense[ni] -= 1
        for placements in plan.node_allocation.values():
            for alloc in placements:
                ni = index_of.get(alloc.node_id, -1)
                if ni < 0:
                    continue
                usage[ni] += alloc_vec(alloc)
                if alloc.job_id == job_id:
                    jc_dense[ni] += 1
        return FleetView(statics=statics, usage=usage,
                         job_counts=jc_dense)

    def view(self, plan, job_id: str) -> FleetView:
        """A FleetView for one eval: mirror base plus the eval's in-flight
        plan deltas (EvalContext.ProposedAllocs semantics, reference
        scheduler/context.go:96-126, fleet-wide)."""
        with self._lock:
            view = self._view_locked(plan, job_id)
        return self._attach_device(view)

    def view_at(self, state, plan, job_id: str) -> Optional[FleetView]:
        """Atomically sync to ``state`` and build a view under one lock
        hold, so a concurrent worker cannot advance the mirror between
        the sync and the view (the view must reflect exactly this eval's
        snapshot).  Returns None when the snapshot is older than the
        mirror — the caller falls back to a from-scratch build.  The
        view's device-usage attachment resolves after the lock releases
        (_attach_device) so the first-use upload never serializes other
        workers' syncs."""
        t = state._t
        with self._lock:
            if not self._sync_locked(t):
                return None
            view = self._view_locked(plan, job_id)
        return self._attach_device(view)


_mirror_create_lock = threading.Lock()


def mirror_for(statics: FleetStatics) -> UsageMirror:
    """The one UsageMirror attached to a fleet generation (created on
    first use; a new fleet generation starts a fresh mirror)."""
    mirror = statics.mirror
    if mirror is None:
        with _mirror_create_lock:
            mirror = statics.mirror
            if mirror is None:
                mirror = statics.mirror = UsageMirror(statics)
    return mirror


def _scatter_rows(usage_d, idx: np.ndarray, rows: np.ndarray):
    """Asynchronous device scatter: overwrite the touched rows.  NOT
    donating: in-flight dispatches may still hold the previous buffer.

    The batch is padded to a power-of-two row count (pad entries rewrite
    row idx[0] with its own value — a no-op) so the jit compiles at most
    log2(N) signatures instead of one per distinct delta size: commit
    streams change a different number of rows every sync, and an XLA
    compile per size (~0.5s) would dwarf the scatter itself.

    The idx/rows update batch is placed EXPLICITLY (counted, replicated
    on the buffer's own sharding mesh when the target is a mesh twin):
    left to jit it was an implicit per-sync transfer — invisible to the
    odometer and rejected by the transfer-guard sanitizer.  This runs
    under the mirror lock by design: the scatter is a bounded
    (<= MAX_SCATTER_ROWS) async dispatch that must stay atomic with the
    host-array swap so the `_usage_d == usage` invariant holds.
    """
    n = len(idx)
    if n == 0:
        return usage_d
    padded = 1 << int(n - 1).bit_length()
    if padded != n:
        pad = padded - n
        idx = np.concatenate([idx, np.repeat(idx[:1], pad)])
        rows = np.concatenate([rows, np.repeat(rows[:1], pad, axis=0)])
    import jax

    from nomad_tpu.parallel.devices import note_transfer
    sharding = getattr(usage_d, "sharding", None)
    mesh = getattr(sharding, "mesh", None)
    note_transfer("h2d", 2)
    if mesh is not None and getattr(mesh, "axis_names", None):
        from jax.sharding import NamedSharding, PartitionSpec as P
        target = NamedSharding(mesh, P())  # replicated update batch
    else:
        from nomad_tpu.parallel.devices import default_device
        target = default_device()
    # devlint-ok(transfer-under-lock): bounded async update batch; must
    # stay atomic with the host swap (see docstring).
    idx_d, rows_d = jax.device_put(idx, target), jax.device_put(rows, target)
    return _ensure_scatter_jit()(usage_d, idx_d, rows_d)


def _scatter_jit_impl(usage, idx, rows):
    return usage.at[idx].set(rows)


_scatter_rows_jit = None


def _ensure_scatter_jit():
    global _scatter_rows_jit
    if _scatter_rows_jit is None:
        import jax
        _scatter_rows_jit = jax.jit(_scatter_jit_impl)
    return _scatter_rows_jit


class FleetCache:
    """Caches FleetStatics per nodes-table generation.  Sound because the
    MVCC store is copy-on-write: a frozen table dict is never mutated, only
    swapped."""

    def __init__(self, max_entries: int = 4) -> None:
        self.max_entries = max_entries
        self._statics: dict = {}

    def _table(self, state, table: str):
        t = getattr(state, "_t", None)
        if t is None:
            return None
        return t.tables[table]

    def statics_for(self, state) -> FleetStatics:
        table = self._table(state, "nodes")
        if table is not None:
            hit = self._statics.get(id(table))
            # Keep the keyed dict alive inside the entry so its id() cannot
            # be reused by a different dict while cached.
            if hit is not None and hit[0] is table:
                return hit[1]
        fleet = build_fleet(list(state.nodes()))
        if table is not None:
            if len(self._statics) >= self.max_entries:
                self._statics.clear()
            self._statics[id(table)] = (table, fleet)
        return fleet


fleet_cache = FleetCache()
