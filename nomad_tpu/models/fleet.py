"""Fleet tensorization: the state -> HBM bridge.

Converts the host data model (Node/Allocation objects in the MVCC store)
into the device-resident tensors the TPU scheduler consumes:

  capacity  f32[N, D]   node.resources       (D = ALL_FIT_DIMS = 6)
  reserved  f32[N, D]   node.reserved
  ready     bool[N]     status == ready and not draining
  dc_codes  i32[N]      interned datacenter id

plus host-side numpy mirrors used to compile constraint masks
(nomad_tpu/models/constraints.py).  Capability parity role: this is the
TPU-native replacement for the iterator walk over memdb state in
/root/reference/scheduler/feasible.go + rank.go — instead of lazily visiting
nodes, the whole fleet is resident on device and every candidate is scored in
one dispatch.

Caching contract: the state store is copy-on-write at table granularity, so
the identity of a snapshot's frozen ``nodes`` table dict is a sound cache key
— if any node changes, the store swaps in a new dict.  ``fleet_cache`` keys
static tensors on that identity; per-eval dynamic state (usage, job counts)
is rebuilt from the allocs table (vectorized, numpy) and cached the same way.

Port/bandwidth dims are a *sound over-approximation* of the exact host-side
NetworkIndex accounting (reference nomad/structs/network.go): the device mask
never admits a node the exact check would reject on total bandwidth, and the
exact per-device/port assignment runs host-side after selection
(SURVEY.md section 7, "Network/port allocation").
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from nomad_tpu.structs import (
    ALL_FIT_DIMS,
    NODE_STATUS_READY,
    Allocation,
    Node,
    Resources,
)

NDIMS = len(ALL_FIT_DIMS)  # cpu, memory_mb, disk_mb, iops, mbits, port_slots

# Dynamic port range size: the port_slots capacity over-approximation
# (reference nomad/structs/network.go:9-18 — 20000..60000 dynamic ports).
PORT_SLOTS_CAPACITY = 40000.0


def _res_vector(res: Optional[Resources]) -> np.ndarray:
    if res is None:
        return np.zeros(NDIMS, dtype=np.float32)
    return np.asarray(res.as_vector(), dtype=np.float32)


def _pad_to(n: int) -> int:
    """Next power of two >= n (>= 8); buckets shapes so jit caches stay hot."""
    p = 8
    while p < n:
        p *= 2
    return p


@dataclass
class FleetStatics:
    """Node-static tensors + host mirrors, cached per nodes-table generation."""

    n_real: int
    n_pad: int
    node_ids: list                      # index -> node id (real rows only)
    index_of: dict                      # node id -> index
    nodes: list                         # index -> Node (host objects)
    capacity: np.ndarray                # f32[n_pad, D]
    reserved: np.ndarray                # f32[n_pad, D]
    ready: np.ndarray                   # bool[n_pad] (padding rows False)
    datacenters: np.ndarray             # object[n_pad] (host-side dc strings)
    # Host-side attribute/meta mirrors for constraint compilation:
    attr_rows: list                     # index -> node.attributes dict
    meta_rows: list                     # index -> node.meta dict
    mask_cache: dict = field(default_factory=dict)   # constraint-key -> bool[n_pad]
    # Device-resident mirrors, populated lazily (jax arrays).  Keys:
    # "capres" -> (capacity, reserved); ("feas", group-keys) -> bool[G, N].
    # Keeping these resident avoids re-uploading the fleet every eval —
    # at 10k nodes the feasibility matrix transfer dominates eval latency.
    device_cache: dict = field(default_factory=dict)
    # node_index -> (frozen used_ports, bw_used, bw_avail, ip, device) or
    # None: the node-static half of the fast network assigner
    # (scheduler/jax_binpack.py _node_net_init).
    net_base: dict = field(default_factory=dict)

    def device_capacity_reserved(self):
        hit = self.device_cache.get("capres")
        if hit is None:
            import jax
            hit = (jax.device_put(self.capacity), jax.device_put(self.reserved))
            self.device_cache["capres"] = hit
        return hit


def build_fleet(nodes: list[Node]) -> FleetStatics:
    n_real = len(nodes)
    n_pad = _pad_to(n_real)

    capacity = np.zeros((n_pad, NDIMS), dtype=np.float32)
    reserved = np.zeros((n_pad, NDIMS), dtype=np.float32)
    ready = np.zeros(n_pad, dtype=bool)
    datacenters = np.empty(n_pad, dtype=object)
    attr_rows, meta_rows, node_ids = [], [], []
    index_of: dict = {}

    for i, node in enumerate(nodes):
        node_ids.append(node.id)
        index_of[node.id] = i
        cap = _res_vector(node.resources)
        cap[5] = PORT_SLOTS_CAPACITY  # port_slots capacity over-approximation
        capacity[i] = cap
        reserved[i] = _res_vector(node.reserved)
        ready[i] = node.status == NODE_STATUS_READY and not node.drain
        datacenters[i] = node.datacenter
        attr_rows.append(node.attributes)
        meta_rows.append(node.meta)

    return FleetStatics(
        n_real=n_real,
        n_pad=n_pad,
        node_ids=node_ids,
        index_of=index_of,
        nodes=list(nodes),
        capacity=capacity,
        reserved=reserved,
        ready=ready,
        datacenters=datacenters,
        attr_rows=attr_rows,
        meta_rows=meta_rows,
    )


@dataclass
class FleetView:
    """One eval's dynamic view: statics + usage + same-job alloc counts."""

    statics: FleetStatics
    usage: np.ndarray       # f32[n_pad, D] — sum of non-terminal alloc asks
    job_counts: np.ndarray  # i32[n_pad] — proposed allocs of the eval's job


def build_usage(statics: FleetStatics, allocs: list[Allocation],
                job_id: str = "") -> FleetView:
    """Aggregate per-node usage + same-job counts from an alloc list.

    Vectorized host-side: one np.add.at scatter instead of a Python loop per
    (alloc x dim).  Terminal allocs must already be filtered by the caller.
    """
    usage = np.zeros((statics.n_pad, NDIMS), dtype=np.float32)
    job_counts = np.zeros(statics.n_pad, dtype=np.int32)
    if allocs:
        idx = np.empty(len(allocs), dtype=np.int64)
        vecs = np.empty((len(allocs), NDIMS), dtype=np.float32)
        keep = 0
        for a in allocs:
            i = statics.index_of.get(a.node_id, -1)
            if i < 0:
                continue
            idx[keep] = i
            vecs[keep] = _res_vector(a.resources)
            if job_id and a.job_id == job_id:
                job_counts[i] += 1
            keep += 1
        np.add.at(usage, idx[:keep], vecs[:keep])
    return FleetView(statics=statics, usage=usage, job_counts=job_counts)


class FleetCache:
    """Caches FleetStatics per nodes-table generation.  Sound because the
    MVCC store is copy-on-write: a frozen table dict is never mutated, only
    swapped."""

    def __init__(self, max_entries: int = 4) -> None:
        self.max_entries = max_entries
        self._statics: dict = {}

    def _table(self, state, table: str):
        t = getattr(state, "_t", None)
        if t is None:
            return None
        return t.tables[table]

    def statics_for(self, state) -> FleetStatics:
        table = self._table(state, "nodes")
        if table is not None:
            hit = self._statics.get(id(table))
            # Keep the keyed dict alive inside the entry so its id() cannot
            # be reused by a different dict while cached.
            if hit is not None and hit[0] is table:
                return hit[1]
        fleet = build_fleet(list(state.nodes()))
        if table is not None:
            if len(self._statics) >= self.max_entries:
                self._statics.clear()
            self._statics[id(table)] = (table, fleet)
        return fleet


fleet_cache = FleetCache()
