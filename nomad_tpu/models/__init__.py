"""Device-facing models: fleet tensorization + constraint compilation."""
from .fleet import FleetStatics, FleetView, build_fleet, fleet_cache  # noqa: F401
from .constraints import compile_group_mask  # noqa: F401
