"""Multi-chip parallelism: mesh construction + node-axis sharding."""
from .mesh import (  # noqa: F401
    fleet_mesh,
    place_sequence_sharded,
    shard_fleet_arrays,
)
