"""Device-mesh scaling for the scheduler: shard the node axis over ICI.

This is the structural cousin of sequence parallelism for a scheduler
workload (SURVEY.md section 5, "Long-context"): the problem dimension that
grows is the fleet (nodes x task groups), so the node axis of every fleet
tensor is sharded across a 1-D ``jax.sharding.Mesh``.  Per-shard work is the
elementwise fit/score math; the argmax winner is reduced across devices by
XLA-inserted collectives riding ICI — no hand-written NCCL/MPI, no host
round-trips (the reference scales this dimension with iterator laziness +
LimitIterator truncation, scheduler/stack.go:106-117; we scale it with
hardware).

Multi-slice/multi-host: the same jit runs under multi-host jax with a mesh
spanning slices; DCN carries only the (tiny) replicated ask/choice tensors,
ICI the sharded fleet math.
"""
from __future__ import annotations

import contextlib
import os

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu.ops.binpack import _place_rounds, _place_sequence

FLEET_AXIS = "fleet"
LANE_AXIS = "lanes"

# -- mesh resolution: the ONE authority ------------------------------------
# Every dispatch that *could* shard the node axis asks dispatch_mesh();
# the answer is a property of the platform (device count) and the
# dispatch shape, overridable by NOMAD_TPU_MESH so a bench or operator
# can force the single-device twin ("off"/"0") or cap the device count
# (an integer) without editing code — the same lever shape as
# NOMAD_TPU_EXECUTOR (scheduler/executor.py).

ENV_VAR = "NOMAD_TPU_MESH"

_MESH_CACHE: dict = {}
# Process override installed by mesh_override(); a one-element holder so
# readers never see a torn update.
_OVERRIDE: list = [None]


def _mesh_policy():
    """Resolved policy: "off", "auto", or an int device cap."""
    value = _OVERRIDE[0]
    if value is None:
        value = os.environ.get(ENV_VAR, "auto")
    value = str(value).strip().lower() or "auto"
    if value in ("off", "none", "0"):
        return "off"
    if value.isdigit():
        return int(value)
    return "auto"


@contextlib.contextmanager
def mesh_override(value):
    """Temporarily force the mesh policy ("off", "auto", or a device
    count) — the bench's unsharded twins and the tier-1 parity rigs
    compare sharded against single-device runs through this."""
    prior = _OVERRIDE[0]
    _OVERRIDE[0] = value
    try:
        yield
    finally:
        _OVERRIDE[0] = prior


def dispatch_mesh(n_lanes: int, n_pad: int):
    """Mesh for a dispatch of ``n_lanes`` evals over an ``n_pad``-wide
    (power-of-two padded) node axis, or None when one device (or the
    "off" policy, or a lane/device shape that cannot split) makes the
    plain jit the right call.

    Lane ways = largest power of two dividing n_lanes, capped at half
    the devices so the fleet axis keeps width; remaining devices shard
    the node axis, capped at n_pad so the sharding always divides it.
    ``n_lanes == 1`` therefore resolves a pure 1-D fleet mesh — the
    single-eval scheduler path — and multi-lane dispatches get the 2-D
    ``(lanes, fleet)`` storm layout when the shape splits.  Devices
    resolve through parallel/devices.default_platform_devices so the
    mesh always lives on the pinned platform."""
    policy = _mesh_policy()
    if policy == "off":
        return None
    from nomad_tpu.parallel.devices import default_platform_devices

    all_devices = default_platform_devices()
    n_dev = len(all_devices)
    if isinstance(policy, int):
        n_dev = min(n_dev, policy)
    if n_dev < 2:
        return None
    n = 1 << (n_dev.bit_length() - 1)  # power-of-two subset
    lane_ways = 1
    while lane_ways * 2 <= min(n // 2, n_lanes) and \
            n_lanes % (lane_ways * 2) == 0:
        lane_ways *= 2
    # Fleet ways must divide the padded node axis (both powers of two,
    # so <= suffices); tiny fleets on big hosts use fewer devices.
    n = min(n, lane_ways * max(1, n_pad))
    if n < 2:
        return None
    key = (all_devices[0].platform, n, lane_ways)
    mesh = _MESH_CACHE.get(key)
    if mesh is None:
        devices = all_devices[:n]
        mesh = storm_mesh(lane_ways, devices) if lane_ways > 1 \
            else fleet_mesh(devices)
        _MESH_CACHE[key] = mesh
    return mesh


def fleet_mesh(devices=None) -> Mesh:
    """1-D mesh over the default platform's (or the given) devices;
    axis name 'fleet'."""
    if devices is None:
        from nomad_tpu.parallel.devices import default_platform_devices
        devices = default_platform_devices()
    return Mesh(np.asarray(devices), (FLEET_AXIS,))


def storm_mesh(lane_ways: int, devices=None) -> Mesh:
    """2-D mesh ``(lanes, fleet)``: storm lanes data-parallel across one
    axis, the node axis sharded across the other.

    This is the scheduler's DP x "context-parallel" layout: each
    lane-axis slice holds a fleet replica serving B/lane_ways evals, so
    storm throughput scales with lane_ways while per-device fleet memory
    still shrinks by the fleet-axis factor.  With lane_ways=1 this is
    fleet_mesh semantics on a 2-D mesh."""
    if devices is None:
        from nomad_tpu.parallel.devices import default_platform_devices
        devices = default_platform_devices()
    n = len(devices)
    if lane_ways <= 0 or n % lane_ways:
        raise ValueError(
            f"lane_ways {lane_ways} must divide device count {n}")
    grid = np.asarray(devices).reshape(lane_ways, n // lane_ways)
    return Mesh(grid, (LANE_AXIS, FLEET_AXIS))


def _put(x, sharding):
    """device_put that skips arrays already resident with the target
    sharding — the seam that lets mesh-resident fleet tensors (the
    sharded usage mirror, cached capacity/reserved) flow into the
    sharded kernels without a per-dispatch upload.  Placements that DO
    happen are explicit and counted (parallel/devices transfer
    odometer): the sharded kernels below route every operand through
    here, so a sharded dispatch performs zero implicit transfers."""
    if getattr(x, "sharding", None) == sharding:
        return x
    from nomad_tpu.parallel.devices import classify_move, note_transfer
    if isinstance(x, jax.Array):
        src = next(iter(x.devices())).platform
        try:
            dst = next(iter(sharding.device_set)).platform
        except Exception:
            dst = src
        kind = classify_move(src, dst)
    else:
        kind = "h2d"
    note_transfer(kind)
    return jax.device_put(x, sharding)


def _shardings(mesh: Mesh):
    node = NamedSharding(mesh, P(FLEET_AXIS))          # [N, ...] row-sharded
    group_node = NamedSharding(mesh, P(None, FLEET_AXIS))  # [G, N]
    repl = NamedSharding(mesh, P())
    return node, group_node, repl


def _batch_shardings(mesh: Mesh):
    """Lane-axis-aware shardings for the storm layouts: on a 1-D fleet
    mesh lanes are replicated work descriptors; on a 2-D storm_mesh the
    leading (eval) axis shards over LANE_AXIS so independent evals run
    data-parallel.  Fleet-static tensors use P(FLEET_AXIS) either way —
    on the 2-D mesh that means replicated across lanes, sharded on
    nodes, which is exactly the storm's sharing pattern."""
    lane_ax = LANE_AXIS if LANE_AXIS in mesh.axis_names else None
    node = NamedSharding(mesh, P(FLEET_AXIS))
    lane_node = NamedSharding(mesh, P(lane_ax, None, FLEET_AXIS))  # [B,G,N]
    lane_n = NamedSharding(mesh, P(lane_ax, FLEET_AXIS))           # [B,N]
    lane = NamedSharding(mesh, P(lane_ax))
    repl = NamedSharding(mesh, P())
    return node, lane_node, lane_n, lane, repl


def shard_fleet_arrays(mesh: Mesh, capacity, reserved, usage, job_counts,
                       feasible):
    """Place fleet tensors on the mesh, node axis sharded."""
    node, group_node, repl = _shardings(mesh)
    return (
        _put(capacity, node),
        _put(reserved, node),
        _put(usage, node),
        _put(job_counts, node),
        _put(feasible, group_node),
    )


@partial(jax.jit, static_argnames=("unroll",))
def _place_sharded(capacity, reserved, usage0, job_counts0, feasible, asks,
                   distinct, group_idx, valid, penalty, unroll=1):
    return _place_sequence(capacity, reserved, usage0, job_counts0, feasible,
                           asks, distinct, group_idx, valid, penalty,
                           unroll=unroll)


def place_sequence_sharded(mesh: Mesh, capacity, reserved, usage0,
                           job_counts0, feasible, asks, distinct, group_idx,
                           valid, penalty):
    """Run the placement scan with the node axis sharded over `mesh`.

    Inputs may be host numpy arrays; they are placed with node-axis
    shardings and the jitted scan lets XLA insert the cross-device argmax
    reduction + scatter updates (psum/all-gather over ICI).
    """
    capacity, reserved, usage0, job_counts0, feasible = shard_fleet_arrays(
        mesh, capacity, reserved, usage0, job_counts0, feasible)
    _, _, repl = _shardings(mesh)
    asks = _put(asks, repl)
    distinct = _put(distinct, repl)
    group_idx = _put(group_idx, repl)
    valid = _put(valid, repl)
    # The penalty scalar rides the same replicated placement as the
    # other work descriptors: left as a host scalar it was an IMPLICIT
    # per-dispatch transfer jit performed silently on every sharded
    # single-eval dispatch (devlint sharding-mix; the batch wrappers
    # below always placed it).
    penalty = _put(penalty, repl)
    return _place_sharded(capacity, reserved, usage0, job_counts0, feasible,
                          asks, distinct, group_idx, valid, penalty)


# -- sharded throughput kernels ------------------------------------------
# The single-eval scan above is the latency path; the carriers of bench
# throughput are place_rounds (top-k round placement) and the vmapped
# batch variants (ops/binpack.py).  Their sharded forms keep the SAME
# node-axis sharding: per-shard score math, with the top_k / argmax
# winner selection resolved by XLA-inserted cross-shard collectives.


@partial(jax.jit, static_argnames=("k_cap", "rounds"))
def _place_rounds_sharded_jit(capacity, reserved, usage0, jc0, feasible,
                              asks, distinct, counts, penalty,
                              k_cap: int, rounds: int):
    return _place_rounds(capacity, reserved, usage0, jc0, feasible, asks,
                         distinct, counts, penalty, k_cap=k_cap,
                         rounds=rounds)


def place_rounds_sharded(mesh: Mesh, capacity, reserved, usage0, jc0,
                         feasible, asks, distinct, counts, penalty, *,
                         k_cap: int, rounds: int):
    """place_rounds with the node axis sharded over ``mesh``: each shard
    scores its slice of the fleet; lax.top_k over the sharded axis becomes
    a per-shard top-k + cross-shard merge (XLA GSPMD)."""
    capacity, reserved, usage0, jc0, feasible = shard_fleet_arrays(
        mesh, capacity, reserved, usage0, jc0, feasible)
    _, _, repl = _shardings(mesh)
    asks = _put(asks, repl)
    distinct = _put(distinct, repl)
    counts = _put(counts, repl)
    penalty = _put(penalty, repl)  # see place_sequence_sharded
    return _place_rounds_sharded_jit(capacity, reserved, usage0, jc0,
                                     feasible, asks, distinct, counts,
                                     penalty, k_cap=k_cap, rounds=rounds)


@partial(jax.jit, static_argnames=("k_cap", "rounds"))
def _place_rounds_batch_sharded_jit(capacity, reserved, usage0, jc0,
                                    feasible, asks, distinct, counts,
                                    penalty, k_cap: int, rounds: int):
    fn = jax.vmap(partial(_place_rounds, k_cap=k_cap, rounds=rounds),
                  in_axes=(None, None, None, 0, 0, 0, 0, 0, 0))
    return fn(capacity, reserved, usage0, jc0, feasible, asks, distinct,
              counts, penalty)


def place_rounds_batch_sharded(mesh: Mesh, capacity, reserved, usage0, jc0,
                               feasible, asks, distinct, counts, penalty, *,
                               k_cap: int, rounds: int):
    """Batched (one lane per eval) rounds placement, node axis sharded.

    On a 1-D fleet mesh lanes are replicated work descriptors — every
    device's fleet slice serves every lane.  On a 2-D ``storm_mesh``
    the lane axis also shards, so independent evals run data-parallel
    across mesh rows while each row's fleet slice stays HBM-resident
    (B x G x N feasibility sharded on lanes + N, base usage shared)."""
    node, lane_node, lane_n, lane, repl = _batch_shardings(mesh)
    capacity = _put(capacity, node)
    reserved = _put(reserved, node)
    usage0 = _put(usage0, node)
    jc0 = _put(jc0, lane_n)
    feasible = _put(feasible, lane_node)
    asks = _put(asks, lane)
    distinct = _put(distinct, lane)
    counts = _put(counts, lane)
    penalty = _put(penalty, repl)
    return _place_rounds_batch_sharded_jit(
        capacity, reserved, usage0, jc0, feasible, asks, distinct, counts,
        penalty, k_cap=k_cap, rounds=rounds)


@jax.jit
def _place_sequence_batch_sharded_jit(capacity, reserved, usage0, jc0,
                                      feasible, asks, distinct, group_idx,
                                      valid, penalty):
    fn = jax.vmap(partial(_place_sequence, unroll=1),
                  in_axes=(None, None, None, 0, 0, 0, 0, 0, 0, 0))
    return fn(capacity, reserved, usage0, jc0, feasible, asks, distinct,
              group_idx, valid, penalty)


def place_sequence_batch_sharded(mesh: Mesh, capacity, reserved, usage0,
                                 jc0, feasible, asks, distinct, group_idx,
                                 valid, penalty):
    """Batched placement scan (one lane per eval), node axis sharded;
    lane axis also shards on a 2-D ``storm_mesh`` (see
    place_rounds_batch_sharded)."""
    node, lane_node, lane_n, lane, repl = _batch_shardings(mesh)
    capacity = _put(capacity, node)
    reserved = _put(reserved, node)
    usage0 = _put(usage0, node)
    jc0 = _put(jc0, lane_n)
    feasible = _put(feasible, lane_node)
    asks = _put(asks, lane)
    distinct = _put(distinct, lane)
    group_idx = _put(group_idx, lane)
    valid = _put(valid, lane)
    penalty = _put(penalty, repl)
    return _place_sequence_batch_sharded_jit(
        capacity, reserved, usage0, jc0, feasible, asks, distinct,
        group_idx, valid, penalty)


# -- window-verify kernel --------------------------------------------------
# The group-commit applier's cross-plan base fit (ops/plan_conflict.py
# _evaluate_window_vec), re-expressed against the mesh-resident twins:
# one dispatch per window, fleet tensors never leave the mesh.  Work
# descriptors are tiny (one row per (plan, node) claim / placement /
# fold entry, all padded to ONE shared power-of-two bucket so distinct
# window sizes reuse the trace), so the dispatch cost is flat in fleet
# size — the property bench 5f's fleet-scaling sub-table asserts.


@jax.jit
def _window_verify_jit(capacity, reserved, usage, pair_ni, row_pair,
                       row_vec, seq_ni, seq_vec, seq_order, seq_comp,
                       pair_order, pair_comp, pair_removed):
    """used/caps/fits for every (plan, node) claim of one window.

    capacity/reserved/usage are the [N, D] node-axis-sharded resident
    twins; everything else is a replicated per-window descriptor padded
    to a shared bucket B:

      pair_ni      i32[B]    claimed node index per pair (0-padded)
      row_pair     i32[B]    pair index per placement row (0-padded)
      row_vec      f32[B,4]  placement resource vectors (0-padded)
      seq_ni       i32[B]    fold-entry node index (-1-padded)
      seq_vec      f32[B,4]  fold-entry delta (adds +, removals -)
      seq_order    i32[B]    fold-entry window plan index
      seq_comp     i32[B]    fold-entry claim-graph component (-1-pad)
      pair_order   i32[B]    pair's window plan index
      pair_comp    i32[B]    pair's claim-graph component
      pair_removed f32[B,4]  pair's own removed-row sums (frame rows)

    All resource values are small integers in float32, so every sum
    here is exact and order-independent — the device numbers (and the
    verdicts compared from them) are byte-identical to the host dense
    pass (the same argument _evaluate_window_vec already relies on).
    """
    npair = pair_ni.shape[0]
    # Claim-scatter: each pair's placement rows sum into its delta row.
    delta = jnp.zeros((npair, 4), dtype=jnp.float32)
    delta = delta.at[row_pair].add(row_vec)
    # Claim-sum: gather the sharded twins at the claimed rows (XLA
    # resolves the cross-shard gather with collectives — the work
    # descriptors are replicated, the fleet axis never gathers whole).
    used = usage[pair_ni, :4] + reserved[pair_ni, :4] + delta
    caps = capacity[pair_ni, :4]
    # Window-scoped overlay: the component folds as ONE scatter-add —
    # pair p's overlay is the sum of every fold entry on its node from
    # strictly-earlier window plans of p's OWN component (host walks
    # are component-local, and a removal entry can land on a mirror-row
    # node outside the claim graph, so node equality alone is not
    # enough), under the optimistic all-accepted assumption the host
    # walk validates (plan_conflict._walk_component's ``clean`` guard).
    fold = jnp.where(
        (seq_ni[None, :] == pair_ni[:, None])
        & (seq_order[None, :] < pair_order[:, None])
        & (seq_comp[None, :] == pair_comp[:, None]),
        jnp.float32(1.0), jnp.float32(0.0))
    used_seq = used + fold @ seq_vec - pair_removed
    fits_seq = jnp.all(used_seq <= caps, axis=1)
    return used, caps, fits_seq


def window_verify_sharded(mesh: Mesh, capacity, reserved, usage, pair_ni,
                          row_pair, row_vec, seq_ni, seq_vec, seq_order,
                          seq_comp, pair_order, pair_comp, pair_removed):
    """One window's base fit + optimistic overlay fold, node axis
    sharded over ``mesh``.

    capacity/reserved/usage normally arrive as the already-resident
    ShardedResidency twins (zero transfers — _put skips them); the
    per-window descriptors are placed replicated and counted.  The
    caller fetches the three results through devices.fetch_host — the
    sanctioned d2h seam — so the whole verify dispatch is implicit-
    transfer-free under the hard transfer guard."""
    node, _, repl = _shardings(mesh)
    capacity = _put(capacity, node)
    reserved = _put(reserved, node)
    usage = _put(usage, node)
    pair_ni = _put(pair_ni, repl)
    row_pair = _put(row_pair, repl)
    row_vec = _put(row_vec, repl)
    seq_ni = _put(seq_ni, repl)
    seq_vec = _put(seq_vec, repl)
    seq_order = _put(seq_order, repl)
    seq_comp = _put(seq_comp, repl)
    pair_order = _put(pair_order, repl)
    pair_comp = _put(pair_comp, repl)
    pair_removed = _put(pair_removed, repl)
    return _window_verify_jit(capacity, reserved, usage, pair_ni,
                              row_pair, row_vec, seq_ni, seq_vec,
                              seq_order, seq_comp, pair_order,
                              pair_comp, pair_removed)
