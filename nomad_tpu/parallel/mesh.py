"""Device-mesh scaling for the scheduler: shard the node axis over ICI.

This is the structural cousin of sequence parallelism for a scheduler
workload (SURVEY.md section 5, "Long-context"): the problem dimension that
grows is the fleet (nodes x task groups), so the node axis of every fleet
tensor is sharded across a 1-D ``jax.sharding.Mesh``.  Per-shard work is the
elementwise fit/score math; the argmax winner is reduced across devices by
XLA-inserted collectives riding ICI — no hand-written NCCL/MPI, no host
round-trips (the reference scales this dimension with iterator laziness +
LimitIterator truncation, scheduler/stack.go:106-117; we scale it with
hardware).

Multi-slice/multi-host: the same jit runs under multi-host jax with a mesh
spanning slices; DCN carries only the (tiny) replicated ask/choice tensors,
ICI the sharded fleet math.
"""
from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nomad_tpu.ops.binpack import _place_sequence

FLEET_AXIS = "fleet"


def fleet_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices; axis name 'fleet'."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (FLEET_AXIS,))


def _shardings(mesh: Mesh):
    node = NamedSharding(mesh, P(FLEET_AXIS))          # [N, ...] row-sharded
    group_node = NamedSharding(mesh, P(None, FLEET_AXIS))  # [G, N]
    repl = NamedSharding(mesh, P())
    return node, group_node, repl


def shard_fleet_arrays(mesh: Mesh, capacity, reserved, usage, job_counts,
                       feasible):
    """Place fleet tensors on the mesh, node axis sharded."""
    node, group_node, repl = _shardings(mesh)
    return (
        jax.device_put(capacity, node),
        jax.device_put(reserved, node),
        jax.device_put(usage, node),
        jax.device_put(job_counts, node),
        jax.device_put(feasible, group_node),
    )


@partial(jax.jit, static_argnames=("unroll",))
def _place_sharded(capacity, reserved, usage0, job_counts0, feasible, asks,
                   distinct, group_idx, valid, penalty, unroll=1):
    return _place_sequence(capacity, reserved, usage0, job_counts0, feasible,
                           asks, distinct, group_idx, valid, penalty,
                           unroll=unroll)


def place_sequence_sharded(mesh: Mesh, capacity, reserved, usage0,
                           job_counts0, feasible, asks, distinct, group_idx,
                           valid, penalty):
    """Run the placement scan with the node axis sharded over `mesh`.

    Inputs may be host numpy arrays; they are placed with node-axis
    shardings and the jitted scan lets XLA insert the cross-device argmax
    reduction + scatter updates (psum/all-gather over ICI).
    """
    capacity, reserved, usage0, job_counts0, feasible = shard_fleet_arrays(
        mesh, capacity, reserved, usage0, job_counts0, feasible)
    _, _, repl = _shardings(mesh)
    asks = jax.device_put(asks, repl)
    distinct = jax.device_put(distinct, repl)
    group_idx = jax.device_put(group_idx, repl)
    valid = jax.device_put(valid, repl)
    return _place_sharded(capacity, reserved, usage0, job_counts0, feasible,
                          asks, distinct, group_idx, valid, penalty)
