"""Device-plane resolution: ONE authority for "which devices do we compute on".

The environment may register more than one jax backend (e.g. a remote
TPU plugin AND the host CPU platform); ``jax.devices()`` favors
whichever backend wins registration, which is NOT necessarily the
platform the runtime was pinned to (tests pin
``jax.config.jax_default_device`` to cpu:0 over an 8-virtual-device
host mesh; the driver's multi-chip dry run does the same).  Every
device-plane entry point — mirror uploads, mesh construction, backend
probes — must resolve devices through here so host tensors, meshes and
jitted dispatches all land on ONE platform.  Mixing backends (CPU mesh
kernels + a default-backend mirror upload) is exactly the class of bug
that produced the round-4 multi-chip failure.

Capability parity role: the reference has no analogue — its compute
plane is the Go runtime itself.  This module is the TPU-native seam
between the host data plane and the XLA device plane.
"""
from __future__ import annotations

import threading

from typing import Optional

import jax

# -- transfer accounting ----------------------------------------------------
# Every EXPLICIT host<->device transfer the runtime performs is counted
# here — the data plane's odometer.  The discipline (enforced by
# analysis/devlint.py statically and the transfer-guard sanitizer at
# runtime) is that device dispatches perform NO implicit transfers:
# everything that crosses the PCIe/ICI boundary goes through one of the
# explicit seams below (put_counted / ensure_on_default / mesh._put /
# ShardedResidency / fetch_host), so "how many transfers per eval" is a
# number the bench can record instead of a guess
# (BENCH host_transfers_per_eval).

_TRANSFER_LOCK = threading.Lock()
_TRANSFERS = {"h2d": 0, "d2h": 0, "d2d": 0}


def note_transfer(kind: str, n: int = 1) -> None:
    """Count ``n`` explicit transfers of ``kind`` ("h2d"/"d2h"/"d2d")."""
    with _TRANSFER_LOCK:
        _TRANSFERS[kind] += n


def transfer_counts() -> dict:
    """Snapshot of the process-lifetime explicit-transfer counters."""
    with _TRANSFER_LOCK:
        return dict(_TRANSFERS)


def default_platform() -> Optional[str]:
    """Platform name of the pinned default device, or None when unpinned.

    ``jax.config.jax_default_device`` may hold a Device or a platform
    string (jax accepts both).
    """
    default = jax.config.jax_default_device
    if default is None:
        return None
    return getattr(default, "platform", None) or str(default).split(":")[0]


def default_platform_devices() -> list:
    """Devices of the platform the runtime actually computes on.

    When a default device is pinned, ALL devices of ITS platform (so an
    8-virtual-device CPU pin yields the whole 8-device mesh); otherwise
    whatever ``jax.devices()`` resolves to.
    """
    platform = default_platform()
    if platform is None:
        return jax.devices()
    return jax.devices(platform)


def default_device():
    """The device unsharded host->device uploads must target (or None).

    ``jax.device_put(x)`` with no device argument lands on the *default
    backend's* device 0 and IGNORES the pinned default device; passing
    this explicitly keeps single-buffer mirrors on the same platform as
    the meshes built from :func:`default_platform_devices`.  Returns
    None when nothing is pinned, which ``jax.device_put`` accepts and
    treats as the unpinned default — same behavior, one code path.
    """
    default = jax.config.jax_default_device
    if default is None:
        return None
    if isinstance(default, str):
        return jax.devices(default_platform())[0]
    return default


def current_platform() -> str:
    """Platform the runtime computes on RIGHT NOW: the pinned default
    device's platform, or the default backend's when nothing is pinned
    (what an argument-less ``jax.device_put`` / unjitted dispatch would
    use)."""
    platform = default_platform()
    if platform is None:
        platform = jax.devices()[0].platform
    return platform


def on_default_platform(arr) -> bool:
    """Is this cached device buffer resident on :func:`current_platform`?

    Device-buffer caches (mirror usage, capacity/reserved, feasibility)
    outlive a runtime re-pin of ``jax_default_device`` (e.g. the
    multi-chip dry run pins the mesh platform mid-process, then restores
    the prior pin); serving a stale buffer would recreate the
    mixed-backend dispatch this module exists to prevent, so caches call
    this and re-upload on mismatch.  Platform-level on purpose: a
    same-platform re-pin (cpu:0 -> cpu:3) must NOT invalidate
    bench-scale fleet tensors.
    """
    return next(iter(arr.devices())).platform == current_platform()


def ensure_on_default(cached, host):
    """Device copy of ``host`` on the current platform, reusing
    ``cached`` when it is still resident there.

    The one invalidation policy for every single-buffer device cache:
    callers keep whatever cache structure they need and route
    (cached, host) pairs through here.  Returns ``cached`` itself when
    it is valid, so callers can detect a re-upload by identity.
    """
    if cached is not None and on_default_platform(cached):
        return cached
    note_transfer("h2d")
    return jax.device_put(host, default_device())


def classify_move(src_platform: str, dst_platform: str) -> str:
    """The ONE h2d/d2h/d2d classification rule for an explicit move of
    a jax.Array between platforms (shared by put_counted and
    mesh._put so the odometer cannot drift between seams): a move
    whose source or destination is the cpu backend crosses the host
    boundary — cpu jax buffers live in host memory — and counting it
    d2d would under-report the h2d odometer the bench's
    host_transfers_per_eval is built on."""
    if src_platform == "cpu" and dst_platform != "cpu":
        return "h2d"
    if dst_platform == "cpu" and src_platform != "cpu":
        return "d2h"
    return "d2d"


def put_counted(x, device=None):
    """EXPLICIT placement of one per-dispatch host value onto the
    current platform (counted).  The dispatch seams route every
    per-eval varying argument (usage views, job counts, fused lane
    stacks) through here instead of letting jit commit them implicitly
    — an implicit transfer is invisible to the odometer AND trips the
    transfer-guard sanitizer; an explicit one is accounted.  Arrays
    already resident on the default platform pass through untouched."""
    if isinstance(x, jax.Array):
        if on_default_platform(x):
            return x
        src = next(iter(x.devices())).platform
        note_transfer(classify_move(src, current_platform()))
        return jax.device_put(x, device or default_device())
    note_transfer("h2d")
    return jax.device_put(x, device or default_device())


def fetch_host(x):
    """EXPLICIT device->host fetch (counted): the one sanctioned way a
    device result becomes a numpy array.  ``jax.device_get`` (not
    ``np.asarray``) so the transfer survives a d2h transfer guard; host
    values pass through untouched."""
    if isinstance(x, jax.Array):
        note_transfer("d2h")
        return jax.device_get(x)
    return x
