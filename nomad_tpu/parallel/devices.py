"""Device-plane resolution: ONE authority for "which devices do we compute on".

The environment may register more than one jax backend (e.g. a remote
TPU plugin AND the host CPU platform); ``jax.devices()`` favors
whichever backend wins registration, which is NOT necessarily the
platform the runtime was pinned to (tests pin
``jax.config.jax_default_device`` to cpu:0 over an 8-virtual-device
host mesh; the driver's multi-chip dry run does the same).  Every
device-plane entry point — mirror uploads, mesh construction, backend
probes — must resolve devices through here so host tensors, meshes and
jitted dispatches all land on ONE platform.  Mixing backends (CPU mesh
kernels + a default-backend mirror upload) is exactly the class of bug
that produced the round-4 multi-chip failure.

Capability parity role: the reference has no analogue — its compute
plane is the Go runtime itself.  This module is the TPU-native seam
between the host data plane and the XLA device plane.
"""
from __future__ import annotations

from typing import Optional

import jax


def default_platform() -> Optional[str]:
    """Platform name of the pinned default device, or None when unpinned.

    ``jax.config.jax_default_device`` may hold a Device or a platform
    string (jax accepts both).
    """
    default = jax.config.jax_default_device
    if default is None:
        return None
    return getattr(default, "platform", None) or str(default).split(":")[0]


def default_platform_devices() -> list:
    """Devices of the platform the runtime actually computes on.

    When a default device is pinned, ALL devices of ITS platform (so an
    8-virtual-device CPU pin yields the whole 8-device mesh); otherwise
    whatever ``jax.devices()`` resolves to.
    """
    platform = default_platform()
    if platform is None:
        return jax.devices()
    return jax.devices(platform)


def default_device():
    """The device unsharded host->device uploads must target (or None).

    ``jax.device_put(x)`` with no device argument lands on the *default
    backend's* device 0 and IGNORES the pinned default device; passing
    this explicitly keeps single-buffer mirrors on the same platform as
    the meshes built from :func:`default_platform_devices`.  Returns
    None when nothing is pinned, which ``jax.device_put`` accepts and
    treats as the unpinned default — same behavior, one code path.
    """
    default = jax.config.jax_default_device
    if default is None:
        return None
    if isinstance(default, str):
        return jax.devices(default_platform())[0]
    return default


def current_platform() -> str:
    """Platform the runtime computes on RIGHT NOW: the pinned default
    device's platform, or the default backend's when nothing is pinned
    (what an argument-less ``jax.device_put`` / unjitted dispatch would
    use)."""
    platform = default_platform()
    if platform is None:
        platform = jax.devices()[0].platform
    return platform


def on_default_platform(arr) -> bool:
    """Is this cached device buffer resident on :func:`current_platform`?

    Device-buffer caches (mirror usage, capacity/reserved, feasibility)
    outlive a runtime re-pin of ``jax_default_device`` (e.g. the
    multi-chip dry run pins the mesh platform mid-process, then restores
    the prior pin); serving a stale buffer would recreate the
    mixed-backend dispatch this module exists to prevent, so caches call
    this and re-upload on mismatch.  Platform-level on purpose: a
    same-platform re-pin (cpu:0 -> cpu:3) must NOT invalidate
    bench-scale fleet tensors.
    """
    return next(iter(arr.devices())).platform == current_platform()


def ensure_on_default(cached, host):
    """Device copy of ``host`` on the current platform, reusing
    ``cached`` when it is still resident there.

    The one invalidation policy for every single-buffer device cache:
    callers keep whatever cache structure they need and route
    (cached, host) pairs through here.  Returns ``cached`` itself when
    it is valid, so callers can detect a re-upload by identity.
    """
    if cached is not None and on_default_platform(cached):
        return cached
    return jax.device_put(host, default_device())
