"""Typed HTTP API client.

Capability parity with /root/reference/api/: query/write options, blocking
queries, and wrappers for Jobs/Nodes/Evaluations/Allocations/Agent/Status.
"""
from .client import (  # noqa: F401
    APIClient,
    APIError,
    QueryMeta,
    QueryOptions,
)
