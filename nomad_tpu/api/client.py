"""HTTP API client library.

Capability parity with /root/reference/api/api.go + jobs.go/nodes.go/
evaluations.go/allocations.go/agent.go/status.go: a typed client over the
agent's /v1 REST surface with blocking-query support.  Domain objects are
returned as structs (nomad_tpu.structs) decoded from the wire dicts.
"""
from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Optional

from nomad_tpu.structs import Allocation, Evaluation, Job, Node


class APIError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


@dataclass
class QueryOptions:
    region: str = ""
    allow_stale: bool = False
    wait_index: int = 0
    wait_time: float = 0.0
    pretty: bool = False

    def params(self) -> dict:
        out: dict = {}
        if self.region:
            out["region"] = self.region
        if self.allow_stale:
            out["stale"] = ""
        if self.wait_index:
            out["index"] = str(self.wait_index)
        if self.wait_time:
            out["wait"] = f"{self.wait_time}s"
        return out


@dataclass
class QueryMeta:
    last_index: int = 0


class APIClient:
    def __init__(self, address: str = "http://127.0.0.1:4646") -> None:
        self.address = address.rstrip("/")

    # -- transport ---------------------------------------------------------
    def _url(self, path: str, params: Optional[dict] = None) -> str:
        url = self.address + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        return url

    def raw(self, method: str, path: str,
            params: Optional[dict] = None,
            body: Any = None) -> tuple[Any, QueryMeta]:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self._url(path, params), data=data,
                                     method=method)
        if data is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=330) as resp:
                meta = QueryMeta(
                    last_index=int(resp.headers.get("X-Nomad-Index") or 0))
                return json.loads(resp.read() or b"null"), meta
        except urllib.error.HTTPError as e:
            try:
                message = json.loads(e.read()).get("error", "")
            except Exception:
                message = str(e)
            raise APIError(e.code, message) from e

    def get(self, path: str, options: Optional[QueryOptions] = None):
        return self.raw("GET", path,
                        options.params() if options else None)

    def put(self, path: str, body: Any = None):
        return self.raw("PUT", path, None, body)

    def delete(self, path: str):
        return self.raw("DELETE", path)

    # -- Jobs ---------------------------------------------------------------
    def jobs_list(self, options=None) -> tuple[list, QueryMeta]:
        data, meta = self.get("/v1/jobs", options)
        return [Job.from_dict(j) for j in data or []], meta

    def job_register(self, job: Job) -> dict:
        data, _ = self.put("/v1/jobs", {"job": job.to_dict()})
        return data

    def job_info(self, job_id: str, options=None) -> tuple[Job, QueryMeta]:
        data, meta = self.get(f"/v1/job/{job_id}", options)
        return Job.from_dict(data), meta

    def job_deregister(self, job_id: str) -> dict:
        data, _ = self.delete(f"/v1/job/{job_id}")
        return data

    def job_allocations(self, job_id: str, options=None
                        ) -> tuple[list, QueryMeta]:
        data, meta = self.get(f"/v1/job/{job_id}/allocations", options)
        return [Allocation.from_dict(a) for a in data or []], meta

    def job_evaluations(self, job_id: str, options=None
                        ) -> tuple[list, QueryMeta]:
        data, meta = self.get(f"/v1/job/{job_id}/evaluations", options)
        return [Evaluation.from_dict(e) for e in data or []], meta

    def job_evaluate(self, job_id: str) -> dict:
        data, _ = self.put(f"/v1/job/{job_id}/evaluate")
        return data

    # -- Nodes --------------------------------------------------------------
    def nodes_list(self, options=None) -> tuple[list, QueryMeta]:
        data, meta = self.get("/v1/nodes", options)
        return [Node.from_dict(n) for n in data or []], meta

    def node_info(self, node_id: str, options=None
                  ) -> tuple[Node, QueryMeta]:
        data, meta = self.get(f"/v1/node/{node_id}", options)
        return Node.from_dict(data), meta

    def node_allocations(self, node_id: str, options=None
                         ) -> tuple[list, QueryMeta]:
        data, meta = self.get(f"/v1/node/{node_id}/allocations", options)
        return [Allocation.from_dict(a) for a in data or []], meta

    def node_drain(self, node_id: str, enable: bool) -> dict:
        data, _ = self.raw("PUT", f"/v1/node/{node_id}/drain",
                           {"enable": "true" if enable else "false"})
        return data

    def node_evaluate(self, node_id: str) -> dict:
        data, _ = self.put(f"/v1/node/{node_id}/evaluate")
        return data

    # -- Evaluations ---------------------------------------------------------
    def evaluations_list(self, options=None) -> tuple[list, QueryMeta]:
        data, meta = self.get("/v1/evaluations", options)
        return [Evaluation.from_dict(e) for e in data or []], meta

    def eval_info(self, eval_id: str, options=None
                  ) -> tuple[Evaluation, QueryMeta]:
        data, meta = self.get(f"/v1/evaluation/{eval_id}", options)
        return Evaluation.from_dict(data), meta

    def eval_allocations(self, eval_id: str, options=None
                         ) -> tuple[list, QueryMeta]:
        data, meta = self.get(f"/v1/evaluation/{eval_id}/allocations",
                              options)
        return [Allocation.from_dict(a) for a in data or []], meta

    # -- Allocations ---------------------------------------------------------
    def allocations_list(self, options=None) -> tuple[list, QueryMeta]:
        data, meta = self.get("/v1/allocations", options)
        return [Allocation.from_dict(a) for a in data or []], meta

    def alloc_info(self, alloc_id: str, options=None
                   ) -> tuple[Allocation, QueryMeta]:
        data, meta = self.get(f"/v1/allocation/{alloc_id}", options)
        return Allocation.from_dict(data), meta

    # -- Agent / Status -------------------------------------------------------
    def agent_self(self) -> dict:
        data, _ = self.get("/v1/agent/self")
        return data

    def agent_monitor(self, lines: int = 0) -> list:
        """Recent agent log lines from the in-process ring
        (/v1/agent/monitor; reference command/agent/log_writer.go)."""
        return self.agent_monitor_since(0, lines)[0]

    def agent_monitor_since(self, since: int,
                            lines: int = 0) -> tuple[list, int]:
        """(lines after monotonic offset ``since`` — newest ``lines``
        of them when nonzero — and the next offset): follow-mode
        polling without re-printing on ring wraps."""
        params: dict = {"since": int(since)}
        if lines:
            params["lines"] = int(lines)
        data, _ = self.raw("GET", "/v1/agent/monitor", params)
        return data.get("lines", []), int(data.get("offset", 0))

    def agent_metrics(self, filter: str = "") -> dict:
        """The unified metrics document (/v1/agent/metrics):
        ``providers`` = flattened nomad.* registry gauges, ``inmem`` =
        the in-memory telemetry sink's counters/gauges/samples.
        ``filter`` trims provider keys server-side (substring match) —
        the watch poller's payload diet."""
        params = {"filter": filter} if filter else None
        data, _ = self.raw("GET", "/v1/agent/metrics", params)
        return data

    def agent_members(self) -> list:
        data, _ = self.get("/v1/agent/members")
        return data.get("members", [])

    def agent_join(self, address: str) -> dict:
        data, _ = self.raw("PUT", "/v1/agent/join", {"address": address})
        return data

    def agent_force_leave(self, node: str) -> None:
        self.raw("PUT", "/v1/agent/force-leave", {"node": node})

    def agent_servers(self) -> list:
        data, _ = self.get("/v1/agent/servers")
        return data

    def agent_set_servers(self, servers: list) -> None:
        self.put("/v1/agent/servers", {"servers": list(servers)})

    def status_leader(self) -> str:
        data, _ = self.get("/v1/status/leader")
        return data

    def status_peers(self) -> list:
        data, _ = self.get("/v1/status/peers")
        return data
