"""Command-line interface (python -m nomad_tpu.cli)."""
from .main import main  # noqa: F401
