"""CLI commands.

Capability parity with /root/reference/command/ + commands.go registry:
agent, run, stop, status, node-status, node-drain, eval-monitor,
server-members, server-join, agent-info, validate, init, version.  All
commands talk to the agent's HTTP API (reference: CLI -> api/ -> agent).
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

from nomad_tpu import __version__
from nomad_tpu.api import APIClient, APIError, QueryOptions

DEFAULT_ADDRESS = os.environ.get("NOMAD_ADDR", "http://127.0.0.1:4646")

EXAMPLE_JOB = """\
# There can only be a single job definition per file.
job "example" {
    # Run the job in the global region, which is the default.
    # region = "global"

    # Specify the datacenters within the region this job can run in.
    datacenters = ["dc1"]

    # Service type jobs optimize for long-lived services.  Use "batch" for
    # short-lived tasks, "system" to run on every node.
    # type = "service"

    # Priority controls access to resources and preemption, 1 to 100.
    # priority = 50

    # Restrict the job to linux nodes.
    constraint {
        attribute = "$attr.kernel.name"
        value = "linux"
    }

    # Rolling updates: one task at a time, 10s apart.
    update {
        stagger = "10s"
        max_parallel = 1
    }

    group "cache" {
        # Number of instances of this group.
        count = 1

        task "redis" {
            driver = "exec"

            config {
                command = "/bin/sleep"
                args = "300"
            }

            resources {
                cpu = 500     # MHz
                memory = 256  # MB
                network {
                    mbits = 10
                    dynamic_ports = ["redis"]
                }
            }
        }
    }
}
"""


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="nomad-tpu",
        description="TPU-native cluster scheduler")
    parser.add_argument("-address", default=DEFAULT_ADDRESS,
                        help="agent HTTP address")
    sub = parser.add_subparsers(dest="command")

    p = sub.add_parser("agent", help="run an agent")
    p.add_argument("-dev", action="store_true")
    p.add_argument("-server", action="store_true")
    p.add_argument("-client", action="store_true")
    p.add_argument("-data-dir", default="")
    p.add_argument("-bind", default="127.0.0.1")
    p.add_argument("-http-port", type=int, default=4646)
    p.add_argument("-rpc-port", type=int, default=4647)
    p.add_argument("-serf-port", type=int, default=4648,
                   help="gossip port for server agents (0 = ephemeral)")
    p.add_argument("-servers", default="",
                   help="comma-separated server RPC addrs (client mode)")
    p.add_argument("-executor", default="",
                   help="placement-kernel executor: auto|host|device "
                        "(overrides config; NOMAD_TPU_EXECUTOR env "
                        "overrides both)")
    p.add_argument("-config", action="append", default=[],
                   help="HCL/JSON config file or directory; repeatable, "
                        "merged in order (reloaded on SIGHUP)")

    p = sub.add_parser("init", help="create an example job file")

    p = sub.add_parser("validate", help="validate a job file")
    p.add_argument("file")

    p = sub.add_parser("run", help="submit a job")
    p.add_argument("file")
    p.add_argument("-detach", action="store_true")

    p = sub.add_parser("stop", help="stop a job")
    p.add_argument("job_id")

    p = sub.add_parser("status", help="job status")
    p.add_argument("job_id", nargs="?")

    p = sub.add_parser("node-status", help="node status")
    p.add_argument("node_id", nargs="?")

    p = sub.add_parser("node-drain", help="toggle node drain")
    p.add_argument("node_id")
    p.add_argument("-enable", action="store_true")
    p.add_argument("-disable", action="store_true")

    p = sub.add_parser("eval-monitor", help="monitor an evaluation")
    p.add_argument("eval_id")

    p = sub.add_parser("alloc-status", help="allocation status")
    p.add_argument("alloc_id")

    sub.add_parser("server-members", help="list cluster servers")
    p = sub.add_parser("server-join", help="join a server")
    p.add_argument("join_address")
    p = sub.add_parser("server-force-leave",
                       help="force a gossip member into left state")
    p.add_argument("member_name")
    p = sub.add_parser("client-config",
                       help="view or update the client's server list")
    p.add_argument("-update-servers", dest="update_servers", default="",
                   help="comma-separated host:port list to switch to")
    p = sub.add_parser("monitor", help="stream recent agent log lines")
    p.add_argument("-lines", type=int, default=0,
                   help="newest N lines (0 = full ring)")
    p.add_argument("-follow", action="store_true",
                   help="poll for new lines until interrupted")
    sub.add_parser("agent-info", help="agent diagnostics")
    p = sub.add_parser(
        "metrics", help="dump the agent's unified metrics registry "
                        "(/v1/agent/metrics: every component stats() "
                        "as nomad.* gauges + the in-mem sink)")
    p.add_argument("-json", dest="as_json", action="store_true",
                   help="raw JSON document instead of the flat listing")
    p.add_argument("-filter", default="",
                   help="only keys containing this substring "
                        "(e.g. 'broker', 'applier')")
    p.add_argument("-watch", type=float, default=0.0, metavar="N",
                   help="re-sample every N seconds and render deltas "
                        "(rates for counters) — live view of the "
                        "feedback controller's behavior; Ctrl-C stops")
    p.add_argument("-rounds", type=int, default=0,
                   help="with -watch: stop after this many re-samples "
                        "(0 = until interrupted); scripts and tests "
                        "bound the loop with it")
    sub.add_parser("version", help="print version")

    p = sub.add_parser(
        "lint", help="static analysis: lock discipline + JAX tracer "
                     "safety (the repo's `go vet`/-race analogue)")
    p.add_argument("path", nargs="?", default="",
                   help="package dir to analyze (default: the installed "
                        "nomad_tpu package)")
    p.add_argument("-allowlist", default="",
                   help="allowlist file (default: LINT_ALLOWLIST.txt "
                        "next to the package)")
    p.add_argument("-strict", action="store_true",
                   help="also report advisory findings (bare reads of "
                        "guarded attributes)")
    p.add_argument("-json", dest="as_json", action="store_true",
                   help="machine-readable output (includes call-graph "
                        "self-coverage)")
    p.add_argument("-changed", metavar="REV", default="",
                   help="only report findings in files touched since "
                        "REV (git diff --name-only REV); the stale-"
                        "allowlist gate is skipped in this mode")
    p.add_argument("-sarif", metavar="PATH", default="",
                   help="also write a SARIF 2.1.0 log (findings + "
                        "coverage under run properties) to PATH; "
                        "composes with -changed (the SARIF carries "
                        "the filtered set)")

    args = parser.parse_args(argv)
    if not args.command:
        parser.print_help()
        return 1
    try:
        return COMMANDS[args.command](args)
    except APIError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except (ConnectionError, OSError) as e:
        print(f"Error connecting to {args.address}: {e}", file=sys.stderr)
        return 1


# ---------------------------------------------------------------------------
# commands
# ---------------------------------------------------------------------------

def cmd_agent(args) -> int:
    from nomad_tpu.agent import Agent, AgentConfig
    from nomad_tpu.utils.gated_log import BootLogGate

    # Gate boot logs until the final level/sinks are known (config files
    # parsed, agent constructed) — reference helper/gated-writer +
    # command/agent/log_writer.go.  Buffered lines replay exactly once.
    gate = BootLogGate()

    try:
        if args.dev:
            cfg = AgentConfig.dev()
            cfg.http_port = args.http_port
            cfg.rpc_port = args.rpc_port
        else:
            cfg = AgentConfig(
                server_enabled=args.server,
                client_enabled=args.client,
                data_dir=args.data_dir,
                bind_addr=args.bind,
                http_port=args.http_port,
                rpc_port=args.rpc_port,
                serf_port=args.serf_port,
            )
            if args.servers:
                for part in args.servers.split(","):
                    host, port = part.rsplit(":", 1)
                    cfg.servers.append((host, int(port)))
        if args.config:
            from nomad_tpu.agent.config import (apply_to_agent_config,
                                                load_config_sources)
            apply_to_agent_config(cfg, load_config_sources(args.config))
        if args.executor:
            # Flag beats config files (later source wins, same rule as
            # -config merge order); the env var beats both at dispatch.
            from nomad_tpu.scheduler.executor import validate_executor
            cfg.executor = validate_executor(args.executor, "-executor")

        agent = Agent(cfg)
    except BaseException:
        # A failed boot must still surface its buffered logs — they are
        # exactly what explains the failure.  DEBUG: show everything.
        gate.open("DEBUG")
        raise
    gate.open(cfg.log_level)
    agent.log_writer = gate.log_writer
    agent.on_log_level = gate.set_level
    http_host, http_port = agent.http.address
    print(f"==> nomad-tpu agent started")
    print(f"    HTTP: http://{http_host}:{http_port}")
    if agent.server is not None and agent.server.rpc_address():
        rh, rp = agent.server.rpc_address()
        print(f"    RPC:  {rh}:{rp}")
    if agent.client is not None:
        print(f"    Node: {agent.client.node.id}")
    stop = []

    def _reload(*_sig):
        # SIGHUP: re-read every -config source and apply the reloadable
        # fields (reference command/agent/command.go:418-423,463).
        if not args.config:
            return
        from nomad_tpu.agent.config import (ConfigError,
                                            load_config_sources)
        print("==> caught SIGHUP, reloading configuration...")
        try:
            applied = agent.reload(load_config_sources(args.config))
        except (ValueError, OSError) as e:
            # ConfigError subclasses ValueError; a reload must never be
            # able to take the agent down (reference command.go:463).
            print(f"    failed to reload configs: {e}", file=sys.stderr)
            return
        print(f"    reloaded: {', '.join(applied) if applied else 'nothing'}")

    # leave_on_interrupt / leave_on_terminate: gracefully gossip-leave
    # before shutdown (reference command.go:403-443 graceful leave).
    signal.signal(signal.SIGINT, lambda *_: stop.append(
        "leave" if cfg.leave_on_int else "stop"))
    signal.signal(signal.SIGTERM, lambda *_: stop.append(
        "leave" if cfg.leave_on_term else "stop"))
    if hasattr(signal, "SIGHUP"):
        signal.signal(signal.SIGHUP, _reload)

    def _dump_metrics(*_sig):
        # SIGUSR1: dump the in-memory telemetry sink (reference
        # go-metrics InmemSignal, command.go setupTelemetry).
        from nomad_tpu.utils.metrics import metrics

        print("==> metrics snapshot:")
        print(json.dumps(metrics.inmem.snapshot(), indent=2,
                         default=str))

    if hasattr(signal, "SIGUSR1"):
        signal.signal(signal.SIGUSR1, _dump_metrics)
    while not stop:
        time.sleep(0.2)
    if stop[0] == "leave":
        print("==> caught signal, gracefully leaving cluster")
        agent.leave()
    else:
        print("==> caught signal, shutting down")
    agent.shutdown()
    return 0


def cmd_init(args) -> int:
    if os.path.exists("example.nomad"):
        print("Job 'example.nomad' already exists", file=sys.stderr)
        return 1
    with open("example.nomad", "w") as fh:
        fh.write(EXAMPLE_JOB)
    print("Example job file written to example.nomad")
    return 0


def cmd_validate(args) -> int:
    from nomad_tpu.jobspec import ParseError, parse_file

    try:
        parse_file(args.file)
    except ParseError as e:
        print(f"Job validation failed: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        # Without this, a missing file would fall through to main()'s
        # connection-error handler and report a bogus agent error.
        print(f"Error reading {args.file}: {e}", file=sys.stderr)
        return 1
    print("Job validation successful")
    return 0


def cmd_run(args) -> int:
    from nomad_tpu.jobspec import ParseError, parse_file

    try:
        job = parse_file(args.file)
    except ParseError as e:
        print(f"Error parsing job: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"Error reading {args.file}: {e}", file=sys.stderr)
        return 1
    client = APIClient(args.address)
    resp = client.job_register(job)
    eval_id = resp.get("eval_id", "")
    if args.detach or not eval_id:
        print(f"Job registration successful\nEvaluation ID: {eval_id}")
        return 0
    return _monitor_eval(client, eval_id)


def cmd_stop(args) -> int:
    client = APIClient(args.address)
    resp = client.job_deregister(args.job_id)
    eval_id = resp.get("eval_id", "")
    print(f"Job deregistered\nEvaluation ID: {eval_id}")
    return 0


def cmd_status(args) -> int:
    client = APIClient(args.address)
    if not args.job_id:
        jobs, _ = client.jobs_list()
        if not jobs:
            print("No jobs registered")
            return 0
        print(f"{'ID':<28} {'Type':<8} {'Priority':<8} Status")
        for job in jobs:
            print(f"{job.id:<28} {job.type:<8} {job.priority:<8} "
                  f"{job.status}")
        return 0
    job, _ = client.job_info(args.job_id)
    print(f"ID       = {job.id}")
    print(f"Name     = {job.name}")
    print(f"Type     = {job.type}")
    print(f"Priority = {job.priority}")
    print(f"Status   = {job.status}")
    allocs, _ = client.job_allocations(args.job_id)
    if allocs:
        print("\nAllocations:")
        print(f"{'ID':<38} {'Node':<38} {'Group':<10} "
              f"{'Desired':<8} Client")
        for a in allocs:
            print(f"{a.id:<38} {a.node_id:<38} {a.task_group:<10} "
                  f"{a.desired_status:<8} {a.client_status}")
    return 0


def cmd_node_status(args) -> int:
    client = APIClient(args.address)
    if not args.node_id:
        nodes, _ = client.nodes_list()
        print(f"{'ID':<38} {'DC':<8} {'Name':<16} {'Class':<12} "
              f"{'Drain':<6} Status")
        for n in nodes:
            print(f"{n.id:<38} {n.datacenter:<8} {n.name:<16} "
                  f"{n.node_class:<12} {str(n.drain).lower():<6} "
                  f"{n.status}")
        return 0
    node, _ = client.node_info(args.node_id)
    print(f"ID     = {node.id}")
    print(f"Name   = {node.name}")
    print(f"Class  = {node.node_class}")
    print(f"DC     = {node.datacenter}")
    print(f"Drain  = {str(node.drain).lower()}")
    print(f"Status = {node.status}")
    print(f"Attributes = "
          f"{', '.join(f'{k}={v}' for k, v in sorted(node.attributes.items()))}")
    allocs, _ = client.node_allocations(args.node_id)
    if allocs:
        print("\nAllocations:")
        for a in allocs:
            print(f"{a.id}  job={a.job_id}  {a.desired_status}/"
                  f"{a.client_status}")
    return 0


def cmd_node_drain(args) -> int:
    if args.enable == args.disable:
        print("Either -enable or -disable is required", file=sys.stderr)
        return 1
    client = APIClient(args.address)
    client.node_drain(args.node_id, args.enable)
    print(f"Node {args.node_id} drain = {args.enable}")
    return 0


def cmd_eval_monitor(args) -> int:
    client = APIClient(args.address)
    return _monitor_eval(client, args.eval_id)


def _dump_alloc_status(alloc, indent: str = "    ") -> None:
    """Scheduling explainability for one allocation: filter/exhaustion
    breakdown + scores (reference command/monitor.go dumpAllocStatus).
    Shared by eval-monitor and alloc-status so AllocMetric has exactly
    one renderer."""
    m = alloc.metrics
    if m is None:
        print(f"{indent}Allocation {alloc.id[:8]} status "
              f"{alloc.client_status!r}")
        return
    print(f"{indent}Allocation {alloc.id[:8]} status "
          f"{alloc.client_status!r} "
          f"({m.nodes_filtered}/{m.nodes_evaluated} nodes filtered)")
    sub = indent + "  "
    if m.nodes_evaluated == 0:
        print(f"{sub}* No nodes were eligible for evaluation")
    for cls, num in sorted((m.class_filtered or {}).items()):
        print(f"{sub}* Class {cls!r} filtered {num} nodes")
    for cons, num in sorted((m.constraint_filtered or {}).items()):
        print(f"{sub}* Constraint {cons!r} filtered {num} nodes")
    if m.nodes_exhausted:
        print(f"{sub}* Resources exhausted on {m.nodes_exhausted} nodes")
    for cls, num in sorted((m.class_exhausted or {}).items()):
        print(f"{sub}* Class {cls!r} exhausted on {num} nodes")
    for dim, num in sorted((m.dimension_exhausted or {}).items()):
        print(f"{sub}* Dimension {dim!r} exhausted on {num} nodes")
    if m.coalesced_failures:
        print(f"{sub}* {m.coalesced_failures} additional placements "
              f"failed the same way")
    for name, score in sorted((m.scores or {}).items()):
        print(f"{sub}* Score {name!r} = {score:.3f}")


MONITOR_MAX_CHAIN = 256  # rolling-update evals followed before bailing


def _monitor_eval(client: APIClient, eval_id: str,
                  timeout: float = 60.0) -> int:
    """Poll an eval until terminal, then report its allocations;
    follows rolling-update eval chains, with ``timeout`` bounding each
    eval in the chain (reference command/monitor.go).  Total runtime is
    bounded: stagger sleeps honor the job's full stagger but are capped
    at an absolute 1h per hop, and at most MONITOR_MAX_CHAIN chained
    evals are followed, so a pathological stagger or an endless chain
    can't hang the CLI."""
    followed = 0
    while True:
        print(f"==> Monitoring evaluation \"{eval_id[:8]}\"")
        deadline = time.monotonic() + timeout
        index = 0
        ev = None
        while time.monotonic() < deadline:
            ev, meta = client.eval_info(eval_id, QueryOptions(
                wait_index=index, wait_time=2.0))
            index = meta.last_index
            if ev.status in ("complete", "failed"):
                break
            ev = None
        if ev is None:
            print("    Monitor timed out", file=sys.stderr)
            return 1
        print(f"    Evaluation status: {ev.status} "
              f"{ev.status_description}")
        allocs, _ = client.eval_allocations(eval_id)
        for a in allocs:
            if a.desired_status == "failed":
                # Scheduling failure: the dump carries the header AND
                # the why (reference monitor.go:220-228 +
                # dumpAllocStatus).
                _dump_alloc_status(a)
            else:
                where = f"on node {a.node_id[:8]}" if a.node_id \
                    else "unplaced"
                print(f"    Allocation {a.id[:8]} {where} "
                      f"({a.desired_status})")
        if ev.next_eval:
            # Rolling update: follow the chain like the reference
            # monitor (monitor.go:244-253).  The stagger lives on the
            # NEXT eval (next_rolling_eval sets its ``wait``; the
            # broker holds it that long), so fetch it and sleep that
            # out before the per-eval poll deadline starts.
            followed += 1
            if followed >= MONITOR_MAX_CHAIN:
                print(f"    Followed {followed} chained evaluations; "
                      "giving up (job keeps rolling server-side)",
                      file=sys.stderr)
                return 1
            nxt, _ = client.eval_info(ev.next_eval)
            # Sleep the FULL stagger (capping below it would time the
            # next eval out while the broker still holds it), bounded
            # by an absolute 1h ceiling so a pathological stagger
            # can't hang the CLI forever.
            wait = min(nxt.wait, 3600.0)
            print(f"==> Monitoring next evaluation "
                  f"\"{ev.next_eval[:8]}\" in {wait:.0f}s")
            time.sleep(wait)
            eval_id = ev.next_eval
            continue
        return 0 if ev.status == "complete" else 2


def cmd_alloc_status(args) -> int:
    client = APIClient(args.address)
    alloc, _ = client.alloc_info(args.alloc_id)
    print(f"ID         = {alloc.id}")
    print(f"Eval       = {alloc.eval_id}")
    print(f"Job        = {alloc.job_id}")
    print(f"TaskGroup  = {alloc.task_group}")
    print(f"Node       = {alloc.node_id}")
    print(f"Desired    = {alloc.desired_status}")
    print(f"Client     = {alloc.client_status}")
    if alloc.metrics:
        print(f"\nPlacement metrics:")
        _dump_alloc_status(alloc, indent="  ")
    return 0


def cmd_server_members(args) -> int:
    client = APIClient(args.address)
    for member in client.agent_members():
        print(member)
    return 0


def cmd_server_join(args) -> int:
    client = APIClient(args.address)
    resp = client.agent_join(args.join_address)
    print(f"Joined {resp.get('num_joined', 0)} servers")
    return 0


def cmd_server_force_leave(args) -> int:
    """Force a gossip member into the left state (reference
    command/server_force_leave.go)."""
    client = APIClient(args.address)
    client.agent_force_leave(args.member_name)
    print(f"Forced leave of member {args.member_name!r}")
    return 0


def cmd_client_config(args) -> int:
    """View or update the client's server list (reference
    command/client_config.go)."""
    client = APIClient(args.address)
    if args.update_servers:
        servers = [s.strip() for s in args.update_servers.split(",")
                   if s.strip()]
        client.agent_set_servers(servers)
        print(f"Updated server list ({len(servers)} servers)")
        return 0
    for host, port in client.agent_servers():
        print(f"{host}:{port}")
    return 0


def cmd_agent_info(args) -> int:
    client = APIClient(args.address)
    print(json.dumps(client.agent_self(), indent=2, default=str))
    return 0


def cmd_monitor(args) -> int:
    """Print the agent's recent log ring; with -follow, poll for new
    lines by monotonic offset (the reference's poll-based monitor
    pattern, monitor.go — offsets survive ring wraps, no re-prints)."""
    if args.lines < 0:
        print("monitor: -lines must be >= 0", file=sys.stderr)
        return 1
    client = APIClient(args.address)
    # One request serves both modes: the (server-trimmed) ring snapshot
    # to print and the offset -follow resumes from.
    lines, offset = client.agent_monitor_since(0, args.lines)
    for line in lines:
        print(line)
    if not args.follow:
        return 0
    try:
        while True:
            time.sleep(1.0)
            try:
                lines, offset = client.agent_monitor_since(offset)
            except (OSError, APIError):
                # Transient (agent reload/restart): the monotonic offset
                # lets the stream resume where it left off.
                continue
            for line in lines:
                print(line)
    except KeyboardInterrupt:
        return 0


def cmd_metrics(args) -> int:
    """Dump the unified metrics registry (obs/registry.py) from a live
    agent: flat ``key = value`` lines sorted by key (the key grammar is
    ``nomad.<provider>.<path...>``), or the raw JSON document with
    -json.  The in-mem sink's counters and sample summaries ride along
    under ``counters.*`` / ``samples.*``.

    ``-watch N`` re-samples every N seconds and renders DELTAS: the
    full listing once, then only the keys that changed, each with its
    per-second rate — so counters read as rates and the feedback
    controller's knob movements (``nomad.controller.knobs.*.value``)
    are observable live."""
    client = APIClient(args.address)
    if args.watch and args.watch > 0:
        return _watch_metrics(client, args.watch, args.filter,
                              args.rounds)
    doc = client.agent_metrics()
    if args.as_json:
        print(json.dumps(doc, indent=2, default=str))
        return 0
    flat = _flat_metrics(doc)
    shown = 0
    for key in sorted(flat):
        if args.filter and args.filter not in key:
            continue
        print(f"{key} = {flat[key]}")
        shown += 1
    if args.filter and not shown:
        print(f"no metric keys contain {args.filter!r}", file=sys.stderr)
        return 1
    return 0


def _flat_metrics(doc: dict) -> dict:
    """ONE flattening grammar (obs/registry.flatten) for the inmem doc
    too: counters.<key>, gauges.<key>, samples.<key>.<stat>."""
    from nomad_tpu.obs.registry import flatten

    flat = dict(doc.get("providers") or {})
    flat.update(flatten(doc.get("inmem") or {}))
    return flat


def _watch_metrics(client, interval: float, flt: str,
                   rounds: int) -> int:
    """The -watch loop: first sample prints the (filtered) listing;
    every later round prints only the keys whose value changed, as
    ``key = new (Δdelta, rate/s)`` for numeric keys — a counter's
    line IS its rate.  The substring filter rides to the server
    (?filter=) so a tight watch does not drag the full document over
    the wire every round."""
    prev: "dict | None" = None
    prev_t = 0.0
    done = 0
    try:
        while True:
            doc = client.agent_metrics(filter=flt)
            now = time.monotonic()
            flat = {k: v for k, v in _flat_metrics(doc).items()
                    if not flt or flt in k}
            if prev is None:
                for key in sorted(flat):
                    print(f"{key} = {flat[key]}")
            else:
                dt = max(now - prev_t, 1e-9)
                changed = []
                for key in sorted(flat):
                    old, new = prev.get(key), flat[key]
                    if old == new:
                        continue
                    if isinstance(new, (int, float)) \
                            and not isinstance(new, bool) \
                            and isinstance(old, (int, float)):
                        delta = new - old
                        changed.append(
                            f"{key} = {new} ({delta:+g}, "
                            f"{delta / dt:+.1f}/s)")
                    else:
                        changed.append(f"{key} = {new} (was {old})")
                print(f"--- +{interval:g}s: {len(changed)} of "
                      f"{len(flat)} keys changed")
                for line in changed:
                    print(line)
            prev, prev_t = flat, now
            done += 1
            if rounds and done > rounds:
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        return 0


def cmd_version(args) -> int:
    print(f"nomad-tpu v{__version__}")
    return 0


def cmd_lint(args) -> int:
    """Run the static analyzers; exit 1 on unallowlisted findings.

    This is the CI gate (tests/test_static_analysis.py runs it over the
    package on every tier-1 run) and the local pre-commit loop: a new
    finding is either fixed or earns a justified line in the allowlist.
    """
    from nomad_tpu.analysis import (default_allowlist_path, load_allowlist,
                                    partition_findings, run_lint)

    allowlist_path = args.allowlist or default_allowlist_path()
    try:
        allowlist = load_allowlist(allowlist_path)
    except ValueError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    # Always analyze at full strictness so allowlist staleness is
    # computed against every finding; -strict only controls whether
    # unallowlisted advisory findings are *displayed*.
    coverage: dict = {}
    try:
        findings = run_lint(args.path or None, strict=True,
                            coverage_out=coverage)
    except FileNotFoundError as e:
        print(f"Error: no such package directory: {e}", file=sys.stderr)
        return 1
    gating, allowed, stale = partition_findings(findings, allowlist)
    advisory = [f for f in findings
                if f.severity != "error" and f.key not in allowlist]

    changed_mode = bool(getattr(args, "changed", ""))
    if changed_mode:
        # Findings filtered to files touched since REV (pre-push loop:
        # "what did MY change introduce?").  Staleness needs the full
        # finding set to be meaningful, so it is not enforced here.
        touched = _changed_files(args.changed, args.path or None)
        if touched is None:
            return 1
        gating = [f for f in gating if f.path in touched]
        advisory = [f for f in advisory if f.path in touched]
        stale = []

    sarif_path = getattr(args, "sarif", "")
    if sarif_path:
        try:
            with open(sarif_path, "w") as fh:
                json.dump(_sarif_log(gating, advisory, coverage), fh,
                          indent=2)
        except OSError as e:
            print(f"Error: cannot write SARIF log: {e}",
                  file=sys.stderr)
            return 1

    if args.as_json:
        print(json.dumps({
            # Bumped when the JSON shape changes incompatibly (keys
            # removed/renamed); additive coverage blocks don't bump it.
            # v2 = schema_version + the consensuslint coverage block
            # with the endpoint read-consistency contract table.
            # v3 = the faultlint coverage block: serving-entry deadline
            # closure, the boundary->fault-site coverage table
            # (coverage.faultlint.boundaries, every row covered or
            # waived), and the retry-closure census.
            "schema_version": 3,
            "gating": [f.__dict__ for f in gating],
            "advisory": [f.__dict__ for f in advisory],
            "allowlisted": len(allowed),
            "stale_allowlist": stale,
            "coverage": coverage,
        }, indent=2))
    else:
        for f in gating:
            print(f.render())
        if args.strict:
            for f in advisory:
                print(f"{f.render()}  [advisory]")
        for key in stale:
            print(f"stale allowlist entry (no matching finding): {key}",
                  file=sys.stderr)
        print(f"{len(gating)} finding(s), {len(allowed)} allowlisted, "
              f"{len(stale)} stale allowlist entr(ies); call-graph "
              f"coverage {coverage.get('resolved_fraction', 0):.0%} "
              f"({coverage.get('dynamic', 0)} dynamic call sites "
              "skipped)")
    return 1 if gating or stale else 0


def _sarif_log(gating, advisory, coverage: dict) -> dict:
    """SARIF 2.1.0 log for the lint run: one run, one result per
    finding (gating = error, advisory = note), the rule inventory in
    the tool driver, and the full coverage block — including
    faultlint's boundary->fault-site table — under run properties so
    SARIF consumers see the proof surface, not just the findings."""
    rules: dict = {}
    results = []
    for f, level in [(f, "error") for f in gating] + \
                    [(f, "note") for f in advisory]:
        rules.setdefault(f.rule, {
            "id": f.rule,
            "defaultConfiguration": {"level": level},
        })
        results.append({
            "ruleId": f.rule,
            "level": level,
            "message": {"text": f"{f.where}: {f.message}"},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path.replace(os.sep,
                                                               "/")},
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
        })
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "nomad-tpu-lint",
                "version": __version__,
                "informationUri":
                    "https://github.com/kardianos/nomad",
                "rules": sorted(rules.values(),
                                key=lambda r: r["id"]),
            }},
            "results": results,
            "properties": {"coverage": coverage},
        }],
    }


def _changed_files(rev: str, package_path) -> "set | None":
    """Repo-relative paths touched since ``rev`` (committed AND working
    tree), resolved against the repo holding the analyzed package."""
    import subprocess

    from nomad_tpu.analysis import default_package_root

    root = os.path.dirname(os.path.abspath(
        package_path or default_package_root()))
    # --relative keys the diff paths to ``root`` (the package parent),
    # matching the analyzer's finding paths even when the package lives
    # below the git toplevel; untracked files are merged in via
    # ls-files — a brand-new module's findings must not be filtered to
    # a false clean.
    try:
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", "--relative",
             rev],
            capture_output=True, text=True, check=True, timeout=30)
        # faultlint-ok(uninjectable-io): dev-tooling git probe inside
        # the lint CLI itself — never on a serving path.
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, check=True, timeout=30)
    except FileNotFoundError:
        print("Error: -changed requires git on PATH", file=sys.stderr)
        return None
    except subprocess.CalledProcessError as e:
        print(f"Error: git diff/ls-files against {rev} failed: "
              f"{e.stderr.strip()}", file=sys.stderr)
        return None
    except subprocess.TimeoutExpired:
        print("Error: git diff timed out", file=sys.stderr)
        return None
    return {line.strip()
            for out in (diff.stdout, untracked.stdout)
            for line in out.splitlines() if line.strip()}


COMMANDS = {
    "agent": cmd_agent,
    "init": cmd_init,
    "validate": cmd_validate,
    "run": cmd_run,
    "stop": cmd_stop,
    "status": cmd_status,
    "node-status": cmd_node_status,
    "node-drain": cmd_node_drain,
    "eval-monitor": cmd_eval_monitor,
    "alloc-status": cmd_alloc_status,
    "server-members": cmd_server_members,
    "server-join": cmd_server_join,
    "server-force-leave": cmd_server_force_leave,
    "client-config": cmd_client_config,
    "monitor": cmd_monitor,
    "agent-info": cmd_agent_info,
    "metrics": cmd_metrics,
    "version": cmd_version,
    "lint": cmd_lint,
}
