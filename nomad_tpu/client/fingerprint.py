"""Node fingerprinting: detect attributes, resources and links.

Capability parity with /root/reference/client/fingerprint/: an ordered
chain of detectors filling Node.attributes / resources / links — arch, cpu
(cores x MHz), host (kernel/os/hostname), memory, storage, network (iface +
speed), env_aws/env_gce (cloud metadata), consul link.  Cloud detectors are
gated on reachability with short timeouts and default off in tests
(options: "fingerprint.denylist").

TPU-native addition: an accelerator fingerprint exposing jax-visible
devices as ``accel.*`` attributes so jobs can constrain on them.
"""
from __future__ import annotations

import logging
import os
import platform
import shutil
import socket
from typing import Callable

from nomad_tpu.structs import NetworkResource, Node, Resources

logger = logging.getLogger("nomad_tpu.client.fingerprint")


def arch_fingerprint(cfg, node: Node) -> bool:
    node.attributes["arch"] = platform.machine() or "unknown"
    return True


def host_fingerprint(cfg, node: Node) -> bool:
    node.attributes["kernel.name"] = platform.system().lower()
    node.attributes["kernel.version"] = platform.release()
    node.attributes["os.name"] = platform.system().lower()
    node.attributes["os.version"] = platform.version()
    node.attributes["unique.hostname"] = socket.gethostname()
    if not node.name:
        node.name = socket.gethostname()
    return True


def cpu_fingerprint(cfg, node: Node) -> bool:
    cores = os.cpu_count() or 1
    node.attributes["cpu.numcores"] = str(cores)
    mhz = 1000.0
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.lower().startswith("cpu mhz"):
                    mhz = float(line.split(":")[1])
                    break
    except (OSError, ValueError):
        pass
    node.attributes["cpu.frequency"] = f"{mhz:.0f}"
    total = int(cores * mhz)
    node.attributes["cpu.totalcompute"] = str(total)
    if node.resources.cpu == 0:
        node.resources.cpu = total
    return True


def memory_fingerprint(cfg, node: Node) -> bool:
    total_mb = 0
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemTotal"):
                    total_mb = int(line.split()[1]) // 1024
                    break
    except (OSError, ValueError):
        pass
    if total_mb:
        node.attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
        if node.resources.memory_mb == 0:
            node.resources.memory_mb = total_mb
    return True


def storage_fingerprint(cfg, node: Node) -> bool:
    path = cfg.alloc_dir or "/"
    try:
        usage = shutil.disk_usage(path)
    except OSError:
        return False
    node.attributes["storage.volume"] = path
    node.attributes["storage.bytestotal"] = str(usage.total)
    node.attributes["storage.bytesfree"] = str(usage.free)
    if node.resources.disk_mb == 0:
        node.resources.disk_mb = usage.free // (1024 * 1024)
    return True


def network_fingerprint(cfg, node: Node) -> bool:
    """Default-route interface + IP; speed from options or 100 Mbit
    heuristic (reference network_unix.go)."""
    ip = cfg.read("network.ip") or _default_ip()
    if not ip:
        return False
    node.attributes["unique.network.ip-address"] = ip
    speed = int(cfg.read("network.speed", "0") or 0)
    if speed == 0:
        speed = 1000 if ip != "127.0.0.1" else 100
    if not node.resources.networks:
        node.resources.networks.append(NetworkResource(
            device="eth0", cidr=f"{ip}/32", ip=ip, mbits=speed))
    return True


def _default_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # No packets are sent; picks the default-route source address.
            # faultlint-ok(uninjectable-io): routing-table lookup, no
            # traffic; OSError already falls back to loopback below.
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def accel_fingerprint(cfg, node: Node) -> bool:
    """TPU/accelerator detection via jax (framework-native extension)."""
    if cfg.read_bool("fingerprint.skip_accel"):
        return False
    try:
        import jax

        devices = jax.devices()
    except Exception:
        return False
    if not devices:
        return False
    kinds: dict = {}
    for d in devices:
        kinds[d.device_kind] = kinds.get(d.device_kind, 0) + 1
    node.attributes["accel.count"] = str(len(devices))
    node.attributes["accel.platform"] = devices[0].platform
    for kind, count in kinds.items():
        key = kind.lower().replace(" ", "-")
        node.attributes[f"accel.kind.{key}"] = str(count)
    return True


def consul_fingerprint(cfg, node: Node) -> bool:
    addr = cfg.read("consul.address")
    if not addr:
        return False
    node.links["consul"] = f"{node.name}.{node.datacenter}"
    return True


def env_aws_fingerprint(cfg, node: Node) -> bool:
    """AWS metadata service probe; off unless explicitly enabled (zero
    egress in tests; reference env_aws.go probes 169.254.169.254).
    The endpoint is overridable for tests, the same trick the
    reference's env_aws_test.go plays with a local httptest server."""
    if not cfg.read_bool("fingerprint.env_aws"):
        return False
    url = cfg.read("fingerprint.env_aws.url") or \
        "http://169.254.169.254"
    return _probe_metadata(cfg, node, url, "platform.aws")


def env_gce_fingerprint(cfg, node: Node) -> bool:
    if not cfg.read_bool("fingerprint.env_gce"):
        return False
    url = cfg.read("fingerprint.env_gce.url") or \
        "http://metadata.google.internal"
    return _probe_metadata(cfg, node, url, "platform.gce")


def _probe_metadata(cfg, node: Node, url: str, prefix: str) -> bool:
    import urllib.request

    try:
        req = urllib.request.Request(url, headers={
            "Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=0.5):
            pass
    except Exception:
        return False
    node.attributes[f"{prefix}.detected"] = "true"
    return True


# Ordered chain (reference fingerprint.go:13-35 BuiltinFingerprints).
BUILTIN_FINGERPRINTS: list[tuple[str, Callable]] = [
    ("arch", arch_fingerprint),
    ("cpu", cpu_fingerprint),
    ("host", host_fingerprint),
    ("memory", memory_fingerprint),
    ("storage", storage_fingerprint),
    ("network", network_fingerprint),
    ("accel", accel_fingerprint),
    ("env_aws", env_aws_fingerprint),
    ("env_gce", env_gce_fingerprint),
    ("consul", consul_fingerprint),
]


def fingerprint_node(cfg, node: Node) -> list:
    """Run the chain; returns the names that applied."""
    denylist = set((cfg.read("fingerprint.denylist") or "").split(","))
    applied = []
    for name, fn in BUILTIN_FINGERPRINTS:
        if name in denylist:
            continue
        try:
            if fn(cfg, node):
                applied.append(name)
        except Exception:
            logger.exception("fingerprint %s failed", name)
    return applied
