"""Client agent: fingerprinting, task execution, alloc lifecycle.

Capability parity with /root/reference/client/: the node-side daemon that
registers with servers, heartbeats, long-polls its allocations, and runs
them through pluggable task drivers with filesystem + resource isolation.
"""
from .client import Client  # noqa: F401
from .config import ClientConfig  # noqa: F401
